"""Informer — a list-watch cache with event handlers.

The reference never touches the apiserver directly for reads:
controller-runtime's manager gives it a cache fed by list+watch informers,
and reconciles are *triggered* by watch deltas filtered through predicates
(upgrade_requestor.go:115-159 registers exactly such handlers). This is
that layer over ``Client.watch``:

* one ``Informer`` maintains a local store for one kind, seeded by a list
  and kept current by a watch resumed from the list's revision — the
  journal-backed resumption means no event is lost between the two;
* a watch window that ENDS (server-side bound) re-watches from the last
  delivered or bookmarked revision; a watch whose CONNECTION dies does
  the same (up to ``max_resume_attempts`` — the journal replays what the
  dead stream swallowed, see docs/wire-path.md); only a watch that
  EXPIRES (``WatchExpiredError``, the 410 Gone analog — the revision
  fell out of the journal) or keeps failing re-lists, diffing the
  relisted state against the store so handlers see synthetic
  ADDED/MODIFIED/DELETED for anything missed;
* handlers run on the informer thread with ``(event_type, obj, old)`` —
  pair them with the requestor's plain-function predicates;
* reads (``get``/``list``) serve from the local store: cheap, point-in-time
  consistent, and exactly as stale as a controller-runtime cached client.

Deltas are **rv-ordered and resync-aware**: the informer remembers the
resourceVersion each key last *dispatched* to handlers, and a resync
sweep (:meth:`resync_once`) coalesces replays whose stored rv handlers
have already seen — a resync tick over a settled store delivers ZERO
events, instead of client-go's replay-everything storm. A resync still
re-delivers any object whose store entry got ahead of dispatch (e.g. a
``record_write`` store repair whose watch echo never arrived), which is
the self-heal a resync exists for. Delta consumers building incremental
state (``upgrade/snapshot.py:IncrementalSnapshotSource``) rely on
exactly this contract.
"""

from __future__ import annotations

import threading
from typing import Callable, Mapping, Optional

from .client import Client, WatchExpiredError
from .objects import KubeObject, deep_copy_json, wrap
from .selectors import parse_selector
from ..utils import tracing
from ..utils.faultpoints import chaos_hold
from ..utils.log import get_logger
from ..utils.lifecycle import lifecycle_resource

log = get_logger("kube.informer")

#: handler signature: (event_type, object, old_object_or_None)
EventHandler = Callable[[str, KubeObject, Optional[KubeObject]], None]


@lifecycle_resource(acquire="start", release="stop")
class Informer:
    def __init__(
        self,
        client: Client,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
        watch_timeout_seconds: int = 300,
        resync_period_s: float = 0.0,
        stream_source=None,
    ) -> None:
        self._client = client
        #: Where the WATCH stream comes from: any object with the
        #: ``Client.watch`` signature. None = the client itself; a
        #: :class:`~.watchhub.WatchHub` here multiplexes this informer
        #: onto the hub's shared upstream stream (N co-hosted informers
        #: of one scope ⇒ 1 upstream watch). Lists (seed + relist) stay
        #: on the client — the hub only owns watches.
        self._stream_source = stream_source
        self.kind = kind
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector
        #: Bounded watch windows so a dead-silent stream cannot park the
        #: informer forever; each window resumes from the last revision.
        self.watch_timeout_seconds = watch_timeout_seconds
        #: How many consecutive watch-stream failures resume from the
        #: last delivered/bookmarked revision before degrading to a full
        #: re-list (a killed connection costs a re-watch, not an O(pool)
        #: LIST; see docs/wire-path.md). Reset by any delivered event or
        #: cleanly ended window.
        self.max_resume_attempts = 3
        #: client-go's resync: every period, every cached object is
        #: re-delivered to handlers as MODIFIED with old == new (the
        #: SharedInformer UpdateFunc(obj, obj) shape) — the self-heal
        #: tick controllers lean on to requeue work lost to a handler
        #: bug. 0 (the default) disables it, like controller-runtime
        #: builders that pass no resync.
        self.resync_period_s = resync_period_s
        self._store: dict[tuple[str, str], dict] = {}
        #: client-go cache.Indexer: name -> fn(KubeObject) -> [values];
        #: indices are maintained incrementally on every store mutation
        #: and rebuilt on relist, so by_index reads are O(bucket).
        self._indexers: dict[str, Callable[[KubeObject], list[str]]] = {}
        self._indices: dict[str, dict[str, set[tuple[str, str]]]] = {}
        # Reentrant: index functions run under this lock (they must see
        # a consistent store) and may legitimately read back through
        # get()/list()/by_index() on the same thread — a plain Lock
        # would self-deadlock the watch thread on the first event.
        self._lock = threading.RLock()
        # Handler deliveries are SERIALIZED across the watch and resync
        # threads (client-go's sharedProcessor delivers through one
        # queue; handlers are never invoked concurrently). Reentrant so
        # the resync loop can hold it across its store re-check.
        self._dispatch_lock = threading.RLock()
        self._handlers: list[EventHandler] = []
        #: resourceVersion last DELIVERED per key: recorded once every
        #: registered handler returned without raising (trivially so with
        #: zero handlers), left behind on a handler failure so the next
        #: resync sweep re-delivers that revision. Guarded by the
        #: dispatch lock; resync_once compares it against the store to
        #: coalesce replays handlers have already seen.
        self._dispatched_rv: dict[tuple[str, str], str] = {}
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resource_version: Optional[str] = None
        #: Last revision this informer is CURRENT through — survives the
        #: resume bookkeeping resets so a degraded re-list can ask the
        #: server for deltas-since-rv (``Client.list_delta``) instead of
        #: a full O(collection) snapshot. Cleared only on 410 (the
        #: revision fell out of the server journal, so the delta ask
        #: would fail the same way).
        self._delta_base_rv: Optional[str] = None
        #: Relist accounting: (full relists, delta relists) — the bench/
        #: test hook proving the delta path carried a repair.
        self.full_relists = 0
        self.delta_relists = 0
        #: Chaos identity (docs/chaos-harness.md): the schedule driver
        #: tags each worker's informers so a ``watch.deliver`` fault can
        #: lag ONE consumer's stream while its peers stay current — the
        #: watch-behind-the-ledger scenario. "" = untargetable.
        self.chaos_tag = ""
        self._watch_handle = None

    # -- lifecycle ---------------------------------------------------------
    def add_event_handler(self, handler: EventHandler) -> None:
        """Register a handler; called as (event_type, obj, old). Watch
        deliveries run on the informer thread, resyncs on the resync
        timer thread — but deliveries are serialized, a handler is never
        invoked concurrently. A handler registered after objects are
        cached is caught up client-go-style: the current store is
        replayed to it (and only it) as synthetic ADDEDs, so late
        registrants see every existing object — deliberately NOT gated
        on the synced flag, which a watch expiry clears while the store
        still holds the last-known objects (a re-list only dispatches
        diffs, so skipping the replay there would lose the unchanged
        ones). Deliveries are at-least-once — an event racing the replay
        can arrive again after it; handlers must be level-driven, as
        controller handlers are."""
        with self._dispatch_lock:
            with self._lock:
                snapshot = list(self._store.values())
            if snapshot:
                for raw in snapshot:
                    obj = wrap(raw)
                    try:
                        handler("ADDED", obj, None)
                    except Exception:  # noqa: BLE001 - handlers own errors
                        log.exception(
                            "informer handler failed during replay for %s",
                            obj.name,
                        )
            self._handlers.append(handler)

    @property
    def started(self) -> bool:
        """True while the informer is RUNNING — the public signal for
        wrappers like ``Controller`` deciding whose lifecycle this is.
        A stopped informer reads False and may be start()ed again."""
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Informer":
        """Start (or restart after stop()). Restart takes fresh control
        state — a previous run that failed to join within stop()'s
        timeout keeps its own stop event and cannot be resurrected —
        and forces a re-list, which repairs the kept store with
        synthetic diff events."""
        if self.started:
            raise RuntimeError(f"informer for {self.kind} already started")
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._resource_version = None
        self._watch_handle = None
        # The run loops capture THIS event as a local: a wedged previous
        # thread (one that outlived stop()'s join timeout) still polls
        # its own event and can never be re-armed by the fresh one.
        stop = self._stop
        self._thread = threading.Thread(
            target=self._run, args=(stop,),
            name=f"informer-{self.kind}", daemon=True,
        )
        self._thread.start()
        if self.resync_period_s > 0:
            self._resync_thread = threading.Thread(
                target=self._resync_loop, args=(stop,),
                name=f"informer-{self.kind}-resync",
                daemon=True,
            )
            self._resync_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        handle = self._watch_handle
        if handle is not None:
            handle.cancel()  # unblock the parked socket read promptly
        if self._thread is not None:
            self._thread.join(timeout=10)
        resync_thread = getattr(self, "_resync_thread", None)
        if resync_thread is not None:
            resync_thread.join(timeout=10)

    def _resync_loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.resync_period_s):
            if not self._synced.is_set():
                continue  # nothing meaningful to re-deliver mid-relist
            self.resync_once(stop)

    def resync_once(self, stop: Optional[threading.Event] = None) -> int:
        """One coalescing resync sweep; returns how many objects were
        re-delivered. Unlike client-go's replay-everything resync, a key
        whose stored resourceVersion handlers were already offered is
        SKIPPED — a sweep over a settled store delivers zero events — and
        only entries the store holds *ahead of dispatch* (a
        ``record_write`` store repair whose watch echo never arrived, or
        an event delivery that died mid-flight) are re-delivered in the
        client-go resync shape, ``UpdateFunc(obj, obj)``. This is the
        self-heal a resync exists for, minus the O(store) replay storm
        on every tick."""
        delivered = 0
        with self._lock:
            keys = list(self._store)
        for key in keys:
            if stop is not None and stop.is_set():
                return delivered
            # Under the dispatch lock, re-check the object is still
            # cached: the watch thread removes from the store BEFORE
            # dispatching DELETED, so a gone object is skipped here
            # and a resync MODIFIED can never follow its DELETED.
            with self._dispatch_lock:
                with self._lock:
                    raw = self._store.get(key)
                if raw is None:
                    continue
                rv = str(
                    (raw.get("metadata") or {}).get("resourceVersion", "")
                )
                if self._dispatched_rv.get(key) == rv:
                    continue  # handlers already saw this exact revision
                # client-go resync shape: UpdateFunc(obj, obj).
                self._dispatch("MODIFIED", raw, raw)
                delivered += 1
        return delivered

    def _in_flight(self) -> tuple[dict, list[dict], int]:
        """One consistent view under the dispatch lock (re-entrant for
        callers already inside it): the store snapshot, the entries
        whose revision has not yet been offered to handlers (the watch
        thread writes the store BEFORE dispatching, so a reader can
        observe the new object while its handlers are still pending),
        and the count of dispatched keys whose store entry is already
        gone (a DELETED mid-flight — its raw is no longer available).
        THE settledness scan: ``pending_dispatch`` and
        ``with_settled_store`` must agree on what "in flight" means, so
        they both read it here."""
        with self._dispatch_lock:
            with self._lock:
                store = dict(self._store)
            pending = []
            for key, raw in store.items():
                rv = str(
                    (raw.get("metadata") or {}).get("resourceVersion", "")
                )
                if self._dispatched_rv.get(key) != rv:
                    pending.append(raw)
            gone = sum(1 for k in self._dispatched_rv if k not in store)
            return store, pending, gone

    def pending_dispatch(self) -> tuple[list[dict], int]:
        """In-flight deliveries: (pending raws, gone-key count).
        ``resync_once`` eventually re-delivers the former; the
        incremental audit path uses this to keep event races out of the
        divergence count."""
        _, pending, gone = self._in_flight()
        return pending, gone

    def with_settled_store(self, fn) -> bool:
        """Run ``fn(raws)`` over the store contents under the dispatch
        lock, but ONLY when no delivery is in flight — returns False
        without calling ``fn`` otherwise. Holding the dispatch lock
        across ``fn`` means no handler can run concurrently, so a
        consumer maintaining an event-derived book (per-DS pod counts,
        say) can rebase it on the store without losing an increment to
        a racing handler: any event arriving after ``fn`` ran is NOT in
        the raws it saw and will be applied by its handler on top of
        the rebased book. ``fn`` sees the SAME snapshot the settledness
        scan checked — a store write landing between the two would
        otherwise hand ``fn`` a pod whose pending dispatch later
        double-counts. ``fn`` must be quick and must not touch this
        informer — it runs inside the dispatch critical section, where
        a re-entrant informer call deadlocks."""
        with self._dispatch_lock:
            store, pending, gone = self._in_flight()
            if pending or gone:
                return False
            fn(list(store.values()))
            return True

    def wait_for_sync(self, timeout: Optional[float] = None) -> bool:
        """Block until the initial list has populated the store."""
        return self._synced.wait(timeout)

    def __enter__(self) -> "Informer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- indexers (client-go cache.Indexer) --------------------------------
    def add_indexer(
        self, name: str, fn: Callable[[KubeObject], list[str]]
    ) -> None:
        """Register a named index: ``fn(obj) -> [values]`` (client-go's
        IndexFunc; multiple values per object are allowed, e.g. one
        bucket per ready condition AND per node). Safe to add after
        start — the index is built from the current store. ``fn`` runs
        under the store lock: keep it fast, and read back through this
        informer only (same-thread reads are safe; blocking on OTHER
        locks from inside an index fn invites deadlock)."""
        with self._lock:
            self._indexers[name] = fn
            self._indices[name] = self._build_index(fn, self._store)

    def _build_index(
        self, fn, store: dict
    ) -> dict[str, set[tuple[str, str]]]:
        """Full build from a store snapshot; caller holds the lock."""
        index: dict[str, set[tuple[str, str]]] = {}
        for key, raw in store.items():
            for value in self._index_values(fn, raw):
                index.setdefault(value, set()).add(key)
        return index

    def by_index(self, name: str, value: str) -> list[KubeObject]:
        """Objects whose index function yielded ``value`` — the
        controller-runtime ``client.MatchingFields`` read path (e.g.
        pods by spec.nodeName) at O(bucket) instead of a store scan."""
        with self._lock:
            if name not in self._indexers:
                raise KeyError(f"no indexer named {name!r}")
            keys = self._indices.get(name, {}).get(value, set())
            out = [
                wrap(deep_copy_json(self._store[k]))
                for k in keys
                if k in self._store
            ]
        return sorted(out, key=lambda o: (o.namespace, o.name))

    @staticmethod
    def _index_values(fn, raw: dict) -> list[str]:
        try:
            return [v for v in fn(wrap(raw)) if v is not None]
        except Exception:  # noqa: BLE001 - index fns own their errors
            log.exception("indexer function failed for %s", raw)
            return []

    def _index_remove(self, key: tuple[str, str], raw: dict) -> None:
        for name, fn in self._indexers.items():
            index = self._indices[name]
            for value in self._index_values(fn, raw):
                bucket = index.get(value)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        index.pop(value, None)

    def _index_add(self, key: tuple[str, str], raw: dict) -> None:
        for name, fn in self._indexers.items():
            index = self._indices[name]
            for value in self._index_values(fn, raw):
                index.setdefault(value, set()).add(key)

    def _store_set(self, key: tuple[str, str], raw: dict) -> None:
        """Store write + incremental index maintenance; caller holds
        the lock."""
        old = self._store.get(key)
        if old is not None:
            self._index_remove(key, old)
        self._store[key] = raw
        self._index_add(key, raw)

    def _store_pop(self, key: tuple[str, str]) -> None:
        old = self._store.pop(key, None)
        if old is not None:
            self._index_remove(key, old)

    # -- write-through -----------------------------------------------------
    def record_write(self, obj: "KubeObject | dict") -> None:
        """Write-through from the object's writer: store the write result
        NOW so the writer's next cached read reflects its own write
        (read-your-writes), instead of waiting for the watch to deliver
        it. The watch event still arrives later; deliveries being
        at-least-once, handlers are level-driven and tolerate the replay.

        Ignored when the store already holds a strictly newer
        resourceVersion (the watch outran the caller), and never
        dispatched to handlers — this is a store repair, not an event.
        """
        raw = obj.raw if isinstance(obj, KubeObject) else obj
        key = self._key(raw)
        new_rv = str((raw.get("metadata") or {}).get("resourceVersion", ""))
        with self._lock:
            old = self._store.get(key)
            if old is not None:
                old_rv = str(
                    (old.get("metadata") or {}).get("resourceVersion", "")
                )
                if (
                    old_rv.isdigit()
                    and new_rv.isdigit()
                    and int(new_rv) <= int(old_rv)
                ):
                    return  # store is already as new as (or newer than) us
            self._store_set(key, deep_copy_json(raw))

    # -- cached reads ------------------------------------------------------
    # get/list/by_index return DEEP COPIES, like a controller-runtime
    # cached client: callers mutate their results (the state provider
    # updates node labels in place; cordon flips unschedulable), and an
    # aliased store object would let those mutations corrupt the shared
    # cache from outside its lock.
    def get(self, name: str, namespace: str = "") -> Optional[KubeObject]:
        with self._lock:
            raw = self._store.get((namespace, name))
            return wrap(deep_copy_json(raw)) if raw is not None else None

    def list(
        self,
        label_selector: Optional[str | Mapping[str, str]] = None,
        copy: bool = True,
    ) -> list[KubeObject]:
        """``copy=False`` skips the defensive copy and wraps the store's
        own dicts — STRICTLY read-only (``FakeCluster.list_peek``'s
        contract): store entries are never edited in place (watch/
        record_write/relist all swap whole dicts), so the refs form a
        consistent snapshot, but a caller mutation would corrupt the
        cache. Reserved for consumers that provably never mutate — the
        snapshot source's Pod/DaemonSet/ControllerRevision reads."""
        if isinstance(label_selector, Mapping):
            label_selector = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        selector = parse_selector(label_selector)
        with self._lock:
            out = []
            for raw in self._store.values():
                labels = (raw.get("metadata") or {}).get("labels") or {}
                if selector.matches(labels):
                    out.append(wrap(raw if not copy else deep_copy_json(raw)))
            return sorted(out, key=lambda o: (o.namespace, o.name))

    # -- internals ---------------------------------------------------------
    @staticmethod
    def _key(raw: dict) -> tuple[str, str]:
        meta = raw.get("metadata") or {}
        return (meta.get("namespace", ""), meta.get("name", ""))

    def _dispatch(self, event: str, raw: dict, old: Optional[dict]) -> None:
        tracer = tracing.tracer()
        if tracer is None:
            # THE hot path: one global read, nothing else.
            self._dispatch_inner(event, raw, old)
            return
        # Delivery attribution (docs/tracing.md): the span JOINS the
        # originating write's trace (write-origin book, keyed by rv —
        # which survives watch windows, killed connections, and hub
        # journal replays) and STARTS at the write's wall time, so its
        # duration IS the write→dispatched delivery lag. Handlers run
        # inside it: a dirty-mark made by a snapshot-source handler
        # records this trace as a wake of the next reconcile pass.
        meta = raw.get("metadata") or {}
        rv = str(meta.get("resourceVersion", ""))
        origin = tracer.write_origin(rv)
        attrs = {
            "kind": self.kind, "name": meta.get("name", ""),
            "event": event, "rv": rv,
        }
        if self.chaos_tag:
            # Consumer identity: two co-hosted workers deliver the SAME
            # origin-less rv as otherwise byte-identical root spans —
            # the tag keeps the deterministic export's content ordering
            # stable (docs/tracing.md, determinism under chaos).
            attrs["consumer"] = self.chaos_tag
        deliver_span = tracer.start_span(
            "informer.deliver", category="wire",
            trace_id=origin[0] if origin else None,
            parent_id=origin[1] if origin else "",
            start=origin[2] if origin else None,
            attrs=attrs,
        )
        try:
            with tracing.use_span(deliver_span):
                self._dispatch_inner(event, raw, old)
        finally:
            tracer.end_span(deliver_span)

    def _dispatch_inner(
        self, event: str, raw: dict, old: Optional[dict]
    ) -> None:
        obj = wrap(raw)
        old_obj = wrap(old) if old is not None else None
        with self._dispatch_lock:
            key = self._key(raw)
            delivered = True
            for handler in self._handlers:
                try:
                    handler(event, obj, old_obj)
                except Exception:  # noqa: BLE001 - handlers own their errors
                    delivered = False
                    log.exception(
                        "informer handler failed for %s %s", event, obj.name
                    )
            # Record the rv only after every handler returned: a raising
            # handler leaves the key behind dispatch, so the next resync
            # sweep re-delivers this revision (the "delivery died
            # mid-flight" self-heal resync_once promises). A DELETED is
            # un-healable either way — the store entry is already gone —
            # so its book entry is dropped regardless.
            if event == "DELETED":
                self._dispatched_rv.pop(key, None)
            elif delivered:
                self._dispatched_rv[key] = str(
                    (raw.get("metadata") or {}).get("resourceVersion", "")
                )

    def _try_delta_relist(self, stop) -> bool:
        """Repair the store from a deltas-since-rv LIST when the client
        and server support it (``list_delta`` + the journal window):
        O(what changed) instead of O(collection), which is what keeps a
        degraded re-list (``max_resume_attempts`` exhausted) from
        costing O(fleet) at fan-out. Returns False to fall back to the
        full snapshot path — outside the window, unsupported, first
        seed, or any error."""
        since = self._delta_base_rv
        lister = getattr(self._client, "list_delta", None)
        with self._lock:
            have_store = bool(self._store)
        if lister is None or not since or not have_store:
            return False
        try:
            delta = lister(
                self.kind,
                since,
                namespace=self.namespace,
                label_selector=self.label_selector,
                field_selector=self.field_selector,
            )
        except Exception:  # noqa: BLE001 - delta is an optimization
            log.debug("delta list failed for %s; full re-list", self.kind,
                      exc_info=True)
            return False
        if delta is None:
            return False  # outside the journal window: full snapshot
        if stop.is_set():
            return True  # superseded: discard; _run exits on stop
        rvs = [int(since)] if since.isdigit() else []
        if str(delta.revision or "").isdigit():
            rvs.append(int(delta.revision))
        changed: list[tuple[tuple[str, str], dict, Optional[dict]]] = []
        dropped: list[tuple[tuple[str, str], dict]] = []
        deleted_keys = list(delta.deleted)
        if delta.full:
            # The server answered a FULL list (it predates delta
            # lists): items is the whole collection, already in hand —
            # diff it against the store instead of refetching the same
            # bytes through the plain-list path. Anything we hold that
            # the list lacks is gone.
            fresh_keys = {self._key(obj.raw) for obj in delta.items}
            with self._lock:
                deleted_keys.extend(
                    key for key in self._store if key not in fresh_keys
                )
        with self._lock:
            for obj in delta.items:
                raw = obj.raw
                key = self._key(raw)
                old = self._store.get(key)
                old_rv = str(
                    ((old or {}).get("metadata") or {}).get(
                        "resourceVersion", ""
                    )
                )
                new_rv = str(
                    (raw.get("metadata") or {}).get("resourceVersion", "")
                )
                if new_rv.isdigit():
                    rvs.append(int(new_rv))
                if (
                    old is not None
                    and old_rv.isdigit()
                    and new_rv.isdigit()
                    and int(new_rv) <= int(old_rv)
                ):
                    continue  # record_write already holds something newer
                self._store_set(key, raw)
                changed.append((key, raw, old))
            for namespace, name in deleted_keys:
                key = (namespace, name)
                old = self._store.get(key)
                if old is not None:
                    self._store_pop(key)
                    dropped.append((key, old))
        for _key, raw, old in changed:
            self._dispatch("MODIFIED" if old is not None else "ADDED",
                           raw, old)
        for _key, old in dropped:
            self._dispatch("DELETED", old, old)
        self._resource_version = str(max(rvs)) if rvs else None
        self._delta_base_rv = self._resource_version
        if delta.full:
            self.full_relists += 1  # a whole collection crossed the wire
        else:
            self.delta_relists += 1
        self._synced.set()
        return True

    def _relist(self, stop) -> None:
        """Seed/repair the store from a fresh list, emitting synthetic
        events for every difference a lapsed watch may have missed.
        ``stop`` is THIS run's stop event: a run superseded while blocked
        in the list call (stop() gave up joining, start() launched a new
        run) must discard its result instead of clobbering the new run's
        store/synced/resume state."""
        if self._try_delta_relist(stop):
            return
        list_kwargs = dict(
            namespace=self.namespace,
            label_selector=self.label_selector,
            field_selector=self.field_selector,
        )
        collection_rv = ""
        lister = getattr(self._client, "list_with_revision", None)
        if lister is not None:
            items, collection_rv = lister(self.kind, **list_kwargs)
        else:
            items = self._client.list(self.kind, **list_kwargs)
        fresh = {self._key(o.raw): o.raw for o in items}
        rvs = [
            int(o.resource_version)
            for o in items
            if str(o.resource_version or "").isdigit()
        ]
        if collection_rv.isdigit():
            rvs.append(int(collection_rv))
        if stop.is_set():
            return  # superseded (or stopping): discard the stale list
        with self._lock:
            previous = self._store
            self._store = fresh
            # Rebuild every index from the fresh snapshot.
            for name, fn in self._indexers.items():
                self._indices[name] = self._build_index(fn, fresh)
        for key, raw in fresh.items():
            old = previous.get(key)
            if old is None:
                self._dispatch("ADDED", raw, None)
            elif old.get("metadata", {}).get("resourceVersion") != raw.get(
                "metadata", {}
            ).get("resourceVersion"):
                self._dispatch("MODIFIED", raw, old)
        for key, old in previous.items():
            if key not in fresh:
                self._dispatch("DELETED", old, old)
        # Resume from the newest revision the list showed; watching from
        # an older one would replay events already reflected in the store.
        self._resource_version = str(max(rvs)) if rvs else None
        self._delta_base_rv = self._resource_version
        self.full_relists += 1
        self._synced.set()

    def _run(self, stop: threading.Event) -> None:
        consecutive_failures = 0
        while not stop.is_set():
            try:
                if not self._synced.is_set() or self._resource_version is None:
                    self._relist(stop)
                    if stop.is_set():
                        return
                watch_kwargs = dict(
                    namespace=self.namespace,
                    label_selector=self.label_selector,
                    field_selector=self.field_selector,
                    timeout_seconds=self.watch_timeout_seconds,
                    resource_version=self._resource_version,
                    # Reflector shape: request bookmarks so a quiet
                    # scoped watch keeps a fresh resume point while the
                    # journal advances under it (no 410 + relist decay).
                    allow_bookmarks=True,
                )
                from .rest import WatchHandle

                if stop.is_set():
                    # A superseded run must not clobber the live run's
                    # handle with a stale one.
                    return
                self._watch_handle = WatchHandle()
                # stop() may have run between the check above and the
                # assignment, when there was no handle to cancel —
                # re-check after publishing the handle so that window
                # cannot park us in a full watch timeout.
                if stop.is_set():
                    return
                watch_source = self._stream_source or self._client
                watch_iter = watch_source.watch(
                    self.kind, handle=self._watch_handle, **watch_kwargs
                )
                for event_type, obj in watch_iter:
                    if stop.is_set():
                        return
                    # Chaos fault point: while a schedule holds this
                    # informer's delivery, events queue UPSTREAM (the
                    # watch generator is not pulled) and the store goes
                    # stale — the lagging-stream scenario. Heal releases
                    # them here in arrival order. No plan = no-op.
                    chaos_hold(
                        "watch.deliver", stop.is_set,
                        kind=self.kind, tag=self.chaos_tag,
                    )
                    if stop.is_set():
                        return
                    consecutive_failures = 0  # the stream delivered
                    raw = obj.raw
                    if event_type == "BOOKMARK":
                        # Resume-point refresh only: no object payload,
                        # nothing to store or dispatch.
                        rv = str(
                            (raw.get("metadata") or {}).get(
                                "resourceVersion", ""
                            )
                        )
                        if rv.isdigit():
                            self._resource_version = rv
                            self._delta_base_rv = rv
                        continue
                    key = self._key(raw)
                    rv = str(
                        (raw.get("metadata") or {}).get("resourceVersion", "")
                    )
                    with self._lock:
                        old = self._store.get(key)
                        if event_type == "DELETED":
                            self._store_pop(key)
                        else:
                            # record_write (provider write-through) may
                            # have landed a NEWER object than this
                            # delivery — applying a lagging event would
                            # regress the store below the caller's own
                            # write, exactly the staleness write-through
                            # exists to remove. Same forward-only rule
                            # record_write itself follows. The event is
                            # still DISPATCHED below: record_write never
                            # dispatches, so dropping the watch echo too
                            # would deliver the write zero times and
                            # starve trigger handlers of their wake-up
                            # (handlers are level-driven; a stale payload
                            # is at-least-once noise, a missing one is a
                            # lost event).
                            old_rv = str(
                                ((old or {}).get("metadata") or {}).get(
                                    "resourceVersion", ""
                                )
                            )
                            if not (
                                old is not None
                                and old_rv.isdigit()
                                and rv.isdigit()
                                and int(rv) <= int(old_rv)
                            ):
                                self._store_set(key, raw)
                    if rv.isdigit():
                        self._resource_version = rv
                        self._delta_base_rv = rv
                    self._dispatch(event_type, raw, old)
                # Watch window ended (server timeout): resume from the
                # last seen revision on the next loop iteration.
                consecutive_failures = 0
            except WatchExpiredError:
                log.info(
                    "%s watch expired at rv=%s; re-listing",
                    self.kind, self._resource_version,
                )
                self._resource_version = None
                if self._stream_source is None:
                    # The revision fell out of the SERVER journal; a
                    # delta LIST from it would be outside the window
                    # too — take the full snapshot directly, not after
                    # a failed ask.
                    self._delta_base_rv = None
                # With a hub stream source the 410 usually means only
                # the HUB's replay window lapsed (slow subscriber); the
                # server-side journal is typically deeper, so KEEP the
                # base rv and let the delta LIST repair in O(changed) —
                # if the server journal lapsed too, list_delta answers
                # None (its own 410) and the full path runs.
                self._synced.clear()
            except NotImplementedError:
                # A client with no watch path must fail fast, not be
                # silently degraded into a re-list hot loop.
                raise
            except Exception as e:  # noqa: BLE001 - stream died; back off
                if stop.is_set():
                    return
                consecutive_failures += 1
                if (
                    self._resource_version is not None
                    and consecutive_failures <= self.max_resume_attempts
                ):
                    # A dead CONNECTION is not a lost CACHE: the store is
                    # still valid through the last delivered revision
                    # (bookmarks keep it fresh on quiet watches), so
                    # resume the watch from there — the journal replays
                    # whatever the dead stream swallowed. Re-listing here
                    # would put an O(pool) LIST on every network blip;
                    # only a 410 (revision fell out of the journal) or
                    # repeated resume failures earn that.
                    log.warning(
                        "%s watch died (%s); resuming from rv=%s "
                        "(attempt %d/%d)",
                        self.kind, e, self._resource_version,
                        consecutive_failures, self.max_resume_attempts,
                    )
                    stop.wait(min(0.2 * consecutive_failures, 1.0))
                    continue
                log.warning("%s watch failed (%s); re-listing", self.kind, e)
                self._resource_version = None
                self._synced.clear()
                stop.wait(1.0)
