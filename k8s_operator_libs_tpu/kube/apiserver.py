"""LocalApiServer — the in-memory apiserver served over real HTTP.

The reference's test strategy is "the cluster is real, the cluster is local":
envtest boots a genuine kube-apiserver + etcd with no nodes
(reference: pkg/upgrade/upgrade_suit_test.go:87-93, Makefile:76-78). This is
the equivalent harness: ``FakeCluster`` (finalizers, optimistic concurrency,
merge-patch, CRD establishment) exposed with Kubernetes REST conventions so
``RestClient`` — and any kubeconfig-speaking tool — exercises the genuine
wire path: URLs, verbs, selectors as query params, Status errors, the
eviction subresource, and bearer-token auth.

Also a deployment artifact, not only a fixture: ``python -m
k8s_operator_libs_tpu.kube.apiserver --port 8001`` serves a scratch cluster
for demos of the apply-crds CLI and the upgrade controller.
"""

from __future__ import annotations

import json
import re
import ssl
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from .client import ApiError, BadRequestError
from .fake import FakeCluster
from .objects import wrap
from .resources import ResourceInfo, resource_for_plural
from .table import accepts_table, render_table

_PATH_RE = re.compile(
    r"^/(?:api|apis)(?:/(?P<group>[^/]+(?:\.[^/]+)*))?/(?P<version>v[^/]+)"
    r"(?:/namespaces/(?P<namespace>[^/]+))?"
    r"/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<subresource>status|eviction))?$"
)

#: GET /api/v1 or /apis/<group>/<version> with no resource segment =
#: API discovery (the endpoint crdutil's wait-for-established polls).
#: Core discovery lives ONLY at /api/<version>; /apis/<version> with no
#: group is a 404 on a real apiserver, so it must be one here too.
_DISCOVERY_RE = re.compile(
    r"^(?:/api/(?P<core_version>v[^/]+)"
    r"|/apis/(?P<group>[^/]+)/(?P<version>v[^/]+))$"
)


def _status_body(code: int, reason: str, message: str) -> dict[str, Any]:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "code": code,
        "reason": reason,
        "message": message,
    }


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "LocalApiServer"

    # -- helpers -----------------------------------------------------------
    def _send_json(self, code: int, body: dict[str, Any]) -> None:
        payload = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _send_error(self, e: ApiError) -> None:
        self._send_json(e.status, _status_body(e.status, e.reason, e.message))

    def _read_body(self) -> dict[str, Any]:
        if not self._body:
            return {}
        return json.loads(self._body)

    def _authorized(self) -> bool:
        token = self.server.token
        if not token:
            return True
        return self.headers.get("Authorization") == f"Bearer {token}"

    def _route(self):
        parsed = urllib.parse.urlparse(self.path)
        m = _PATH_RE.match(parsed.path)
        if m is None:
            return None
        group = m.group("group") or ""
        # /api/v1 has no group segment; the regex puts "v1" in version there.
        try:
            info = resource_for_plural(group, m.group("plural"))
        except KeyError:
            return None
        version = m.group("version")
        requested_gv = f"{group}/{version}" if group else version
        if info.api_version != requested_gv:
            # The URL names a version the registry doesn't serve this
            # resource at. A real apiserver routes per served
            # group/version — accept only if discovery says a stored
            # CRD serves the plural at that version; otherwise 404.
            try:
                served = self.server.cluster.discover(group, version)
            except ApiError:
                return None
            if not any(
                r.get("name") == m.group("plural") for r in served
            ):
                return None
            # Downstream (list apiVersion, printer columns) must speak
            # the REQUESTED version, not the registry's default.
            info = ResourceInfo(
                info.kind, requested_gv, info.plural, info.namespaced
            )
        query = dict(urllib.parse.parse_qsl(parsed.query))
        return (
            info,
            m.group("namespace") or "",
            m.group("name") or "",
            m.group("subresource") or "",
            query,
        )

    def _handle(self, verb: str) -> None:
        # Drain the body FIRST, fresh for every request: the handler
        # instance is reused across keep-alive requests, and replying with
        # unread body bytes on the socket corrupts the next request.
        length = int(self.headers.get("Content-Length") or 0)
        self._body = self.rfile.read(length) if length else b""
        if not self._authorized():
            self._send_json(
                401, _status_body(401, "Unauthorized", "invalid bearer token")
            )
            return
        if verb == "GET":
            parsed = urllib.parse.urlparse(self.path)
            discovery = _DISCOVERY_RE.match(parsed.path)
            if discovery is not None:
                core = discovery.group("core_version")
                self._do_discovery(
                    "" if core else discovery.group("group"),
                    core or discovery.group("version"),
                )
                return
        route = self._route()
        if route is None:
            self._send_json(
                404, _status_body(404, "NotFound", f"no route for {self.path}")
            )
            return
        info, namespace, name, subresource, query = route
        cluster = self.server.cluster
        try:
            getattr(self, f"_do_{verb.lower()}")(
                cluster, info, namespace, name, subresource, query
            )
        except ApiError as e:
            self._send_error(e)
        except Exception as e:  # noqa: BLE001 - surfaced as 500 Status
            self._send_json(500, _status_body(500, "InternalError", str(e)))

    def _do_discovery(self, group: str, version: str) -> None:
        """Serve the APIResourceList discovery document (what the real
        apiserver returns for /apis/<group>/<version>); 404 while the
        group/version is not yet servable — the Established-but-
        undiscoverable window crdutil polls through."""
        try:
            resources = self.server.cluster.discover(group, version)
        except ApiError as e:
            self._send_error(e)
            return
        gv = f"{group}/{version}" if group else version
        self._send_json(
            200,
            {
                "kind": "APIResourceList",
                "apiVersion": "v1",
                "groupVersion": gv,
                "resources": resources,
            },
        )

    # -- verbs -------------------------------------------------------------
    def _do_get(self, cluster, info, namespace, name, subresource, query):
        if not name and query.get("watch") in ("true", "1"):
            self._do_watch(cluster, info, namespace, query)
            return
        as_table = accepts_table(self.headers.get("Accept", ""))
        if name:
            obj = cluster.get(info.kind, name, namespace)
            if as_table:
                self._send_json(200, self._table(cluster, info, [obj.raw],
                                                 query))
                return
            self._send_json(200, obj.raw)
            return
        try:
            limit = int(query.get("limit", "0") or "0")
        except ValueError:
            raise BadRequestError(f"invalid limit {query.get('limit')!r}")
        items, revision, next_continue, remaining = cluster.list_page(
            info.kind,
            namespace=namespace,
            label_selector=query.get("labelSelector") or None,
            field_selector=query.get("fieldSelector") or None,
            limit=limit,
            continue_token=query.get("continue", ""),
        )
        # Collection revision: what a watch resumes from even when the
        # list is empty (no items to take a revision from). On chunked
        # lists it is the first page's snapshot revision, continue and
        # remainingItemCount follow the real server's listMeta.
        metadata: dict = {"resourceVersion": revision}
        if next_continue:
            metadata["continue"] = next_continue
        if remaining is not None:
            metadata["remainingItemCount"] = remaining
        if as_table:
            self._send_json(200, self._table(
                cluster, info, [o.raw for o in items], query,
                list_metadata=metadata,
            ))
            return
        self._send_json(
            200,
            {
                "apiVersion": info.api_version,
                "kind": f"{info.kind}List",
                "metadata": metadata,
                "items": [o.raw for o in items],
            },
        )

    @staticmethod
    def _table(cluster, info, raws, query, list_metadata=None):
        include_object = query.get("includeObject", "") or "Metadata"
        if include_object not in ("Metadata", "Object", "None"):
            raise BadRequestError(
                f"invalid includeObject value {include_object!r}"
            )
        return render_table(
            raws,
            crd_columns=cluster.printer_columns(
                info.kind, info.api_version
            ),
            include_object=include_object,
            list_metadata=list_metadata,
        )

    @staticmethod
    def _bookmark_object(info, resource_version: str) -> dict:
        """The real server's bookmark payload: an object of the watched
        kind carrying ONLY metadata.resourceVersion."""
        return {
            "kind": info.kind,
            "apiVersion": info.api_version,
            "metadata": {"resourceVersion": resource_version},
        }

    def _do_watch(self, cluster, info, namespace, query):
        """``?watch=true``: stream newline-delimited watch events.

        Kubernetes watch semantics in the shape the library consumes:

        * ``resourceVersion=N`` resumes from the event journal — the
          list-then-watch pattern with no lost-event window (events since
          the listed revision replay first; an expired revision returns
          410 Gone and the client must re-list);
        * without ``resourceVersion``, events after establishment stream;
        * scope transitions follow the real apiserver: an object whose
          update makes it START matching the selector arrives as ADDED,
          one that STOPS matching arrives as DELETED;
        * a consumer too slow to drain its event queue loses the watch
          (stream closed) rather than silently losing events;
        * ``timeoutSeconds`` bounds the stream server-side;
        * ``allowWatchBookmarks=true`` opts into periodic BOOKMARK events
          carrying only the current collection resourceVersion, so a
          quiet (e.g. selector-scoped) watch keeps a fresh resume point
          and resumption does not decay into 410 + full re-list.

        Events are ``{"type": ADDED|MODIFIED|DELETED, "object": {...}}``
        JSON lines; the stream is EOF-delimited (``Connection: close``).
        """
        import queue
        import time

        from .fake import classify_watch_event
        from .selectors import parse_field_selector, parse_selector

        selector = parse_selector(query.get("labelSelector") or None)
        fields = parse_field_selector(query.get("fieldSelector") or None)
        timeout_s = (
            float(query["timeoutSeconds"])
            if query.get("timeoutSeconds")
            else None
        )
        kind = info.kind
        events: queue.Queue = queue.Queue(maxsize=1024)
        overflowed = threading.Event()

        def scoped_event(event_type: str, data: dict, old):
            return classify_watch_event(event_type, data, old, selector, fields)

        def on_event(event_type: str, data: dict, old) -> None:
            # Cheap static filters only; scope classification happens on
            # the handler thread.
            if data.get("kind") != kind:
                return
            meta = data.get("metadata") or {}
            if namespace and meta.get("namespace", "") != namespace:
                return
            try:
                events.put_nowait((event_type, data, old))
            except queue.Full:
                overflowed.set()  # close the watch; the client re-lists

        try:
            replay = cluster.subscribe_since(
                on_event, query.get("resourceVersion")
            )
        except ApiError as e:
            self._send_error(e)
            return
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            # EOF-delimited stream: the client reads lines until close.
            self.send_header("Connection", "close")
            self.end_headers()
            for event_type, data, old in replay:
                if data.get("kind") != kind:
                    continue
                meta = data.get("metadata") or {}
                if namespace and meta.get("namespace", "") != namespace:
                    continue
                mapped = scoped_event(event_type, data, old)
                if mapped is None:
                    continue
                if not self._write_event(mapped, data):
                    return
            deadline = (
                time.monotonic() + timeout_s if timeout_s is not None else None
            )
            bookmarks = query.get("allowWatchBookmarks") in ("true", "1")
            interval = self.server.bookmark_interval_s
            next_bookmark = time.monotonic() + interval
            while not overflowed.is_set():
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    poll = min(0.2, remaining)
                else:
                    poll = 0.2
                if bookmarks:
                    poll = min(poll, max(0.01, next_bookmark - time.monotonic()))
                try:
                    event_type, data, old = events.get(timeout=poll)
                except queue.Empty:
                    # Bookmark only from a DRAINED queue — "every event up
                    # to this rv has been delivered". rv read before the
                    # emptiness re-check: the cluster's _emit bumps rv and
                    # notifies watchers under one lock hold, so an rv
                    # observed here implies its event is already enqueued.
                    if bookmarks and time.monotonic() >= next_bookmark:
                        rv = cluster.current_resource_version()
                        if events.empty():
                            next_bookmark = time.monotonic() + interval
                            if not self._write_event(
                                "BOOKMARK",
                                self._bookmark_object(info, rv),
                            ):
                                break
                    continue
                mapped = scoped_event(event_type, data, old)
                if mapped is None:
                    continue
                if not self._write_event(mapped, data):
                    break
        finally:
            cluster.unsubscribe(on_event)
            self.close_connection = True

    def _write_event(self, event_type: str, data: dict) -> bool:
        line = json.dumps({"type": event_type, "object": data}) + "\n"
        try:
            self.wfile.write(line.encode())
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError):
            return False

    @staticmethod
    def _dry_run(query) -> bool:
        value = query.get("dryRun", "")
        if value and value != "All":
            # Real-apiserver validation: All is the only accepted value.
            raise BadRequestError(f"invalid dryRun value {value!r}")
        return bool(value)

    def _do_post(self, cluster, info, namespace, name, subresource, query):
        body = self._read_body()
        if subresource == "eviction":
            # dryRun travels either as a query param or inside the
            # Eviction body's deleteOptions (kubectl sends the latter).
            opts = (body or {}).get("deleteOptions") or {}
            body_dry = opts.get("dryRun") or []
            if body_dry and body_dry != ["All"]:
                raise BadRequestError(f"invalid dryRun value {body_dry!r}")
            cluster.evict(
                name, namespace,
                dry_run=self._dry_run(query) or bool(body_dry),
            )
            self._send_json(200, _ok_status())
            return
        meta = body.setdefault("metadata", {})
        if info.namespaced and not meta.get("namespace"):
            meta["namespace"] = namespace
        created = cluster.create(
            wrap(body),
            field_manager=query.get("fieldManager", ""),
            dry_run=self._dry_run(query),
        )
        self._send_json(201, created.raw)

    def _do_put(self, cluster, info, namespace, name, subresource, query):
        obj = wrap(self._read_body())
        manager = query.get("fieldManager", "")
        dry = self._dry_run(query)
        if subresource == "status":
            updated = cluster.update_status(
                obj, field_manager=manager, dry_run=dry
            )
        else:
            updated = cluster.update(obj, field_manager=manager, dry_run=dry)
        self._send_json(200, updated.raw)

    def _do_patch(self, cluster, info, namespace, name, subresource, query):
        content_type = self.headers.get("Content-Type", "")
        if "apply-patch" in content_type:
            # Server-side apply: the body is the applied config itself.
            if subresource:
                raise BadRequestError(
                    "server-side apply to subresources is not supported "
                    "(PARITY: apply targets the main resource only)"
                )
            body = self._read_body()
            meta = body.setdefault("metadata", {})
            if meta.get("name") and meta["name"] != name:
                # Real-apiserver rule: the body may not address a
                # different object than the URL.
                raise BadRequestError(
                    f"metadata.name {meta['name']!r} does not match the "
                    f"request path name {name!r}"
                )
            meta["name"] = name
            if info.namespaced:
                if meta.get("namespace") and meta["namespace"] != namespace:
                    raise BadRequestError(
                        f"metadata.namespace {meta['namespace']!r} does not "
                        f"match the request path namespace {namespace!r}"
                    )
                meta["namespace"] = namespace
            created = (
                cluster.get_or_none(info.kind, name, namespace) is None
            )
            applied = cluster.apply(
                body,
                field_manager=query.get("fieldManager", ""),
                force=query.get("force") == "true",
                dry_run=self._dry_run(query),
            )
            self._send_json(201 if created else 200, applied.raw)
            return
        if "strategic-merge-patch" in content_type:
            patch_type = "strategic"
        elif "json-patch" in content_type:
            patch_type = "json"
        else:
            patch_type = "merge"
        patched = cluster.patch(
            info.kind,
            name,
            namespace,
            patch=self._read_body(),
            patch_type=patch_type,
            field_manager=query.get("fieldManager", ""),
            dry_run=self._dry_run(query),
        )
        self._send_json(200, patched.raw)

    def _do_delete(self, cluster, info, namespace, name, subresource, query):
        if not name:
            # DELETE on the collection: client-go's deleteCollection.
            # Mirror of the fake's guard (ADVICE.md): a real apiserver
            # does not serve deletecollection on the all-namespaces path
            # of a namespaced resource — refuse before the cluster call
            # so registered custom kinds get the same protection over
            # the wire as typed kinds get in-process.
            if info.namespaced and not namespace:
                raise BadRequestError(
                    f"deleteCollection on namespaced kind {info.kind} "
                    "requires a namespace (all-namespaces "
                    "deletecollection is not served by a real apiserver)"
                )
            deleted = cluster.delete_collection(
                info.kind,
                namespace,
                label_selector=query.get("labelSelector") or None,
                field_selector=query.get("fieldSelector") or None,
                propagation_policy=query.get("propagationPolicy") or None,
                dry_run=self._dry_run(query),
            )
            self._send_json(200, {
                "apiVersion": info.api_version,
                "kind": f"{info.kind}List",
                "items": [o.raw for o in deleted],
            })
            return
        preconditions = (self._read_body() or {}).get("preconditions") or {}
        cluster.delete(
            info.kind,
            name,
            namespace,
            dry_run=self._dry_run(query),
            propagation_policy=query.get("propagationPolicy") or None,
            precondition_uid=preconditions.get("uid"),
            precondition_resource_version=preconditions.get(
                "resourceVersion"
            ),
        )
        self._send_json(200, _ok_status())

    def do_GET(self):  # noqa: N802 - http.server API
        self._handle("GET")

    def do_POST(self):  # noqa: N802
        self._handle("POST")

    def do_PUT(self):  # noqa: N802
        self._handle("PUT")

    def do_PATCH(self):  # noqa: N802
        self._handle("PATCH")

    def do_DELETE(self):  # noqa: N802
        self._handle("DELETE")

    def log_message(self, fmt, *args):  # noqa: D102 - silence default logging
        pass


def _ok_status() -> dict[str, Any]:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Success",
        "code": 200,
    }


class LocalApiServer(ThreadingHTTPServer):
    """Serve a FakeCluster on 127.0.0.1; use as a context manager in tests."""

    daemon_threads = True

    def __init__(
        self,
        cluster: Optional[FakeCluster] = None,
        port: int = 0,
        token: str = "",
        certfile: str = "",
        keyfile: str = "",
        bookmark_interval_s: float = 15.0,
    ) -> None:
        super().__init__(("127.0.0.1", port), _Handler)
        self.cluster = cluster if cluster is not None else FakeCluster()
        self.token = token
        #: Cadence of BOOKMARK events on watches that opted in via
        #: ``allowWatchBookmarks=true`` (the real server sends them about
        #: once a minute; tests shrink this to exercise the path).
        self.bookmark_interval_s = bookmark_interval_s
        self.tls = bool(certfile)
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile or None)
            self.socket = ctx.wrap_socket(self.socket, server_side=True)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://127.0.0.1:{self.server_address[1]}"

    def start(self) -> "LocalApiServer":
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.server_close()
        self.cluster.close()

    def __enter__(self) -> "LocalApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- kubeconfig emission ----------------------------------------------
    def write_kubeconfig(self, path: str, ca_file: str = "") -> str:
        """Write a kubeconfig pointing at this server (envtest does the
        same for its booted apiserver)."""
        import yaml

        cluster_entry: dict[str, Any] = {"server": self.url}
        if self.tls:
            if ca_file:
                cluster_entry["certificate-authority"] = ca_file
            else:
                cluster_entry["insecure-skip-tls-verify"] = True
        user: dict[str, Any] = {}
        if self.token:
            user["token"] = self.token
        doc = {
            "apiVersion": "v1",
            "kind": "Config",
            "current-context": "local",
            "clusters": [{"name": "local", "cluster": cluster_entry}],
            "users": [{"name": "local-user", "user": user}],
            "contexts": [
                {
                    "name": "local",
                    "context": {"cluster": "local", "user": "local-user"},
                }
            ],
        }
        with open(path, "w") as f:
            yaml.safe_dump(doc, f)
        return path


def main() -> None:  # pragma: no cover - manual demo entry point
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=8001)
    parser.add_argument("--token", default="")
    parser.add_argument(
        "--kubeconfig", default="", help="write a kubeconfig to this path"
    )
    args = parser.parse_args()
    server = LocalApiServer(port=args.port, token=args.token)
    if args.kubeconfig:
        server.write_kubeconfig(args.kubeconfig)
        print(f"kubeconfig written to {args.kubeconfig}")
    print(f"serving in-memory cluster at {server.url}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
