"""LocalApiServer — the in-memory apiserver served over real HTTP.

The reference's test strategy is "the cluster is real, the cluster is local":
envtest boots a genuine kube-apiserver + etcd with no nodes
(reference: pkg/upgrade/upgrade_suit_test.go:87-93, Makefile:76-78). This is
the equivalent harness: ``FakeCluster`` (finalizers, optimistic concurrency,
merge-patch, CRD establishment) exposed with Kubernetes REST conventions so
``RestClient`` — and any kubeconfig-speaking tool — exercises the genuine
wire path: URLs, verbs, selectors as query params, Status errors, the
eviction subresource, and bearer-token auth.

Since the asyncio rebuild (docs/wire-path.md) the server is a
single-event-loop HTTP/1.1 server rather than a thread-per-connection
``ThreadingHTTPServer``:

* **keep-alive + pipelining** — connections persist across requests
  (HTTP/1.1 default) and a client may write several requests before
  reading the first response; the per-connection loop answers them in
  order off the already-buffered bytes, so a pipelined informer seed
  pays one round trip for N LISTs;
* **streamed watch frames** — a watch is a chunked-transfer response on
  the SAME held connection (no ``Connection: close``): events stream as
  frames, periodic BOOKMARK frames carry the store rv, and the window's
  end is the terminal chunk — the connection goes back to keep-alive and
  the next watch window reuses it, no TCP/TLS re-setup per window;
* **content negotiation** — object and watch-frame payloads are encoded
  per the request's ``Accept`` header (``kube/wire.py``): JSON by
  default, the compact binary encoding when the caller asks (the
  protobuf posture of a real apiserver), and the ``;as=Table`` transform
  for ``kubectl get`` — including ``kubectl get -w``: a Table-negotiated
  watch streams Table-encoded event frames;
* **TCP_NODELAY** — asyncio sets it on every accepted socket, which is
  worth ~40ms per request/response turn over the old stack's
  Nagle/delayed-ACK interaction on loopback.

Also a deployment artifact, not only a fixture: ``python -m
k8s_operator_libs_tpu.kube.apiserver --port 8001`` serves a scratch cluster
for demos of the apply-crds CLI and the upgrade controller.
"""

from __future__ import annotations

import asyncio
import re
import ssl
import threading
import urllib.parse
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from .client import ApiError, BadRequestError, WatchExpiredError
from .fake import FakeCluster, WatchFrameSource
from .loopwatch import LoopStallWatchdog
from .objects import wrap
from .resources import ResourceInfo, resource_for_plural
from .table import accepts_table, render_table
from .wire import (
    content_type_for,
    decode_body,
    encode_body,
    encode_watch_frame,
    negotiate_encoding,
)
from ..utils import tracing
from ..utils.faultpoints import wall_now
from ..utils.lifecycle import lifecycle_resource

_PATH_RE = re.compile(
    r"^/(?:api|apis)(?:/(?P<group>[^/]+(?:\.[^/]+)*))?/(?P<version>v[^/]+)"
    r"(?:/namespaces/(?P<namespace>[^/]+))?"
    r"/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<subresource>status|eviction))?$"
)

#: GET /api/v1 or /apis/<group>/<version> with no resource segment =
#: API discovery (the endpoint crdutil's wait-for-established polls).
#: Core discovery lives ONLY at /api/<version>; /apis/<version> with no
#: group is a 404 on a real apiserver, so it must be one here too.
_DISCOVERY_RE = re.compile(
    r"^(?:/api/(?P<core_version>v[^/]+)"
    r"|/apis/(?P<group>[^/]+)/(?P<version>v[^/]+))$"
)

_REASONS = {
    200: "OK", 201: "Created", 400: "Bad Request", 401: "Unauthorized",
    404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
    410: "Gone", 415: "Unsupported Media Type", 422: "Unprocessable Entity",
    429: "Too Many Requests", 500: "Internal Server Error",
}

#: Upper bound on queued-but-undelivered events per watch stream; a
#: consumer this far behind loses the watch (stream ended cleanly) and
#: resumes from its last delivered revision — the journal replays what
#: the queue dropped. Same bound the threaded server used.
_WATCH_QUEUE_LIMIT = 1024

_MAX_HEADER_BYTES = 65536


def _status_body(code: int, reason: str, message: str) -> dict[str, Any]:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "code": code,
        "reason": reason,
        "message": message,
    }


def _ok_status() -> dict[str, Any]:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Success",
        "code": 200,
    }


class _Request:
    """One parsed HTTP request (the transport-neutral shape the
    dispatcher consumes)."""

    __slots__ = ("method", "target", "path", "query", "headers", "body",
                 "keep_alive")

    def __init__(self, method, target, headers, body, keep_alive):
        self.method = method
        self.target = target
        parsed = urllib.parse.urlparse(target)
        self.path = parsed.path
        self.query = dict(urllib.parse.parse_qsl(parsed.query))
        self.headers = headers  # lower-cased keys
        self.body = body
        self.keep_alive = keep_alive

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)


class _Response:
    """A buffered (non-streaming) response: ``body`` is the JSON-model
    payload, encoded per negotiation at write time."""

    __slots__ = ("status", "body")

    def __init__(self, status: int, body: Optional[dict[str, Any]]):
        self.status = status
        self.body = body


class _WatchParams:
    """Marker result: the dispatcher routed a ``?watch=true`` GET; the
    connection handler streams it."""

    __slots__ = ("info", "namespace", "query")

    def __init__(self, info, namespace, query):
        self.info = info
        self.namespace = namespace
        self.query = query


@dataclass(frozen=True)
class FlowConfig:
    """One APF flow's bounds: ``queue_depth`` pending requests (overflow
    sheds 429 + Retry-After) and ``concurrency`` — the dispatch batch the
    scheduler drains from this flow before re-checking higher-priority
    queues (handlers are synchronous on the loop, so this is the unit of
    head-of-line blocking a flow may impose on flows above it)."""

    queue_depth: int
    concurrency: int = 1


#: The flow a request belongs to, in strict priority order. Lease traffic
#: (the heartbeats that keep shard ownership alive) outranks reconcile
#: writes, which outrank informer reads (seed LISTs + watch
#: establishment), which outrank telemetry status reports — so a
#: monitor-report storm from thousands of nodes degrades telemetry
#: freshness, never lease renewal (docs/wire-path.md "Priority and
#: fairness").
APF_PRIORITY = ("lease", "reconcile", "informer", "telemetry")


def _default_flows() -> dict:
    return {
        "lease": FlowConfig(queue_depth=1024, concurrency=4),
        "reconcile": FlowConfig(queue_depth=1024, concurrency=4),
        "informer": FlowConfig(queue_depth=1024, concurrency=2),
        "telemetry": FlowConfig(queue_depth=256, concurrency=2),
    }


@dataclass
class ApfConfig:
    """API priority-and-fairness at the LocalApiServer: per-flow FIFO
    queues with bounded depth, drained in strict priority order; a full
    queue sheds the request as 429 with ``Retry-After`` (honored by
    RestClient's typed-error retry path). Defaults are generous enough
    that only a genuine storm sheds; production tunings shrink the
    telemetry queue. A partial ``flows`` dict is MERGED over the
    defaults — ``ApfConfig(flows={"telemetry": FlowConfig(8)})`` tunes
    one flow without un-configuring the other three."""

    enabled: bool = True
    #: Retry-After hint sent with every 429 (seconds; fractional OK for
    #: the in-process client, rendered as-is).
    retry_after_s: float = 1.0
    flows: dict = field(default_factory=_default_flows)

    def __post_init__(self) -> None:
        for flow, cfg in _default_flows().items():
            self.flows.setdefault(flow, cfg)


def classify_flow(method: str, path: str) -> str:
    """Request → APF flow, from the RESOURCE segment of the parsed path
    (the same route grammar the dispatcher uses — a pod named
    ``leases-cache-0`` or a namespace named ``leases`` must not ride the
    lease flow): Lease objects (any verb) are ``lease``;
    NodeHealthReport writes are ``telemetry``; remaining GETs
    (list/watch) are ``informer``; every other write is ``reconcile``."""
    m = _PATH_RE.match(path)
    plural = m.group("plural") if m is not None else ""
    if plural == "leases":
        return "lease"
    if method != "GET" and plural == "nodehealthreports":
        return "telemetry"
    if method == "GET":
        return "informer"
    return "reconcile"


class _ApfShed(Exception):
    """Internal marker: the flow queue was full; answer 429."""


class _ApfScheduler:
    """Per-flow FIFO queues drained in strict priority order by ONE
    task on the server loop. Handlers are synchronous, so the scheduler
    IS the concurrency bound; its job is ordering and shedding: a lease
    renewal enqueued behind a thousand pending telemetry writes is
    served next, and telemetry past its queue depth is shed instead of
    ever entering the loop's work."""

    def __init__(self, config: ApfConfig, loop) -> None:
        self._config = config
        self._loop = loop
        self._queues: dict[str, deque] = {f: deque() for f in APF_PRIORITY}
        self._wake = asyncio.Event()
        self.stats: dict[str, dict[str, int]] = {
            f: {"admitted": 0, "shed": 0, "max_queued": 0}
            for f in APF_PRIORITY
        }
        self._task = loop.create_task(self._drain())

    def close(self) -> None:
        self._task.cancel()

    def queue_depths(self) -> dict[str, int]:
        return {f: len(q) for f, q in self._queues.items()}

    async def submit(self, flow: str, thunk):
        """Enqueue ``thunk`` on ``flow``'s FIFO and await its result;
        raises ``_ApfShed`` immediately when the queue is full."""
        q = self._queues[flow]
        cfg = self._config.flows[flow]
        stats = self.stats[flow]
        if len(q) >= cfg.queue_depth:
            stats["shed"] += 1
            raise _ApfShed()
        future = self._loop.create_future()
        q.append((future, thunk))
        if len(q) > stats["max_queued"]:
            stats["max_queued"] = len(q)
        self._wake.set()
        return await future

    async def _drain(self) -> None:
        while True:
            flow = next(
                (f for f in APF_PRIORITY if self._queues[f]), None
            )
            if flow is None:
                self._wake.clear()
                await self._wake.wait()
                continue
            q = self._queues[flow]
            batch = max(1, self._config.flows[flow].concurrency)
            for _ in range(batch):
                if not q:
                    break
                future, thunk = q.popleft()
                if future.done():
                    continue  # submitter went away (connection killed)
                self.stats[flow]["admitted"] += 1
                try:
                    future.set_result(thunk())
                except BaseException as e:  # noqa: BLE001 - to submitter
                    future.set_exception(e)
            # Yield between batches: connection tasks get to read newly
            # arrived requests, so a lease renewal landing mid-storm is
            # seen before the next telemetry batch.
            await asyncio.sleep(0)


class _Dispatcher:
    """The verb logic, transport-free: ``(method, path, query, headers,
    body) -> _Response | _WatchParams``. Exactly the semantics the
    threaded handler had; errors surface as ApiError and are rendered
    into Status bodies by the caller."""

    def __init__(self, server: "LocalApiServer") -> None:
        self.server = server

    def dispatch(self, req: _Request) -> "_Response | _WatchParams":
        if not self._authorized(req):
            return _Response(
                401, _status_body(401, "Unauthorized", "invalid bearer token")
            )
        if req.method == "GET":
            discovery = _DISCOVERY_RE.match(req.path)
            if discovery is not None:
                core = discovery.group("core_version")
                return self._do_discovery(
                    "" if core else discovery.group("group"),
                    core or discovery.group("version"),
                )
        route = self._route(req)
        if route is None:
            return _Response(
                404, _status_body(404, "NotFound", f"no route for {req.path}")
            )
        info, namespace, name, subresource, query = route
        if req.method == "GET" and not name and query.get("watch") in (
            "true", "1"
        ):
            return _WatchParams(info, namespace, query)
        handler = getattr(self, f"_do_{req.method.lower()}", None)
        if handler is None:
            return _Response(
                405,
                _status_body(
                    405, "MethodNotAllowed", f"method {req.method} not allowed"
                ),
            )
        return handler(req, info, namespace, name, subresource, query)

    # -- helpers -----------------------------------------------------------
    def _authorized(self, req: _Request) -> bool:
        token = self.server.token
        if not token:
            return True
        return req.header("Authorization") == f"Bearer {token}"

    def _route(self, req: _Request):
        m = _PATH_RE.match(req.path)
        if m is None:
            return None
        group = m.group("group") or ""
        # /api/v1 has no group segment; the regex puts "v1" in version there.
        try:
            info = resource_for_plural(group, m.group("plural"))
        except KeyError:
            return None
        version = m.group("version")
        requested_gv = f"{group}/{version}" if group else version
        if info.api_version != requested_gv:
            # The URL names a version the registry doesn't serve this
            # resource at. A real apiserver routes per served
            # group/version — accept only if discovery says a stored
            # CRD serves the plural at that version; otherwise 404.
            try:
                served = self.server.cluster.discover(group, version)
            except ApiError:
                return None
            if not any(
                r.get("name") == m.group("plural") for r in served
            ):
                return None
            # Downstream (list apiVersion, printer columns) must speak
            # the REQUESTED version, not the registry's default.
            info = ResourceInfo(
                info.kind, requested_gv, info.plural, info.namespaced
            )
        return (
            info,
            m.group("namespace") or "",
            m.group("name") or "",
            m.group("subresource") or "",
            req.query,
        )

    def _read_body(self, req: _Request) -> dict[str, Any]:
        """Decode a write body by its Content-Type: JSON (the default)
        or the negotiated compact encoding — a compact-speaking client
        sends its create/update payloads compact too."""
        if not req.body:
            return {}
        return decode_body(req.body, req.header("Content-Type"))

    @staticmethod
    def _dry_run(query) -> bool:
        value = query.get("dryRun", "")
        if value and value != "All":
            # Real-apiserver validation: All is the only accepted value.
            raise BadRequestError(f"invalid dryRun value {value!r}")
        return bool(value)

    @staticmethod
    def _table(cluster, info, raws, query, list_metadata=None):
        include_object = query.get("includeObject", "") or "Metadata"
        if include_object not in ("Metadata", "Object", "None"):
            raise BadRequestError(
                f"invalid includeObject value {include_object!r}"
            )
        return render_table(
            raws,
            crd_columns=cluster.printer_columns(
                info.kind, info.api_version
            ),
            include_object=include_object,
            list_metadata=list_metadata,
        )

    # -- verbs -------------------------------------------------------------
    def _do_discovery(self, group: str, version: str) -> _Response:
        """Serve the APIResourceList discovery document (what the real
        apiserver returns for /apis/<group>/<version>); 404 while the
        group/version is not yet servable — the Established-but-
        undiscoverable window crdutil polls through."""
        resources = self.server.cluster.discover(group, version)
        gv = f"{group}/{version}" if group else version
        return _Response(
            200,
            {
                "kind": "APIResourceList",
                "apiVersion": "v1",
                "groupVersion": gv,
                "resources": resources,
            },
        )

    def _do_get(self, req, info, namespace, name, subresource, query):
        cluster = self.server.cluster
        as_table = accepts_table(req.header("Accept"))
        if name:
            obj = cluster.get(info.kind, name, namespace)
            if as_table:
                return _Response(
                    200, self._table(cluster, info, [obj.raw], query)
                )
            return _Response(200, obj.raw)
        since = query.get("sinceResourceVersion", "")
        if since:
            # Delta-aware LIST (docs/wire-path.md): the client presents
            # the revision it is current through; inside the journal
            # window the response is deltas-since-rv (changed items +
            # departed keys) instead of a full snapshot. Outside the
            # window: 410 Gone, and the client takes the full path —
            # the same decay contract as watch resumption.
            delta = cluster.list_delta(
                info.kind,
                since,
                namespace=namespace,
                label_selector=query.get("labelSelector") or None,
                field_selector=query.get("fieldSelector") or None,
            )
            if delta is None:
                raise WatchExpiredError(
                    f"resourceVersion {since} fell out of the journal; "
                    "a full list is required"
                )
            return _Response(200, {
                "apiVersion": info.api_version,
                "kind": f"{info.kind}List",
                "metadata": {
                    "resourceVersion": delta.revision,
                    "deltaSince": since,
                },
                "items": [o.raw for o in delta.items],
                "deletedItems": [
                    {"namespace": ns, "name": n}
                    for ns, n in delta.deleted
                ],
            })
        try:
            limit = int(query.get("limit", "0") or "0")
        except ValueError:
            raise BadRequestError(
                f"invalid limit {query.get('limit')!r}"
            ) from None
        items, revision, next_continue, remaining = cluster.list_page(
            info.kind,
            namespace=namespace,
            label_selector=query.get("labelSelector") or None,
            field_selector=query.get("fieldSelector") or None,
            limit=limit,
            continue_token=query.get("continue", ""),
        )
        # Collection revision: what a watch resumes from even when the
        # list is empty (no items to take a revision from). On chunked
        # lists it is the first page's snapshot revision, continue and
        # remainingItemCount follow the real server's listMeta.
        metadata: dict = {"resourceVersion": revision}
        if next_continue:
            metadata["continue"] = next_continue
        if remaining is not None:
            metadata["remainingItemCount"] = remaining
        if as_table:
            return _Response(200, self._table(
                cluster, info, [o.raw for o in items], query,
                list_metadata=metadata,
            ))
        return _Response(
            200,
            {
                "apiVersion": info.api_version,
                "kind": f"{info.kind}List",
                "metadata": metadata,
                "items": [o.raw for o in items],
            },
        )

    def _do_post(self, req, info, namespace, name, subresource, query):
        cluster = self.server.cluster
        body = self._read_body(req)
        if subresource == "eviction":
            # dryRun travels either as a query param or inside the
            # Eviction body's deleteOptions (kubectl sends the latter).
            opts = (body or {}).get("deleteOptions") or {}
            body_dry = opts.get("dryRun") or []
            if body_dry and body_dry != ["All"]:
                raise BadRequestError(f"invalid dryRun value {body_dry!r}")
            cluster.evict(
                name, namespace,
                dry_run=self._dry_run(query) or bool(body_dry),
            )
            return _Response(200, _ok_status())
        meta = body.setdefault("metadata", {})
        if info.namespaced and not meta.get("namespace"):
            meta["namespace"] = namespace
        created = cluster.create(
            wrap(body),
            field_manager=query.get("fieldManager", ""),
            dry_run=self._dry_run(query),
        )
        return _Response(201, created.raw)

    def _do_put(self, req, info, namespace, name, subresource, query):
        cluster = self.server.cluster
        obj = wrap(self._read_body(req))
        manager = query.get("fieldManager", "")
        dry = self._dry_run(query)
        if subresource == "status":
            updated = cluster.update_status(
                obj, field_manager=manager, dry_run=dry
            )
        else:
            updated = cluster.update(obj, field_manager=manager, dry_run=dry)
        return _Response(200, updated.raw)

    def _do_patch(self, req, info, namespace, name, subresource, query):
        cluster = self.server.cluster
        content_type = req.header("Content-Type")
        if "apply-patch" in content_type:
            # Server-side apply: the body is the applied config itself.
            if subresource:
                raise BadRequestError(
                    "server-side apply to subresources is not supported "
                    "(PARITY: apply targets the main resource only)"
                )
            body = self._read_body(req)
            meta = body.setdefault("metadata", {})
            if meta.get("name") and meta["name"] != name:
                # Real-apiserver rule: the body may not address a
                # different object than the URL.
                raise BadRequestError(
                    f"metadata.name {meta['name']!r} does not match the "
                    f"request path name {name!r}"
                )
            meta["name"] = name
            if info.namespaced:
                if meta.get("namespace") and meta["namespace"] != namespace:
                    raise BadRequestError(
                        f"metadata.namespace {meta['namespace']!r} does not "
                        f"match the request path namespace {namespace!r}"
                    )
                meta["namespace"] = namespace
            created = (
                cluster.get_or_none(info.kind, name, namespace) is None
            )
            applied = cluster.apply(
                body,
                field_manager=query.get("fieldManager", ""),
                force=query.get("force") == "true",
                dry_run=self._dry_run(query),
            )
            return _Response(201 if created else 200, applied.raw)
        if "strategic-merge-patch" in content_type:
            patch_type = "strategic"
        elif "json-patch" in content_type:
            patch_type = "json"
        else:
            patch_type = "merge"
        patched = cluster.patch(
            info.kind,
            name,
            namespace,
            patch=self._read_body(req),
            patch_type=patch_type,
            field_manager=query.get("fieldManager", ""),
            dry_run=self._dry_run(query),
        )
        return _Response(200, patched.raw)

    def _do_delete(self, req, info, namespace, name, subresource, query):
        cluster = self.server.cluster
        if not name:
            # DELETE on the collection: client-go's deleteCollection.
            # Mirror of the fake's guard (ADVICE.md): a real apiserver
            # does not serve deletecollection on the all-namespaces path
            # of a namespaced resource — refuse before the cluster call
            # so registered custom kinds get the same protection over
            # the wire as typed kinds get in-process.
            if info.namespaced and not namespace:
                raise BadRequestError(
                    f"deleteCollection on namespaced kind {info.kind} "
                    "requires a namespace (all-namespaces "
                    "deletecollection is not served by a real apiserver)"
                )
            deleted = cluster.delete_collection(
                info.kind,
                namespace,
                label_selector=query.get("labelSelector") or None,
                field_selector=query.get("fieldSelector") or None,
                propagation_policy=query.get("propagationPolicy") or None,
                dry_run=self._dry_run(query),
            )
            return _Response(200, {
                "apiVersion": info.api_version,
                "kind": f"{info.kind}List",
                "items": [o.raw for o in deleted],
            })
        preconditions = (self._read_body(req) or {}).get("preconditions") or {}
        cluster.delete(
            info.kind,
            name,
            namespace,
            dry_run=self._dry_run(query),
            propagation_policy=query.get("propagationPolicy") or None,
            precondition_uid=preconditions.get("uid"),
            precondition_resource_version=preconditions.get(
                "resourceVersion"
            ),
        )
        return _Response(200, _ok_status())


async def _read_request(
    reader: asyncio.StreamReader, writer: asyncio.StreamWriter
) -> Optional[_Request]:
    """Parse one HTTP/1.1 request off the connection; None on a clean
    EOF between requests (keep-alive peer went away)."""
    try:
        line = await reader.readline()
    except (ConnectionError, asyncio.IncompleteReadError):
        return None
    if not line or line in (b"\r\n", b"\n"):
        return None
    try:
        method, target, version = line.decode("latin-1").strip().split(" ", 2)
    except ValueError:
        raise BadRequestError("malformed request line") from None
    headers: dict[str, str] = {}
    total = len(line)
    while True:
        line = await reader.readline()
        total += len(line)
        if total > _MAX_HEADER_BYTES:
            raise BadRequestError("request headers too large")
        if not line:
            return None  # EOF mid-headers: peer gone
        if line in (b"\r\n", b"\n"):
            break
        name, _, value = line.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    if headers.get("expect", "").lower() == "100-continue":
        # We always read the full body; a conforming client (curl with a
        # large POST) WAITS for this interim response before sending it —
        # without the write both sides stall until the client's fallback
        # timer (the old BaseHTTPRequestHandler sent it automatically).
        headers.pop("expect")
        writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
        await writer.drain()
    body = b""
    length = int(headers.get("content-length") or 0)
    if length:
        body = await reader.readexactly(length)
    keep_alive = (
        version.upper() != "HTTP/1.0"
        and headers.get("connection", "").lower() != "close"
    )
    return _Request(method.upper(), target, headers, body, keep_alive)


@lifecycle_resource(acquire="start", release=("stop", "shutdown"))
class LocalApiServer:
    """Serve a FakeCluster on 127.0.0.1; use as a context manager in tests.

    Single asyncio event loop on a background thread; the public surface
    (``cluster``, ``token``, ``url``, ``start``/``stop``, context
    manager, ``write_kubeconfig``) is unchanged from the threaded
    implementation. New observability for the wire path:
    ``connections_opened`` / ``requests_served`` / ``watch_streams`` /
    ``watch_frames_sent`` / ``bytes_sent`` counters (the counting hook
    the connection-reuse tests and the bench's attribution read), and
    ``kill_connections()`` force-drops every live connection — the
    fault hook for watch-resume tests."""

    def __init__(
        self,
        cluster: Optional[FakeCluster] = None,
        port: int = 0,
        token: str = "",
        certfile: str = "",
        keyfile: str = "",
        bookmark_interval_s: float = 15.0,
        apf: Optional[ApfConfig] = None,
        stall_watchdog_threshold_s: float = 0.0,
        read_only: bool = False,
    ) -> None:
        self.cluster = cluster if cluster is not None else FakeCluster()
        self.token = token
        #: Read replica mode (docs/wire-path.md "Read replicas"): serve
        #: GET/HEAD — LIST, delta-LIST, watch — and refuse writes with
        #: 405, keeping every mutation ordered on the primary. Replicas
        #: share the primary's cluster journal (see :meth:`read_replica`),
        #: so a watch served here carries the same revisions in the same
        #: order the primary assigned.
        self.read_only = bool(read_only)
        #: Priority-and-fairness: per-flow FIFO queues + shedding. On by
        #: default with storm-sized bounds (see ApfConfig); pass
        #: ``ApfConfig(enabled=False)`` for the raw dispatch path.
        self.apf = apf if apf is not None else ApfConfig()
        self._apf_scheduler: Optional[_ApfScheduler] = None
        #: > 0 starts a :class:`~.loopwatch.LoopStallWatchdog` on the
        #: server loop — the runtime proof that no handler blocks it
        #: (ASY601's twin; read via :meth:`loop_stall_stats`).
        self.stall_watchdog_threshold_s = float(stall_watchdog_threshold_s)
        self._stall_watchdog: Optional[LoopStallWatchdog] = None
        #: Cadence of BOOKMARK events on watches that opted in via
        #: ``allowWatchBookmarks=true`` (the real server sends them about
        #: once a minute; tests shrink this to exercise the path).
        self.bookmark_interval_s = bookmark_interval_s
        self._port_requested = port
        self.tls = bool(certfile)
        self._ssl_ctx = None
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile or None)
            self._ssl_ctx = ctx
        self._dispatcher = _Dispatcher(self)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._port: Optional[int] = None
        self._started = threading.Event()
        self._writers: set[asyncio.StreamWriter] = set()
        # -- wire counters (loop-thread writes; cross-thread reads are
        # single-field reads of ints, safe under the GIL) --
        self.connections_opened = 0
        self.requests_served = 0
        self.watch_streams = 0
        self.watch_frames_sent = 0
        self.bytes_sent = 0
        #: Bytes written on watch STREAMS only (head + frames + terminal
        #: chunk) — the attribution the hub bench compares across worker
        #: counts (aggregate watch bytes must not multiply with workers).
        self.watch_bytes_sent = 0
        self._request_log: Optional[list] = None
        self._wire_log: Optional[list] = None

    def apf_stats(self) -> dict[str, dict[str, int]]:
        """Per-flow priority-and-fairness counters: current queue depth,
        admitted/shed totals (a shed IS a 429), high-water queue depth.
        Empty when APF is disabled. Feeds ``tpu_operator_wire_apf_*``."""
        scheduler = self._apf_scheduler
        if scheduler is None:
            return {}
        depths = scheduler.queue_depths()
        return {
            flow: {
                "queued": depths.get(flow, 0),
                "admitted_total": stats["admitted"],
                "shed_429_total": stats["shed"],
                "max_queued": stats["max_queued"],
            }
            for flow, stats in scheduler.stats.items()
        }

    def loop_stall_stats(self) -> dict:
        """Server-loop stall watchdog stats (``{}`` when the watchdog is
        off) — the ``tpu_operator_wire_loop_stall_*`` feed for the
        server side, and the ``report_storm`` bench's hard-zero."""
        watchdog = self._stall_watchdog
        return watchdog.stats() if watchdog is not None else {}

    def start_request_log(self) -> list:
        """Begin recording ``(method, path, query)`` per request served
        (the counting hook transport tests assert against — e.g. "a
        killed watch connection resumes with a watch, not a LIST").
        Returns the live list; ``stop_request_log()`` detaches it."""
        log: list = []
        self._request_log = log
        return log

    def stop_request_log(self) -> list:
        log, self._request_log = self._request_log, None
        return log if log is not None else []

    def start_wire_log(self) -> list:
        """Begin recording ``(method, path, pipelined)`` per request served,
        where ``pipelined`` means the request's bytes were ALREADY buffered
        on the connection when the previous response finished — i.e. it
        rode a pipelined burst and cost no extra round trip. A roll's
        write round trips are therefore its non-pipelined writes (the
        first request of each burst), which is what the ``write_batching``
        bench floors. Conservative in the honest direction: a request the
        client pipelined but the kernel hadn't delivered yet counts as a
        round trip. Returns the live list; ``stop_wire_log()`` detaches."""
        log: list = []
        self._wire_log = log
        return log

    def stop_wire_log(self) -> list:
        log, self._wire_log = self._wire_log, None
        return log if log is not None else []

    # -- lifecycle ---------------------------------------------------------
    @property
    def url(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://127.0.0.1:{self._port}"

    @property
    def server_address(self) -> tuple[str, int]:
        """(host, port) — kept from the socketserver implementation for
        callers that rebind a revived server to the same port."""
        return ("127.0.0.1", self._port or self._port_requested)

    def start(self) -> "LocalApiServer":
        self._thread = threading.Thread(
            target=self._run_loop, name="local-apiserver", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("LocalApiServer failed to start")
        if self._startup_error is not None:
            raise self._startup_error
        return self

    def _run_loop(self) -> None:
        self._startup_error: Optional[BaseException] = None
        loop = asyncio.new_event_loop()
        self._loop = loop
        try:
            try:
                self._server = loop.run_until_complete(
                    asyncio.start_server(
                        self._serve_connection,
                        "127.0.0.1",
                        self._port_requested,
                        ssl=self._ssl_ctx,
                    )
                )
                self._port = self._server.sockets[0].getsockname()[1]
                if self.apf.enabled:
                    self._apf_scheduler = _ApfScheduler(self.apf, loop)
                if self.stall_watchdog_threshold_s > 0:
                    self._stall_watchdog = LoopStallWatchdog(
                        loop, threshold_s=self.stall_watchdog_threshold_s
                    ).start()
            except BaseException as e:  # noqa: BLE001 - surfaced to start()
                self._startup_error = e
                return
            finally:
                self._started.set()
            loop.run_forever()
            # stop() requested: tear down the acceptor, then connections
            # (in that order — on newer Pythons Server.wait_closed blocks
            # until handlers finish, so handlers must be cancelled first,
            # and the acceptor must stop before that so no new ones land).
            self._server.close()
            for writer in list(self._writers):
                transport = writer.transport
                if transport is not None:
                    transport.abort()
            pending = [
                t for t in asyncio.all_tasks(loop) if not t.done()
            ]
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
        finally:
            loop.close()

    def shutdown(self) -> None:
        """Stop serving (acceptor, live connections, loop thread) but
        leave the cluster alone — the socketserver-era split callers use
        to revive a server over the same store."""
        watchdog = self._stall_watchdog
        if watchdog is not None:
            # Before loop.stop(): the cancel must be queued while the
            # loop still drains callbacks (LIF801). Stats stay readable.
            watchdog.stop()
        loop = self._loop
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)

    def server_close(self) -> None:
        """Kept for socketserver-API compatibility; the listening socket
        is already closed by shutdown()."""

    def stop(self) -> None:
        self.shutdown()
        # A read replica never owns the journal: closing the shared
        # cluster would take the primary (and its watches) down with it.
        if not self.read_only:
            self.cluster.close()

    def read_replica(self, port: int = 0) -> "LocalApiServer":
        """A NOT-yet-started read-only replica over this server's
        cluster journal. Sharing the journal object is the in-process
        stand-in for journal replication: the replica serves LIST,
        delta-LIST, and watch windows with the primary's revision
        order, while every write it receives is refused with 405.
        Clients spread reads via ``RestConfig.read_servers`` and fail
        over to the primary when a replica dies mid-storm."""
        return LocalApiServer(
            cluster=self.cluster,
            port=port,
            token=self.token,
            bookmark_interval_s=self.bookmark_interval_s,
            apf=self.apf,
            read_only=True,
        )

    def serve_forever(self) -> None:  # pragma: no cover - CLI entry path
        """Block until interrupted (the __main__ demo path)."""
        if self._thread is None:
            self.start()
        self._thread.join()

    def __enter__(self) -> "LocalApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def kill_connections(self) -> int:
        """Force-close every live connection (test/fault hook: simulates
        the peer's TCP state vanishing mid-watch, the failure the
        bookmark-resume path exists for). Returns how many were hit."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return 0
        writers = list(self._writers)

        def _close_all():
            for writer in writers:
                transport = writer.transport
                if transport is not None:
                    transport.abort()

        loop.call_soon_threadsafe(_close_all)
        return len(writers)

    # -- connection handling ----------------------------------------------
    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self.connections_opened += 1
        self._writers.add(writer)
        served_on_connection = 0
        try:
            while True:
                # Sampled BEFORE the read blocks: bytes already buffered
                # while a previous response was in flight mean this next
                # request was pipelined — it shares the earlier request's
                # round trip (start_wire_log docstring).
                pipelined = served_on_connection > 0 and bool(
                    getattr(reader, "_buffer", b"")
                )
                try:
                    req = await _read_request(reader, writer)
                except BadRequestError as e:
                    await self._write_response(
                        writer, 400,
                        _status_body(400, "BadRequest", e.message),
                        "json", keep_alive=False,
                    )
                    return
                if req is None:
                    return
                self.requests_served += 1
                served_on_connection += 1
                request_log = self._request_log
                if request_log is not None:
                    request_log.append((req.method, req.path, dict(req.query)))
                wire_log = self._wire_log
                if wire_log is not None:
                    wire_log.append((req.method, req.path, pipelined))
                if self.read_only and req.method not in ("GET", "HEAD"):
                    await self._write_response(
                        writer, 405,
                        _status_body(
                            405, "MethodNotAllowed",
                            "read-only replica: send writes to the "
                            "primary apiserver",
                        ),
                        "json", keep_alive=req.keep_alive,
                    )
                    if not req.keep_alive:
                        return
                    continue
                scheduler = self._apf_scheduler
                # Server-side trace context (docs/tracing.md): a request
                # carrying a traceparent joins the CLIENT's trace — its
                # server span decomposes client-observed latency into
                # APF queue wait (the child span below) vs dispatch, and
                # any cluster write made during dispatch records this
                # trace as its write origin. One global read when off.
                tracer = tracing.tracer()
                server_span = None
                if tracer is not None:
                    trace_ctx = tracing.parse_traceparent(
                        req.header("traceparent")
                    )
                    server_span = tracer.start_span(
                        "server.request", category="wire",
                        trace_id=trace_ctx[0] if trace_ctx else None,
                        # "" (not None): an uncontexted request is a
                        # fresh root, never a child of a leaked span.
                        parent_id=trace_ctx[1] if trace_ctx else "",
                        attrs={"method": req.method, "path": req.path},
                    )
                try:
                    try:
                        if scheduler is not None:
                            flow = classify_flow(req.method, req.path)
                            enqueued = (
                                wall_now() if server_span is not None
                                else 0.0
                            )
                            dispatched = [0.0]

                            def _dispatch_traced(
                                req=req, server_span=server_span,
                                dispatched=dispatched,
                            ):
                                dispatched[0] = wall_now()
                                with tracing.use_span(server_span):
                                    return self._dispatcher.dispatch(req)

                            try:
                                result = await scheduler.submit(
                                    flow, _dispatch_traced
                                )
                            finally:
                                # Record the queue wait even when
                                # dispatch RAISED (routine 404/409 —
                                # error-heavy storms are exactly where
                                # queue attribution matters); a shed
                                # never dispatched, so dispatched[0]
                                # stays 0 and nothing is recorded.
                                if server_span is not None and (
                                    dispatched[0]
                                ):
                                    server_span.attrs["flow"] = flow
                                    tracer.add_span(
                                        "apf.queue", category="queue",
                                        start=enqueued,
                                        end=dispatched[0],
                                        parent=server_span,
                                        attrs={"flow": flow},
                                    )
                        else:
                            with tracing.use_span(server_span):
                                result = self._dispatcher.dispatch(req)
                    except _ApfShed:
                        # Shed, not queued: the flow is over its depth.
                        # The client backs off per Retry-After and
                        # retries; the connection stays healthy
                        # (keep-alive preserved).
                        if server_span is not None:
                            server_span.attrs["status"] = 429
                        await self._write_response(
                            writer, 429,
                            _status_body(
                                429, "TooManyRequests",
                                "request shed by priority-and-fairness; "
                                "retry after backoff",
                            ),
                            "json", keep_alive=req.keep_alive,
                            extra_headers={
                                "Retry-After": f"{self.apf.retry_after_s:g}"
                            },
                        )
                        if not req.keep_alive:
                            return
                        continue
                    except ApiError as e:
                        result = _Response(
                            e.status,
                            _status_body(e.status, e.reason, e.message),
                        )
                    except Exception as e:  # noqa: BLE001 - surfaced as 500
                        result = _Response(
                            500, _status_body(500, "InternalError", str(e))
                        )
                    if isinstance(result, _WatchParams):
                        if server_span is not None:
                            # The span measures dispatch, not the stream's
                            # lifetime; end it before streaming (end_span
                            # is idempotent for the finally below).
                            server_span.attrs["status"] = "watch"
                            tracer.end_span(server_span)
                        await self._stream_watch(writer, req, result)
                    else:
                        if server_span is not None:
                            server_span.attrs["status"] = result.status
                        encoding = (
                            "json"
                            if accepts_table(req.header("Accept"))
                            else negotiate_encoding(req.header("Accept"))
                        )
                        await self._write_response(
                            writer, result.status, result.body, encoding,
                            keep_alive=req.keep_alive,
                        )
                finally:
                    if server_span is not None:
                        tracer.end_span(server_span)
                if not req.keep_alive:
                    return
        except (ConnectionError, asyncio.IncompleteReadError):
            return  # peer went away mid-exchange
        except asyncio.CancelledError:
            raise
        finally:
            self._writers.discard(writer)
            writer.close()

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        body: Optional[dict[str, Any]],
        encoding: str,
        keep_alive: bool,
        extra_headers: Optional[dict[str, str]] = None,
    ) -> None:
        payload = encode_body(body, encoding) if body is not None else b""
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Status')}\r\n"
            f"Content-Type: {content_type_for(encoding)}\r\n"
            f"Content-Length: {len(payload)}\r\n"
        )
        for header_name, header_value in (extra_headers or {}).items():
            head += f"{header_name}: {header_value}\r\n"
        if not keep_alive:
            head += "Connection: close\r\n"
        data = head.encode("latin-1") + b"\r\n" + payload
        writer.write(data)
        self.bytes_sent += len(data)
        await writer.drain()

    # -- watch streaming ---------------------------------------------------
    async def _stream_watch(
        self, writer: asyncio.StreamWriter, req: _Request, params: _WatchParams
    ) -> None:
        """``?watch=true``: stream watch events as chunked frames on the
        held connection.

        Kubernetes watch semantics in the shape the library consumes:

        * ``resourceVersion=N`` resumes from the event journal — the
          list-then-watch pattern with no lost-event window (events since
          the listed revision replay first; an expired revision returns
          410 Gone and the client must re-list);
        * without ``resourceVersion``, events after establishment stream;
        * scope transitions follow the real apiserver: an object whose
          update makes it START matching the selector arrives as ADDED,
          one that STOPS matching arrives as DELETED;
        * a consumer too slow to drain its event queue loses the watch
          (stream ended at the last delivered revision) rather than
          silently losing events;
        * ``timeoutSeconds`` bounds the stream server-side — the window
          ends with the terminal chunk and the CONNECTION STAYS OPEN:
          the next watch window rides the same socket;
        * ``allowWatchBookmarks=true`` opts into periodic BOOKMARK frames
          carrying only the current collection resourceVersion, so a
          quiet (e.g. selector-scoped) watch keeps a fresh resume point
          and resumption does not decay into 410 + full re-list;
        * frames are encoded per the negotiated encoding (JSON lines or
          length-prefixed compact frames), and a Table-negotiated watch
          (``Accept: ...;as=Table`` — kubectl get -w) streams
          Table-transformed event frames.
        """
        info, namespace, query = params.info, params.namespace, params.query
        accept = req.header("Accept")
        as_table = accepts_table(accept)
        encoding = "json" if as_table else negotiate_encoding(accept)
        timeout_s = (
            float(query["timeoutSeconds"])
            if query.get("timeoutSeconds")
            else None
        )
        loop = asyncio.get_running_loop()
        events: asyncio.Queue = asyncio.Queue()
        overflowed = asyncio.Event()

        def emit(event_type, data, old):
            # Runs on the WRITER's thread (any cluster mutator) — or on
            # the loop thread itself when the mutation came through this
            # server; call_soon_threadsafe is correct from both. It must
            # NEVER raise into the mutator (FakeCluster._emit does not
            # isolate watcher errors): a loop torn down mid-teardown
            # reads as a dead stream, not a cluster write failure.
            def _put():
                if events.qsize() >= _WATCH_QUEUE_LIMIT:
                    overflowed.set()  # end the stream; the client resumes
                else:
                    events.put_nowait((event_type, data, old))

            try:
                loop.call_soon_threadsafe(_put)
            except RuntimeError:
                pass  # loop closed while the subscription unwound

        source = WatchFrameSource(
            self.cluster,
            info.kind,
            info.api_version,
            namespace=namespace,
            label_selector=query.get("labelSelector") or None,
            field_selector=query.get("fieldSelector") or None,
        )
        try:
            # Everything from open() on is covered by the unsubscribe
            # (close() is idempotent and safe pre-open): a cancellation
            # landing anywhere in the stream cannot leak the watcher.
            try:
                replay = source.open(emit, query.get("resourceVersion"))
            except ApiError as e:
                await self._write_response(
                    writer, e.status,
                    _status_body(e.status, e.reason, e.message),
                    "json" if as_table else encoding,
                    keep_alive=req.keep_alive,
                )
                return
            self.watch_streams += 1
            head = (
                "HTTP/1.1 200 OK\r\n"
                f"Content-Type: {content_type_for(encoding)}\r\n"
                "Transfer-Encoding: chunked\r\n"
                "\r\n"
            ).encode("latin-1")
            writer.write(head)
            self.bytes_sent += len(head)
            self.watch_bytes_sent += len(head)
            for frame, data in replay:
                await self._write_frame(
                    writer, frame, data, encoding, info, query, as_table
                )
            deadline = (
                loop.time() + timeout_s if timeout_s is not None else None
            )
            interval = self.bookmark_interval_s
            bookmarks = query.get("allowWatchBookmarks") in ("true", "1")
            next_bookmark = loop.time() + interval
            while not overflowed.is_set():
                if deadline is not None:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    poll = min(0.2, remaining)
                else:
                    poll = 0.2
                if bookmarks:
                    poll = min(poll, max(0.01, next_bookmark - loop.time()))
                try:
                    event_type, data, old = await asyncio.wait_for(
                        events.get(), poll
                    )
                except asyncio.TimeoutError:
                    if bookmarks and loop.time() >= next_bookmark:
                        # Bookmark only from a DRAINED queue — see
                        # WatchFrameSource.bookmark for the rv-before-
                        # emptiness-recheck ordering.
                        frame, data = source.bookmark()
                        if events.empty():
                            next_bookmark = loop.time() + interval
                            await self._write_frame(
                                writer, frame, data, encoding, info, query,
                                as_table,
                            )
                    continue
                mapped = source.classify(event_type, data, old)
                if mapped is None:
                    continue
                await self._write_frame(
                    writer, mapped, data, encoding, info, query, as_table
                )
            # Terminal chunk: the window is over, the connection lives on.
            writer.write(b"0\r\n\r\n")
            self.bytes_sent += 5
            self.watch_bytes_sent += 5
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            return  # consumer went away mid-stream
        finally:
            source.close()

    async def _write_frame(
        self,
        writer: asyncio.StreamWriter,
        event_type: str,
        data: dict[str, Any],
        encoding: str,
        info,
        query,
        as_table: bool,
    ) -> None:
        if as_table:
            # kubectl get -w: every event object — bookmarks included —
            # is Table-transformed, one row per event.
            data = self._dispatcher._table(self.cluster, info, [data], query)
        frame = encode_watch_frame(
            {"type": event_type, "object": data}, encoding
        )
        chunk = b"%x\r\n" % len(frame) + frame + b"\r\n"
        writer.write(chunk)
        self.watch_frames_sent += 1
        self.bytes_sent += len(chunk)
        self.watch_bytes_sent += len(chunk)
        await writer.drain()

    # -- kubeconfig emission ----------------------------------------------
    def write_kubeconfig(self, path: str, ca_file: str = "") -> str:
        """Write a kubeconfig pointing at this server (envtest does the
        same for its booted apiserver)."""
        import yaml

        cluster_entry: dict[str, Any] = {"server": self.url}
        if self.tls:
            if ca_file:
                cluster_entry["certificate-authority"] = ca_file
            else:
                cluster_entry["insecure-skip-tls-verify"] = True
        user: dict[str, Any] = {}
        if self.token:
            user["token"] = self.token
        doc = {
            "apiVersion": "v1",
            "kind": "Config",
            "current-context": "local",
            "clusters": [{"name": "local", "cluster": cluster_entry}],
            "users": [{"name": "local-user", "user": user}],
            "contexts": [
                {
                    "name": "local",
                    "context": {"cluster": "local", "user": "local-user"},
                }
            ],
        }
        with open(path, "w") as f:
            yaml.safe_dump(doc, f)
        return path


def main() -> None:  # pragma: no cover - manual demo entry point
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=8001)
    parser.add_argument("--token", default="")
    parser.add_argument(
        "--kubeconfig", default="", help="write a kubeconfig to this path"
    )
    args = parser.parse_args()
    server = LocalApiServer(port=args.port, token=args.token).start()
    try:
        if args.kubeconfig:
            server.write_kubeconfig(args.kubeconfig)
            print(f"kubeconfig written to {args.kubeconfig}")
        print(f"serving in-memory cluster at {server.url}")
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
    finally:
        server.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
