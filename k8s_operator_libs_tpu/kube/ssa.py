"""Server-side apply: field ownership, conflict detection, managedFields.

The real apiserver's fourth patch flavor (``application/apply-patch+yaml``,
client-go's ``client.Apply`` / ``kubectl apply --server-side``) is how
modern controller-runtime consumers co-manage objects: each manager sends
its *intent* (a partial object), the server tracks which manager owns which
field in ``metadata.managedFields``, removes fields a manager stops
declaring, and refuses (409) to let one manager silently overwrite
another's field unless forced. The reference's consumer operators deploy
onto clusters where this machinery arbitrates every write; envtest gets it
for free from the real apiserver — this module gives FakeCluster /
LocalApiServer the same semantics (structured-merge-diff's behavior,
re-implemented schema-less).

Internal representation: a field set is a ``set`` of leaf *paths*; each
path is a tuple of steps ``("f", name)`` (map field), ``("k", json)``
(keyed-list element, canonical-JSON key), ``("v", json)`` (set-list
member). The wire format is upstream's FieldsV1 (``f:``/``k:``/``v:``
keys, ``.`` marking ownership of a container itself) so managedFields
round-trip through clients unchanged.

Deviations from upstream (documented in PARITY.md): list merge keys come
from the field-name registry shared with the strategic engine (no OpenAPI
schema); writes without an explicit ``field_manager`` on objects that have
never been managed stay untracked (upstream derives a manager name from
the user agent); ``null`` values in an applied config are treated as
omitted; apply targets the main resource (no status apply).
"""

from __future__ import annotations

import copy
import json
from typing import Any, Iterable, Mapping, Optional

from .client import BadRequestError, ConflictError, InvalidError

#: Path step kinds.
_F = "f"  # map field
_K = "k"  # keyed-list element
_V = "v"  # set-list member

Step = tuple[str, str]
Path = tuple[Step, ...]

#: The manager name recorded for writes that did not declare one —
#: mirrors upstream's fallback behavior (it derives something like
#: "Go-http-client" from the user agent; we use a fixed sentinel).
UNKNOWN_MANAGER = "unknown"


def _canon(value: Any) -> str:
    return json.dumps(value, sort_keys=True, separators=(",", ":"))


def _json_equal(a: Any, b: Any) -> bool:
    # Duplicated from fake.py's engine-level helper to avoid an import
    # cycle; JSON-strict (bool is not a number).
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, Mapping) and isinstance(b, Mapping):
        return a.keys() == b.keys() and all(_json_equal(a[k], b[k]) for k in a)
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(map(_json_equal, a, b))
    return a == b


def _registries():
    # Lazy: fake.py owns the merge-key registries (and imports this
    # module's engine); importing at call time breaks the cycle.
    from .fake import _LIST_MERGE_KEYS, _PRIMITIVE_MERGE_FIELDS

    return _LIST_MERGE_KEYS, _PRIMITIVE_MERGE_FIELDS


def _scalar(v: Any) -> bool:
    return not isinstance(v, (Mapping, list))


def _list_mode(field: str, items: list[Any]) -> tuple[str, Optional[str]]:
    """Classify a list field: ("map", key) | ("set", None) | ("atomic", None).

    Mirrors the strategic engine's resolution: keyed lists via the
    field-name registry with ``name`` as the universal fallback, the two
    upstream ``listType=set`` primitive fields as sets, everything else
    atomic (owned and replaced wholesale) — upstream's default for
    untagged/CRD lists.
    """
    merge_keys, primitive_fields = _registries()
    if field in primitive_fields and all(_scalar(i) for i in items):
        return ("set", None)
    if items and all(isinstance(i, Mapping) for i in items):
        for key in merge_keys.get(field, ()) + ("name",):
            if all(key in i for i in items):
                return ("map", key)
    return ("atomic", None)


# ---------------------------------------------------------------------------
# Field-set extraction and the FieldsV1 wire format


#: Identity and server-owned metadata never enters a field set (upstream
#: fieldsets carry a manager's intent — labels, annotations, finalizers,
#: ownerReferences — never the object's coordinates or server bookkeeping).
_SERVER_OWNED_META = frozenset(
    {
        "name",
        "namespace",
        "uid",
        "resourceVersion",
        "creationTimestamp",
        "generation",
        "selfLink",
        "deletionTimestamp",
        "deletionGracePeriodSeconds",
        "managedFields",
    }
)

_META_PREFIX: Path = ((_F, "metadata"),)


def extract_leaves(obj: Mapping[str, Any]) -> dict[Path, Any]:
    """Leaf path -> value for every managed field of ``obj``."""
    out: dict[Path, Any] = {}
    _extract_into(obj, (), out, top=True)
    return out


def _extract_into(
    obj: Mapping[str, Any], prefix: Path, out: dict[Path, Any], top: bool = False
) -> None:
    for field, value in obj.items():
        if top and field in ("apiVersion", "kind"):
            # Type identity, not managed state.
            continue
        if prefix == _META_PREFIX and field in _SERVER_OWNED_META:
            continue
        path = prefix + ((_F, field),)
        _extract_value(field, value, path, out)
    if not obj and prefix:
        out[prefix] = {}


def _extract_value(field: str, value: Any, path: Path, out: dict[Path, Any]) -> None:
    if isinstance(value, Mapping):
        if value:
            _extract_into(value, path, out)
        else:
            out[path] = {}
    elif isinstance(value, list):
        mode, key = _list_mode(field, value)
        if mode == "map":
            for item in value:
                kpath = path + ((_K, _canon({key: item[key]})),)
                if len(item) > 1:
                    _extract_into(item, kpath, out)
                else:
                    out[kpath] = copy.deepcopy(item)
        elif mode == "set":
            for item in value:
                out[path + ((_V, _canon(item)),)] = item
        else:
            out[path] = copy.deepcopy(value)
    else:
        out[path] = copy.deepcopy(value)


def leaves_to_fields_v1(paths: Iterable[Path]) -> dict[str, Any]:
    """Render an internal leaf set in upstream's FieldsV1 wire shape."""
    root: dict[str, Any] = {}
    for path in sorted(paths):
        node = root
        for kind, token in path:
            node = node.setdefault(f"{kind}:{token}", {})
        # A leaf that is also a container for deeper-owned leaves gets the
        # upstream "." self-marker; pure leaves stay {}.
        if node:
            node["."] = {}
    return root


def fields_v1_to_leaves(fv1: Mapping[str, Any]) -> set[Path]:
    out: set[Path] = set()
    _parse_fv1(fv1, (), out)
    return out


def _parse_fv1(node: Mapping[str, Any], prefix: Path, out: set[Path]) -> None:
    children = False
    for key, sub in node.items():
        if key == ".":
            out.add(prefix)
            continue
        kind, _, token = key.partition(":")
        children = True
        _parse_fv1(sub, prefix + ((kind, token),), out)
    if not children and prefix:
        out.add(prefix)


# ---------------------------------------------------------------------------
# Navigation / mutation by path


def value_at(obj: Any, path: Path) -> tuple[bool, Any]:
    cur = obj
    for kind, token in path:
        if kind == _F:
            if not isinstance(cur, Mapping) or token not in cur:
                return (False, None)
            cur = cur[token]
        elif kind == _K:
            if not isinstance(cur, list):
                return (False, None)
            keyd = json.loads(token)
            cur = next(
                (
                    i
                    for i in cur
                    if isinstance(i, Mapping)
                    and all(i.get(k) == v for k, v in keyd.items())
                ),
                None,
            )
            if cur is None:
                return (False, None)
        else:  # _V
            if not isinstance(cur, list):
                return (False, None)
            want = json.loads(token)
            if not any(_json_equal(i, want) for i in cur):
                return (False, None)
            cur = want
    return (True, cur)


def remove_leaf(obj: dict[str, Any], path: Path) -> None:
    """Remove the value at ``path`` (missing = no-op), pruning containers
    left empty along the way — the applier created them, nobody declares
    them anymore."""
    _remove_leaf(obj, path)


def _remove_leaf(cur: Any, path: Path) -> bool:
    """Returns True when ``cur`` became empty and should be pruned."""
    if not path:
        return False
    (kind, token), rest = path[0], path[1:]
    if kind == _F:
        if not isinstance(cur, Mapping) or token not in cur:
            return False
        if rest:
            if _remove_leaf(cur[token], rest):
                del cur[token]
        else:
            del cur[token]
    elif kind == _K:
        if not isinstance(cur, list):
            return False
        keyd = json.loads(token)
        if (
            len(rest) == 1
            and rest[0][0] == _F
            and rest[0][1] in keyd
        ):
            # The merge key is structural: it leaves only WITH the element
            # (the key-only collapse below), never alone — deleting it
            # first would strand a keyless ghost that declassifies the
            # whole list to atomic.
            return False
        for i, item in enumerate(cur):
            if isinstance(item, Mapping) and all(
                item.get(k) == v for k, v in keyd.items()
            ):
                if rest:
                    if _remove_leaf(item, rest) or set(item) == set(keyd):
                        cur.pop(i)
                else:
                    cur.pop(i)
                break
    else:  # _V
        if not isinstance(cur, list):
            return False
        want = json.loads(token)
        cur[:] = [i for i in cur if not _json_equal(i, want)]
    return (isinstance(cur, (Mapping, list)) and not cur)


# ---------------------------------------------------------------------------
# Structural merge of an applied config into the live object


def merge_applied(live: dict[str, Any], applied: Mapping[str, Any]) -> None:
    for field, value in applied.items():
        if value is None:
            # Apply declares intent; null is "not my field" (removal
            # happens via omission + ownership pruning).
            continue
        if isinstance(value, Mapping):
            cur = live.get(field)
            if isinstance(cur, dict):
                merge_applied(cur, value)
            else:
                live[field] = copy.deepcopy(value)
        elif isinstance(value, list):
            live[field] = _merge_list(field, live.get(field), value)
        else:
            live[field] = copy.deepcopy(value)


def _merge_list(field: str, live: Any, applied: list[Any]) -> list[Any]:
    # An empty applied list cannot be classified from its own items —
    # fall back to the live list's shape, so declaring "none of mine"
    # on a keyed list keeps other managers' elements (their removal is
    # ownership pruning's job, never the merge's).
    mode, key = _list_mode(
        field, applied or (live if isinstance(live, list) else [])
    )
    if not isinstance(live, list) or mode == "atomic":
        return copy.deepcopy(applied)
    if mode == "set":
        merged = list(live)
        merged.extend(
            item
            for item in applied
            if not any(_json_equal(item, m) for m in merged)
        )
        return merged
    # keyed: merge by element key, live order first, new elements appended
    merged = copy.deepcopy(live)
    index = {
        item.get(key): i
        for i, item in enumerate(merged)
        if isinstance(item, Mapping)
    }
    for item in applied:
        kval = item.get(key)
        if kval in index:
            target = merged[index[kval]]
            if isinstance(target, Mapping):
                merge_applied(target, item)
            else:
                merged[index[kval]] = copy.deepcopy(item)
        else:
            merged.append(copy.deepcopy(item))
            index[kval] = len(merged) - 1
    return merged


# ---------------------------------------------------------------------------
# managedFields entries (wire shape) <-> internal


class ApplyConflictError(ConflictError):
    """409 carrying the per-field conflict list, upstream-style."""

    def __init__(self, conflicts: list[tuple[str, str]]) -> None:
        self.conflicts = conflicts
        detail = ", ".join(
            f'conflict with "{mgr}": {field}' for mgr, field in conflicts
        )
        n = len(conflicts)
        super().__init__(
            f"Apply failed with {n} conflict{'s' if n != 1 else ''}: {detail}"
        )


def dotted_path(path: Path) -> str:
    """Render a path the way upstream conflict messages do:
    ``.spec.containers[name="a"].image``."""
    out = []
    for kind, token in path:
        if kind == _F:
            out.append(f".{token}")
        elif kind == _K:
            keyd = json.loads(token)
            inner = ",".join(f'{k}="{v}"' for k, v in sorted(keyd.items()))
            out.append(f"[{inner}]")
        else:
            out.append(f"[v={token}]")
    return "".join(out)


def _entry_leaves(entry: Mapping[str, Any]) -> set[Path]:
    return fields_v1_to_leaves(entry.get("fieldsV1") or {})


def _make_entry(
    manager: str,
    operation: str,
    api_version: str,
    leaves: Iterable[Path],
    now_iso: str,
    subresource: str = "",
) -> dict[str, Any]:
    entry: dict[str, Any] = {
        "manager": manager,
        "operation": operation,
        "apiVersion": api_version,
        "time": now_iso,
        "fieldsType": "FieldsV1",
        "fieldsV1": leaves_to_fields_v1(leaves),
    }
    if subresource:
        entry["subresource"] = subresource
    return entry


def server_side_apply(
    live: dict[str, Any],
    applied: Mapping[str, Any],
    manager: str,
    force: bool,
    now_iso: str,
) -> None:
    """Apply ``applied`` into ``live`` in place under ``manager``'s name.

    Implements the upstream contract: fields the manager declared last
    time but omits now are removed (unless co-owned); fields owned by
    another manager with a *different* value raise ApplyConflictError
    unless ``force`` (same value = shared ownership, no conflict —
    upstream: "multiple appliers can set the same value").
    """
    if not manager:
        raise BadRequestError("fieldManager is required for apply requests")
    if (applied.get("metadata") or {}).get("managedFields"):
        raise InvalidError(
            "metadata.managedFields must not be set in an apply request"
        )
    entries = (live.get("metadata") or {}).get("managedFields") or []
    api_version = str(
        applied.get("apiVersion") or live.get("apiVersion") or ""
    )
    applied_leaves = extract_leaves(applied)
    new_set = set(applied_leaves)

    old_self: set[Path] = set()
    others: list[tuple[dict[str, Any], set[Path]]] = []
    kept_entries: list[dict[str, Any]] = []
    for entry in entries:
        if (
            entry.get("manager") == manager
            and entry.get("operation") == "Apply"
            and not entry.get("subresource")
        ):
            old_self |= _entry_leaves(entry)
        else:
            others.append((entry, _entry_leaves(entry)))
            kept_entries.append(entry)

    # Conflicts: a leaf we declare, another manager owns, and the value
    # we want differs from what is live.
    conflicts: list[tuple[str, str]] = []
    conflicted: set[Path] = set()
    for path in sorted(new_set):
        want = applied_leaves[path]
        if path[-1][0] == _K and isinstance(want, Mapping):
            keyd = json.loads(path[-1][1])
            if set(want) <= set(keyd):
                # A key-only element ({"name": "a"}) declares the
                # element's presence, not its contents — shared element
                # ownership, never a value conflict (the live item
                # legitimately carries other managers' fields).
                continue
        found, have = value_at(live, path)
        if not found or _json_equal(want, have):
            continue
        for entry, leaves in others:
            if path in leaves:
                conflicts.append(
                    (str(entry.get("manager", "")), dotted_path(path))
                )
                conflicted.add(path)
    if conflicts and not force:
        raise ApplyConflictError(conflicts)

    # Removal: leaves we owned, no longer declare, and nobody else owns.
    foreign: set[Path] = set()
    for _, leaves in others:
        foreign |= leaves
    # Deepest-first and fully deterministic (never set-iteration order —
    # removal order within an element matters for the key-only collapse).
    for path in sorted(
        old_self - new_set - foreign, key=lambda p: (len(p), p), reverse=True
    ):
        remove_leaf(live, path)

    merge_applied(live, applied)

    # Forced takeover strips the conflicted leaves from their old owners.
    if conflicted and force:
        for entry, leaves in others:
            remaining = leaves - conflicted
            if remaining != leaves:
                entry["fieldsV1"] = leaves_to_fields_v1(remaining)
        kept_entries = [
            e for e in kept_entries if fields_v1_to_leaves(e.get("fieldsV1") or {})
        ]

    kept_entries.append(
        _make_entry(manager, "Apply", api_version, new_set, now_iso)
    )
    live.setdefault("metadata", {})["managedFields"] = kept_entries


def reassign_on_write(
    old: Mapping[str, Any],
    new: dict[str, Any],
    manager: str,
    now_iso: str,
    subresource: str = "",
) -> None:
    """After a non-apply write (update / merge / strategic / json patch):
    every changed or removed field leaves its previous owners' sets, and
    changed fields are recorded under the writer's Update entry — so the
    next apply by a displaced manager sees an honest conflict (the
    kubectl-scale-then-apply story).

    No-ops (leaving the object untracked) when the object has no
    managedFields and the writer declared no manager — the activation
    rule that keeps unmanaged clusters byte-identical to round-4 behavior.
    """
    entries = (old.get("metadata") or {}).get("managedFields")
    if not entries and not manager:
        new.get("metadata", {}).pop("managedFields", None)
        return
    manager = manager or UNKNOWN_MANAGER
    entries = copy.deepcopy(entries or [])
    old_leaves = extract_leaves(old)
    new_leaves = extract_leaves(new)
    changed = {
        p
        for p, v in new_leaves.items()
        if p not in old_leaves or not _json_equal(old_leaves[p], v)
    }
    removed = set(old_leaves) - set(new_leaves)
    touched = changed | removed
    api_version = str(new.get("apiVersion") or old.get("apiVersion") or "")

    kept: list[dict[str, Any]] = []
    writer_leaves: set[Path] = set()
    for entry in entries:
        if (
            entry.get("manager") == manager
            and entry.get("operation") == "Update"
            and entry.get("subresource", "") == subresource
        ):
            writer_leaves |= _entry_leaves(entry)
            continue
        remaining = _entry_leaves(entry) - touched
        if remaining:
            if remaining != _entry_leaves(entry):
                entry["fieldsV1"] = leaves_to_fields_v1(remaining)
                entry["time"] = entry.get("time") or now_iso
            kept.append(entry)
    writer_leaves = (writer_leaves - removed) | changed
    if writer_leaves:
        kept.append(
            _make_entry(
                manager, "Update", api_version, writer_leaves, now_iso,
                subresource=subresource,
            )
        )
    meta = new.setdefault("metadata", {})
    if kept:
        meta["managedFields"] = kept
    else:
        meta.pop("managedFields", None)
