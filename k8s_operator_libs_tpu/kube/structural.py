"""Structural-schema admission for custom resources — the apiserver's
prune → default → validate pipeline.

The reference's test strategy stands on envtest booting a REAL
kube-apiserver with the NodeMaintenance CRD installed
(`/root/reference/pkg/upgrade/upgrade_suit_test.go:87-89`), which means
every CR its suite writes is pruned against the CRD's structural
openAPIV3Schema, defaulted from it, and validated by it before storage.
`FakeCluster` replicates that admission here so a stored CRD activates
the same contract: unknown fields are pruned (unless
``x-kubernetes-preserve-unknown-fields``), ``default``s are applied into
existing objects, and violations answer 422 Invalid with apiserver-shaped
field paths.

Scope (the structural subset, mirroring
apiextensions-apiserver/pkg/apiserver/schema semantics):

* types ``object``/``array``/``string``/``integer``/``number``/
  ``boolean``; ``x-kubernetes-int-or-string``; ``nullable``
* ``properties`` / ``items`` / ``additionalProperties`` (schema or
  ``true``)
* ``required``, ``enum``, ``minimum``/``maximum`` (+ boolean
  ``exclusiveMinimum``/``exclusiveMaximum``), ``minLength``/
  ``maxLength``, ``pattern``, ``minItems``/``maxItems``,
  ``allOf``/``anyOf``/``oneOf``/``not``; ``uniqueItems: true`` is
  REJECTED at CRD admission like upstream apiextensions (the 422 a
  real apiserver answers — a CRD never gains non-upstream validation)
* ``format`` is accepted but not enforced (upstream treats most formats
  as annotations for CRDs; enforcing none is the closest uniform rule)

At the document root, ``apiVersion``/``kind``/``metadata`` are server
territory: never pruned, never validated by the CR schema (upstream
coerces metadata through ObjectMeta instead of the schema).
"""

from __future__ import annotations

import copy
import re
from typing import Any, Mapping, Optional

_ROOT_SERVER_KEYS = frozenset({"apiVersion", "kind", "metadata"})


def validate_crd_structural(crd_data: Mapping[str, Any]) -> list[str]:
    """apiextensions' structural-schema requirements for the CRD object
    itself — upstream rejects a v1 CRD whose declared schemas are not
    structural. Checked for EVERY version carrying a schema — served or
    not, like upstream (a version with NO schema stays admitted,
    matching this package's schema-less activation rule):

    * the root must be ``type: object``;
    * every specified field must declare a ``type`` (or be
      int-or-string / preserve-unknown-fields); an empty field schema
      is rejected, and a typeless node is tolerated only when its
      constraints live entirely in junctors;
    * ``items`` must be a single schema (upstream forbids the array
      form);
    * ``properties`` and ``additionalProperties`` are mutually
      exclusive on one node; ``additionalProperties`` must be ``true``
      or a schema (``false`` is upstream-invalid);
    * inside junctor subtrees (allOf/anyOf/oneOf/not), ``type``,
      ``additionalProperties``, and ``default`` are forbidden —
      upstream exempts junctors from the type REQUIREMENT but forbids
      those keywords there."""
    errors: list[str] = []
    for v in (crd_data.get("spec") or {}).get("versions") or []:
        raw = ((v.get("schema") or {}).get("openAPIV3Schema")) or None
        if not raw:
            continue
        version = v.get("name", "?")
        if raw.get("type") != "object":
            errors.append(
                f"spec.versions[{version}].schema.openAPIV3Schema.type: "
                "Required value: must be object"
            )
        _check_structural(raw, f"spec.versions[{version}].schema"
                               ".openAPIV3Schema", errors, root=True)
    return errors


_JUNCTORS = ("allOf", "anyOf", "oneOf", "not")


def _check_structural(
    node: Any, path: str, errors: list[str], root: bool = False
) -> None:
    if not isinstance(node, Mapping):
        return
    props = node.get("properties")
    addl = node.get("additionalProperties")
    items = node.get("items")
    if props is not None and addl is not None:
        errors.append(
            f"{path}: Forbidden: properties and additionalProperties "
            "are mutually exclusive"
        )
    if addl is False:
        errors.append(
            f"{path}.additionalProperties: Forbidden: must be true or "
            "a schema"
        )
    if isinstance(items, list):
        errors.append(
            f"{path}.items: Forbidden: must be a schema, not an array "
            "of schemas"
        )
        items = None
    if node.get("uniqueItems"):
        # apiextensions forbids uniqueItems: true ANYWHERE in a
        # structural schema (deep-equality dedup is O(n^2) server work
        # an admitted object could weaponize) — the CRD is 422'd at
        # admission, it does not gain non-upstream validation behavior.
        errors.append(
            f"{path}.uniqueItems: Forbidden: uniqueItems cannot be set "
            "to true"
        )
    typed = (
        node.get("type")
        or node.get("x-kubernetes-int-or-string")
        or node.get("x-kubernetes-preserve-unknown-fields")
    )
    has_core = (
        props is not None or addl is not None or items is not None
    )
    has_junctor = any(j in node for j in _JUNCTORS)
    if not typed and not root:
        if has_core:
            errors.append(f"{path}.type: Required value")
        elif not has_junctor:
            errors.append(
                f"{path}: Required value: must not be empty for "
                "specified fields"
            )
    if isinstance(props, Mapping):
        for key, sub in props.items():
            _check_structural(sub, f"{path}.properties[{key}]", errors)
    if isinstance(addl, Mapping):
        _check_structural(addl, f"{path}.additionalProperties", errors)
    if isinstance(items, Mapping):
        _check_structural(items, f"{path}.items", errors)
    # x-kubernetes-int-or-string carries upstream's one junctor-type
    # exception: its canonical anyOf [{type: integer}, {type: string}]
    # branches may name types.
    allow_type = bool(node.get("x-kubernetes-int-or-string"))
    for junctor in _JUNCTORS:
        subtree = node.get(junctor)
        branches = (
            [subtree] if isinstance(subtree, Mapping)
            else subtree if isinstance(subtree, list) else []
        )
        for i, branch in enumerate(branches):
            _check_junctor(
                branch, f"{path}.{junctor}[{i}]", errors,
                allow_type=allow_type,
            )


def _check_junctor(
    node: Any, path: str, errors: list[str], allow_type: bool = False
) -> None:
    """Inside allOf/anyOf/oneOf/not: value validations only — type,
    additionalProperties, and default are forbidden (apiextensions'
    junctor rules; ``allow_type`` covers the int-or-string
    exception)."""
    if not isinstance(node, Mapping):
        return
    forbidden_keys = ("additionalProperties", "default") if allow_type \
        else ("type", "additionalProperties", "default")
    for forbidden in forbidden_keys:
        if forbidden in node:
            errors.append(
                f"{path}.{forbidden}: Forbidden: must not be set "
                "inside allOf/anyOf/oneOf/not"
            )
    if node.get("uniqueItems"):
        # Forbidden in junctor subtrees too — upstream's rule is
        # schema-wide, not structure-subtree-only.
        errors.append(
            f"{path}.uniqueItems: Forbidden: uniqueItems cannot be set "
            "to true"
        )
    props = node.get("properties")
    if isinstance(props, Mapping):
        for key, sub in props.items():
            _check_junctor(sub, f"{path}.properties[{key}]", errors)
    items = node.get("items")
    if isinstance(items, Mapping):
        _check_junctor(items, f"{path}.items", errors)
    for junctor in _JUNCTORS:
        subtree = node.get(junctor)
        branches = (
            [subtree] if isinstance(subtree, Mapping)
            else subtree if isinstance(subtree, list) else []
        )
        for i, branch in enumerate(branches):
            # allow_type propagates through nested junctors: the other
            # canonical int-or-string wrap is allOf -> anyOf -> types.
            _check_junctor(branch, f"{path}.{junctor}[{i}]", errors,
                           allow_type=allow_type)


def error_root_field(error: str) -> str:
    """The root field segment of a validation error's path — the text
    before the first ``.``, ``[``, or ``:``. Used for exact-field
    filtering (a field named ``statusHistory`` is not ``status``)."""
    head = error.split(":", 1)[0]
    for sep in (".", "["):
        head = head.split(sep, 1)[0]
    return head.strip()


def schema_for_crd_version(
    crd_data: Mapping[str, Any], version: str
) -> Optional["StructuralSchema"]:
    """The version's openAPIV3Schema as a ``StructuralSchema``, or None
    when that version carries no schema (schema-less CRDs admit
    anything, like upstream with preserveUnknownFields)."""
    for v in (crd_data.get("spec") or {}).get("versions") or []:
        if v.get("name") != version:
            continue
        raw = ((v.get("schema") or {}).get("openAPIV3Schema")) or None
        return StructuralSchema(raw) if raw else None
    return None


class StructuralSchema:
    def __init__(self, root: Mapping[str, Any]) -> None:
        self.root = root

    # -- the admission pipeline -------------------------------------------
    def admit(self, data: dict[str, Any]) -> list[str]:
        """Prune, then default, then validate ``data`` in place —
        upstream's write-path order. Returns validation errors
        (empty = admitted)."""
        self.prune(data)
        self.apply_defaults(data)
        return self.validate(data)

    # The root is an ordinary object node EXCEPT that
    # apiVersion/kind/metadata are server territory: they are set aside
    # before each walk (so the schema can neither prune, default into,
    # nor validate them) and restored after. Everything else — including
    # root-level additionalProperties, enum, and combinators — goes
    # through the same node walkers as every nested level.

    # -- pruning -----------------------------------------------------------
    def prune(self, data: dict[str, Any]) -> None:
        """Drop fields the schema does not specify (the apiserver's
        field pruning). Root server-owned keys are untouched."""
        aside = {
            k: data.pop(k) for k in list(data) if k in _ROOT_SERVER_KEYS
        }
        try:
            _prune_value(data, self.root)
        finally:
            data.update(aside)

    # -- defaulting --------------------------------------------------------
    def apply_defaults(self, data: dict[str, Any]) -> None:
        aside = {
            k: data.pop(k) for k in list(data) if k in _ROOT_SERVER_KEYS
        }
        try:
            _default_value(data, self.root)
        finally:
            data.update(aside)

    # -- validation --------------------------------------------------------
    def validate(self, data: Mapping[str, Any]) -> list[str]:
        view = {
            k: v for k, v in data.items() if k not in _ROOT_SERVER_KEYS
        }
        errors: list[str] = []
        _validate_value(view, self.root, "", errors)
        # A schema demanding server keys (required: [metadata]) is not
        # the CR author's problem — those live outside the schema. Match
        # the error path's ROOT SEGMENT exactly: a field merely named
        # "kinds" or "metadataPolicy" must not be silently excused.
        return [
            e for e in errors
            if error_root_field(e) not in _ROOT_SERVER_KEYS
        ]


# ---------------------------------------------------------------------------
# Node-level walkers
# ---------------------------------------------------------------------------


def _prune_value(value: Any, schema: Mapping[str, Any]) -> None:
    if isinstance(value, dict):
        if schema.get("x-kubernetes-int-or-string"):
            return  # int-or-string holds scalars; leave malformed input
            # for validation to report rather than silently emptying it
        props = schema.get("properties") or {}
        addl = schema.get("additionalProperties")
        preserve = schema.get("x-kubernetes-preserve-unknown-fields")
        for key in list(value):
            if key in props:
                _prune_value(value[key], props[key])
            elif isinstance(addl, Mapping):
                _prune_value(value[key], addl)
            elif addl is True or preserve:
                continue
            else:
                del value[key]
    elif isinstance(value, list):
        items = schema.get("items")
        if isinstance(items, Mapping):
            for element in value:
                _prune_value(element, items)


def _default_value(value: Any, schema: Mapping[str, Any]) -> None:
    if isinstance(value, dict):
        props = schema.get("properties") or {}
        for key, sub in props.items():
            if key not in value and "default" in sub:
                value[key] = copy.deepcopy(sub["default"])
            if key in value:
                _default_value(value[key], sub)
        addl = schema.get("additionalProperties")
        if isinstance(addl, Mapping):
            for key, element in value.items():
                if key not in props:
                    _default_value(element, addl)
    elif isinstance(value, list):
        items = schema.get("items")
        if isinstance(items, Mapping):
            for element in value:
                _default_value(element, items)


def _type_ok(value: Any, type_name: str) -> bool:
    if type_name == "object":
        return isinstance(value, dict)
    if type_name == "array":
        return isinstance(value, list)
    if type_name == "string":
        return isinstance(value, str)
    if type_name == "boolean":
        return isinstance(value, bool)
    if type_name == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if type_name == "number":
        return (
            isinstance(value, (int, float)) and not isinstance(value, bool)
        )
    return True  # unknown type names admit (upstream rejects at CRD
    # creation; a stored schema never carries one)


def _fmt(value: Any) -> str:
    return repr(value) if not isinstance(value, str) else f'"{value}"'


def _child(path: str, key: str) -> str:
    return f"{path}.{key}" if path else key


def _validate_value(
    value: Any,
    schema: Mapping[str, Any],
    path: str,
    errors: list[str],
) -> None:
    label = path or "<root>"
    if value is None:
        if not schema.get("nullable"):
            errors.append(f"{label}: Invalid value: null")
        return
    if schema.get("x-kubernetes-int-or-string"):
        if not (
            isinstance(value, str)
            or (isinstance(value, int) and not isinstance(value, bool))
        ):
            errors.append(
                f"{label}: Invalid value: {_fmt(value)}: "
                "expected integer or string"
            )
            return
    else:
        type_name = schema.get("type", "")
        if type_name and not _type_ok(value, type_name):
            errors.append(
                f"{label}: Invalid value: {_fmt(value)}: "
                f"expected {type_name}"
            )
            return

    if "enum" in schema and value not in schema["enum"]:
        allowed = ", ".join(_fmt(v) for v in schema["enum"])
        errors.append(
            f"{label}: Unsupported value: {_fmt(value)}: "
            f"supported values: {allowed}"
        )

    if isinstance(value, (int, float)) and not isinstance(value, bool):
        minimum = schema.get("minimum")
        if minimum is not None:
            if schema.get("exclusiveMinimum"):
                if value <= minimum:
                    errors.append(
                        f"{label}: Invalid value: {value}: must be greater "
                        f"than {minimum}"
                    )
            elif value < minimum:
                errors.append(
                    f"{label}: Invalid value: {value}: must be greater than "
                    f"or equal to {minimum}"
                )
        maximum = schema.get("maximum")
        if maximum is not None:
            if schema.get("exclusiveMaximum"):
                if value >= maximum:
                    errors.append(
                        f"{label}: Invalid value: {value}: must be less "
                        f"than {maximum}"
                    )
            elif value > maximum:
                errors.append(
                    f"{label}: Invalid value: {value}: must be less than "
                    f"or equal to {maximum}"
                )

    if isinstance(value, str):
        min_len = schema.get("minLength")
        if min_len is not None and len(value) < min_len:
            errors.append(
                f"{label}: Invalid value: {_fmt(value)}: must be at least "
                f"{min_len} chars long"
            )
        max_len = schema.get("maxLength")
        if max_len is not None and len(value) > max_len:
            errors.append(
                f"{label}: Invalid value: {_fmt(value)}: may not be longer "
                f"than {max_len}"
            )
        pattern = schema.get("pattern")
        if pattern is not None and re.search(pattern, value) is None:
            errors.append(
                f"{label}: Invalid value: {_fmt(value)}: must match "
                f"pattern {pattern}"
            )

    if isinstance(value, list):
        min_items = schema.get("minItems")
        if min_items is not None and len(value) < min_items:
            errors.append(
                f"{label}: Invalid value: must have at least {min_items} "
                "items"
            )
        max_items = schema.get("maxItems")
        if max_items is not None and len(value) > max_items:
            errors.append(
                f"{label}: Invalid value: must have at most {max_items} "
                "items"
            )
        if schema.get("uniqueItems"):
            seen: list[Any] = []
            for element in value:
                if element in seen:
                    errors.append(
                        f"{label}: Duplicate value: {_fmt(element)}"
                    )
                    break
                seen.append(element)
        items = schema.get("items")
        if isinstance(items, Mapping):
            for i, element in enumerate(value):
                _validate_value(element, items, f"{path}[{i}]", errors)

    if isinstance(value, dict) and not schema.get("x-kubernetes-int-or-string"):
        props = schema.get("properties") or {}
        for key in schema.get("required") or []:
            if key not in value:
                errors.append(f"{_child(path, key)}: Required value")
        addl = schema.get("additionalProperties")
        for key, element in value.items():
            if key in props:
                _validate_value(element, props[key], _child(path, key), errors)
            elif isinstance(addl, Mapping):
                _validate_value(element, addl, _child(path, key), errors)

    # Value-validation combinators (structural schemas restrict these to
    # validation-only subtrees; we evaluate them as predicates).
    for sub in schema.get("allOf") or []:
        _validate_value(value, sub, path, errors)
    any_of = schema.get("anyOf")
    if any_of:
        if not any(_passes(value, sub, path) for sub in any_of):
            errors.append(
                f"{label}: Invalid value: {_fmt(value)}: must validate "
                "against at least one schema (anyOf)"
            )
    one_of = schema.get("oneOf")
    if one_of:
        matches = sum(1 for sub in one_of if _passes(value, sub, path))
        if matches != 1:
            errors.append(
                f"{label}: Invalid value: {_fmt(value)}: must validate "
                f"against exactly one schema (oneOf), matched {matches}"
            )
    if "not" in schema and _passes(value, schema["not"], path):
        errors.append(
            f"{label}: Invalid value: {_fmt(value)}: must not validate "
            "against the schema (not)"
        )


def _passes(value: Any, schema: Mapping[str, Any], path: str) -> bool:
    probe: list[str] = []
    _validate_value(value, schema, path, probe)
    return not probe
