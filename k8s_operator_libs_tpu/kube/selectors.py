"""Kubernetes label-selector semantics (string form and matchLabels form).

The reference leans on apimachinery's labels.Parse for drain pod selectors,
validation pod selectors and DaemonSet selectors (reference:
pkg/upgrade/validation_manager.go:71-116, pod_manager.go:122-229). This module
implements the subset of the grammar those paths use: equality (``=``, ``==``,
``!=``), set ops (``in``, ``notin``), existence (``key``, ``!key``), and
comma-joined conjunction.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Mapping

from .jsonpath import dotted_value


class SelectorError(ValueError):
    pass


@dataclass(frozen=True)
class Requirement:
    key: str
    op: str  # "=", "!=", "in", "notin", "exists", "!"
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        present = self.key in labels
        value = labels.get(self.key)
        if self.op == "=":
            return present and value == self.values[0]
        if self.op == "!=":
            # apimachinery: NotEquals also matches when the key is absent.
            return not present or value != self.values[0]
        if self.op == "in":
            return present and value in self.values
        if self.op == "notin":
            return not present or value not in self.values
        if self.op == "exists":
            return present
        if self.op == "!":
            return not present
        raise SelectorError(f"unknown operator {self.op!r}")


@dataclass(frozen=True)
class LabelSelector:
    """A conjunction of label requirements."""

    requirements: tuple[Requirement, ...] = field(default_factory=tuple)

    def matches(self, labels: Mapping[str, str] | None) -> bool:
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    @property
    def empty(self) -> bool:
        return not self.requirements

    @staticmethod
    def from_match_labels(match_labels: Mapping[str, str] | None) -> "LabelSelector":
        """Build from a LabelSelector.matchLabels map (used for DaemonSet
        selectors, reference: pkg/upgrade/common_manager.go:168-187)."""
        reqs = tuple(
            Requirement(key=k, op="=", values=(v,))
            for k, v in sorted((match_labels or {}).items())
        )
        return LabelSelector(requirements=reqs)


_SET_RE = re.compile(
    r"^\s*(?P<key>[^\s!=,()]+)\s+(?P<op>in|notin)\s+\((?P<vals>[^)]*)\)\s*$"
)

# Qualified label key: optional dns-ish prefix, then a name segment.
_KEY_RE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_./]*[A-Za-z0-9])?$")


def _validate_key(key: str, term: str) -> str:
    if not _KEY_RE.match(key):
        raise SelectorError(f"invalid label key {key!r} in selector term {term!r}")
    return key


def _split_top_level(expr: str) -> list[str]:
    """Split on commas not inside parentheses."""
    parts: list[str] = []
    depth = 0
    current: list[str] = []
    for ch in expr:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            parts.append("".join(current))
            current = []
        else:
            current.append(ch)
    parts.append("".join(current))
    return [p for p in (s.strip() for s in parts) if p]


def _parse_requirement(term: str) -> Requirement:
    m = _SET_RE.match(term)
    if m:
        vals = tuple(v.strip() for v in m.group("vals").split(",") if v.strip())
        if not vals:
            raise SelectorError(f"empty value set in {term!r}")
        return Requirement(
            key=_validate_key(m.group("key"), term), op=m.group("op"), values=vals
        )
    if "!=" in term:
        key, _, val = term.partition("!=")
        return Requirement(key=_validate_key(key.strip(), term), op="!=", values=(val.strip(),))
    if "==" in term:
        key, _, val = term.partition("==")
        return Requirement(key=_validate_key(key.strip(), term), op="=", values=(val.strip(),))
    if "=" in term:
        key, _, val = term.partition("=")
        return Requirement(key=_validate_key(key.strip(), term), op="=", values=(val.strip(),))
    if term.startswith("!"):
        key = term[1:].strip()
        if not key:
            raise SelectorError("empty key in existence requirement")
        return Requirement(key=_validate_key(key, term), op="!")
    return Requirement(key=_validate_key(term.strip(), term), op="exists")


def parse_selector(selector: str | None) -> LabelSelector:
    """Parse a label-selector string; empty/None selects everything."""
    if not selector or not selector.strip():
        return LabelSelector()
    reqs = tuple(_parse_requirement(t) for t in _split_top_level(selector))
    return LabelSelector(requirements=reqs)


@dataclass(frozen=True)
class FieldRequirement:
    """One field-selector term. apimachinery's fields.Selector grammar:
    ``=``/``==`` (equality) and ``!=`` (inequality) — comparison is on
    the field's STRING form, with an absent field reading as ``""``
    (the real apiserver's behavior for e.g. an unscheduled pod's
    ``spec.nodeName``)."""

    key: str
    op: str  # "=" or "!="
    value: str

    def matches(self, data: Mapping) -> bool:
        actual = dotted_value(data, self.key)
        actual_s = "" if actual is None else str(actual)
        if self.op == "=":
            return actual_s == self.value
        return actual_s != self.value


@dataclass(frozen=True)
class FieldSelector:
    """A conjunction of field requirements, evaluated server-side on
    list/watch scopes (kube/fake.py, the HTTP apiserver's watch
    streams) and client-side by the cached client — one matcher, so the
    two can never disagree."""

    requirements: tuple[FieldRequirement, ...] = field(default_factory=tuple)

    @property
    def empty(self) -> bool:
        return not self.requirements

    def matches(self, data: Mapping | None) -> bool:
        data = data or {}
        return all(r.matches(data) for r in self.requirements)


def parse_field_selector(selector: str | None) -> FieldSelector:
    """Parse a field selector like ``spec.nodeName=node-1`` (comma-joined
    conjunction; ``=``, ``==`` and ``!=`` terms — the apimachinery
    fields.Selector grammar subset). Empty/None selects everything."""
    if not selector or not selector.strip():
        return FieldSelector()
    reqs: list[FieldRequirement] = []
    for term in selector.split(","):
        term = term.strip()
        if not term:
            continue
        if "!=" in term:
            key, _, val = term.partition("!=")
            op = "!="
        elif "=" in term:
            key, _, val = (
                term.partition("==") if "==" in term else term.partition("=")
            )
            op = "="
        else:
            raise SelectorError(f"unsupported field selector term {term!r}")
        key = key.strip()
        if not key:
            raise SelectorError(f"empty key in field selector term {term!r}")
        reqs.append(FieldRequirement(key=key, op=op, value=val.strip()))
    return FieldSelector(requirements=tuple(reqs))
