"""An in-memory Kubernetes apiserver for tests and simulation.

Plays the role envtest plays in the reference's suites — a real apiserver+etcd
booted locally with no kubelets, against which Node/Pod/CR objects are plain
API objects (reference: upgrade_suit_test.go:87-93, §4 of SURVEY.md). This
implementation keeps the apiserver *semantics* the framework depends on:

* monotonic resourceVersion, bumped on every write,
* optimistic concurrency (Conflict on stale resourceVersion for updates),
* RFC 7386 merge patch with ``null`` deleting keys,
* finalizers: delete marks ``deletionTimestamp`` and the object lingers until
  finalizers are cleared (the reference's suites strip NodeMaintenance
  finalizers in cleanup, upgrade_state_test.go:1797-1813),
* label/field selector list filtering,
* watch events for cache emulation,
* injectable reactors for fault injection (client-go fake style).
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Mapping, Optional

from .client import (
    AlreadyExistsError,
    BadRequestError,
    Client,
    ConflictError,
    InvalidError,
    ListDelta,
    NotFoundError,
    UnsupportedMediaTypeError,
    WatchExpiredError,
)
from .objects import (
    KINDS,
    deep_copy_json,
    CustomResourceDefinition,
    KubeObject,
    rfc3339_now,
    wrap,
)
from .resources import resource_for_kind
from .selectors import LabelSelector, parse_field_selector, parse_selector
from ..utils import tracing
from .ssa import reassign_on_write, server_side_apply
from .structural import (
    error_root_field,
    schema_for_crd_version,
    validate_crd_structural,
)

#: reactor signature: (verb, kind, payload) -> None; raise to inject a failure.
Reactor = Callable[[str, str, dict[str, Any]], None]

#: uid generation: ``uuid.uuid4`` reads ``os.urandom`` on every call, a
#: measurable per-create cost on the pod-churn hot path (the simulated
#: kubelet recreates one driver pod per node per roll). A process-local
#: PRNG seeded once from urandom keeps uids RFC 4122 v4-shaped and
#: unique-in-practice at ``getrandbits`` speed.
_UID_RNG = random.Random(int.from_bytes(os.urandom(16), "big"))


def _new_uid() -> str:
    return str(uuid.UUID(int=_UID_RNG.getrandbits(128), version=4))


_WATCH_ADDED = "ADDED"
_WATCH_MODIFIED = "MODIFIED"
_WATCH_DELETED = "DELETED"


def merge_patch(target: dict[str, Any], patch: Mapping[str, Any]) -> dict[str, Any]:
    """Apply an RFC 7386 JSON merge patch in place; null values delete keys.

    This is the write primitive the whole state machine rides on — label and
    annotation writes are merge patches with ``null`` used for key deletion
    (reference: pkg/upgrade/node_upgrade_state_provider.go:80-82, 147-150).
    """
    for key, value in patch.items():
        if value is None:
            target.pop(key, None)
        elif isinstance(value, Mapping):
            existing = target.get(key)
            if not isinstance(existing, dict):
                existing = {}
                target[key] = existing
            merge_patch(existing, value)
        else:
            target[key] = deep_copy_json(value)
    return target


#: Field-name-keyed mirror of the ``patchStrategy:"merge"`` /
#: ``patchMergeKey:"..."`` struct tags in k8s.io/api. apimachinery resolves
#: these per Go type; schema-less, this engine keys them by FIELD NAME —
#: Kubernetes API conventions keep the names consistent across types, and
#: the one ambiguous name (``ports``: ContainerPort keys by containerPort,
#: ServicePort by port) carries every upstream candidate, resolved against
#: the elements actually present. Fields without a ``patchStrategy`` tag
#: upstream (e.g. tolerations, args) are deliberately absent: they stay
#: atomic/replace here too. ``name`` remains the universal fallback key —
#: it is the K8s default merge key and the convention CRDs follow.
_LIST_MERGE_KEYS: dict[str, tuple[str, ...]] = {
    "containers": ("name",),
    "initContainers": ("name",),
    "ephemeralContainers": ("name",),
    "env": ("name",),
    "ports": ("containerPort", "port"),
    "volumes": ("name",),
    "volumeMounts": ("mountPath",),
    "volumeDevices": ("devicePath",),
    "imagePullSecrets": ("name",),
    "secrets": ("name",),
    "resourceClaims": ("name",),
    "hostAliases": ("ip",),
    "podIPs": ("ip",),
    "hostIPs": ("ip",),
    "taints": ("key",),
    "conditions": ("type",),
    "addresses": ("type",),
    "ownerReferences": ("uid",),
    "topologySpreadConstraints": ("topologyKey",),
}

#: ``patchStrategy:"merge"`` on PRIMITIVE lists (``[]string``) upstream:
#: ObjectMeta.finalizers, NodeStatus.volumesInUse. Patch values union into
#: the live list (live order first, new values in patch order); removals
#: go through ``$deleteFromPrimitiveList/<field>``.
_PRIMITIVE_MERGE_FIELDS = frozenset({"finalizers", "volumesInUse"})


def strategic_merge_patch(
    target: dict[str, Any], patch: Mapping[str, Any]
) -> dict[str, Any]:
    """Apply a Kubernetes strategic-merge patch in place.

    The reference writes the node state label with a *strategic* merge
    patch (node_upgrade_state_provider.go:80-82) while annotations go via
    RFC 7386 merge patch (:147-150); this fake supports both content types
    so the distinction is honest rather than papered over. For the map
    fields this library patches (labels/annotations), the two are
    equivalent — ``tests/test_patch_semantics.py`` pins that equivalence.

    Supported strategic semantics:

    * maps merge recursively; ``null`` deletes a key (same as merge patch),
    * a map containing ``{"$patch": "replace"}`` replaces wholesale; a map
      value of ``{"$patch": "delete"}`` deletes the key,
    * ``$retainKeys: [k...]`` on a map drops every key of the merged
      result not in the list (apimachinery's retainKeys strategy),
    * lists of objects merge by the upstream merge key — resolved from
      ``_LIST_MERGE_KEYS`` by field name, ``name`` as the fallback; an
      item ``{"$patch": "delete", <key>: x}`` removes the matching
      element, and any ``{"$patch": "replace"}`` element makes the
      remaining items replace the list wholesale (apimachinery's
      mergeSliceWithSpecialElements),
    * ``$setElementOrder/<field>: [...]`` reorders the merged list: listed
      elements take the directive's order, server-only elements keep their
      relative position by live index (apimachinery's normalizeElementOrder),
    * merge-strategy primitive lists (``_PRIMITIVE_MERGE_FIELDS``) union;
      ``$deleteFromPrimitiveList/<field>: [v...]`` removes values,
    * other primitive lists are replaced (the K8s atomic default).

    Remaining deviations from apimachinery (PARITY.md, pinned by
    tests/test_conformance_vectors.py): merge keys resolve by field name
    rather than by typed schema, and invalid patches apimachinery rejects
    (e.g. a ``$setElementOrder`` list omitting a patched element) apply
    leniently instead of erroring.
    """
    orders: dict[str, list[Any]] = {}
    live_before: dict[str, Any] = {}
    for key, value in patch.items():
        if key.startswith("$setElementOrder/") and isinstance(value, list):
            field_name = key.split("/", 1)[1]
            orders[field_name] = value
            live_before[field_name] = deep_copy_json(target.get(field_name))
    for key, value in patch.items():
        if key in ("$patch", "$retainKeys"):
            continue
        if key.startswith("$setElementOrder/"):
            continue
        if key.startswith("$deleteFromPrimitiveList/"):
            field_name = key.split("/", 1)[1]
            current = target.get(field_name)
            if isinstance(current, list) and isinstance(value, list):
                target[field_name] = [v for v in current if v not in value]
            continue
        if value is None:
            target.pop(key, None)
        elif isinstance(value, Mapping):
            directive = value.get("$patch")
            if directive == "delete":
                target.pop(key, None)
                continue
            if directive == "replace":
                target[key] = _strip_directives(value)
                continue
            existing = target.get(key)
            if not isinstance(existing, dict):
                existing = {}
                target[key] = existing
            strategic_merge_patch(existing, value)
        elif isinstance(value, list):
            merged_list = _strategic_merge_list(key, target.get(key), value)
            # Pure-directive patches ($patch:delete of absent elements)
            # must not conjure the key into existence — a real apiserver
            # treats them as a no-op. An explicit empty list still sets.
            if key not in target and not merged_list and value:
                continue
            target[key] = merged_list
        else:
            target[key] = deep_copy_json(value)
    for field_name, order in orders.items():
        current = target.get(field_name)
        if isinstance(current, list):
            target[field_name] = _reorder_list(
                field_name, current, order, live_before.get(field_name)
            )
    retain = patch.get("$retainKeys")
    if isinstance(retain, list):
        for key in list(target):
            if key not in retain:
                target.pop(key)
    return target


def _is_directive_key(key: Any) -> bool:
    return isinstance(key, str) and (
        key in ("$patch", "$retainKeys")
        or key.startswith(("$setElementOrder/", "$deleteFromPrimitiveList/"))
    )


def _strip_directives(item: Mapping[str, Any]) -> dict[str, Any]:
    """Deep-copy a patch element minus directive keys — directives are
    instructions to the merge, never data a real apiserver persists."""
    return {
        k: deep_copy_json(v) for k, v in item.items() if not _is_directive_key(k)
    }


def _merge_key_for(
    field: str, current_items: list[Any], patch_items: list[Any]
) -> Optional[str]:
    """Pick the upstream merge key for a list field, or None for the
    atomic/replace strategy. Every element on both sides must carry the
    key — mirroring apimachinery, which errors on keyless elements (we
    fall back to replace instead of erroring)."""
    pool = list(current_items) + list(patch_items)
    if not pool:
        return "name"
    if not all(isinstance(i, Mapping) for i in pool):
        return None
    for key in _LIST_MERGE_KEYS.get(field, ()) + ("name",):
        if all(key in i for i in pool):
            return key
    return None


def _strategic_merge_list(
    field: str, current: Any, patch_items: list[Any]
) -> list[Any]:
    if any(
        isinstance(i, Mapping) and i.get("$patch") == "replace"
        for i in patch_items
    ):
        # apimachinery (mergeSliceWithSpecialElements): ANY element
        # carrying {"$patch": "replace"} makes the remaining items replace
        # the list wholesale; directive elements themselves are dropped.
        result: list[Any] = []
        for i in patch_items:
            if isinstance(i, Mapping):
                if i.get("$patch") == "delete":
                    continue
                stripped = _strip_directives(i)
                if stripped or "$patch" not in i:
                    result.append(stripped)
            else:
                result.append(deep_copy_json(i))
        return result
    cur_list = current if isinstance(current, list) else []
    if field in _PRIMITIVE_MERGE_FIELDS and all(
        not isinstance(i, Mapping)
        for i in itertools.chain(cur_list, patch_items)
    ):
        merged = [deep_copy_json(v) for v in cur_list]
        for v in patch_items:
            if v not in merged:
                merged.append(deep_copy_json(v))
        return merged
    key = _merge_key_for(field, cur_list, patch_items)
    if key is None or (current is not None and not isinstance(current, list)):
        # Replace strategy — but directives are instructions, not data: a
        # $patch:delete of an absent element is a no-op on a real
        # apiserver, never a stored phantom object, and directive keys
        # are never persisted.
        return [
            _strip_directives(i) if isinstance(i, Mapping) else deep_copy_json(i)
            for i in patch_items
            if not (isinstance(i, Mapping) and i.get("$patch") == "delete")
        ]
    merged = [deep_copy_json(i) for i in cur_list]
    index = {item[key]: pos for pos, item in enumerate(merged)}
    for item in patch_items:
        kval = item[key]
        directive = item.get("$patch")
        if directive == "delete":
            if kval in index:
                merged = [m for m in merged if m[key] != kval]
                index = {m[key]: pos for pos, m in enumerate(merged)}
            continue
        if kval in index:
            strategic_merge_patch(merged[index[kval]], item)
        else:
            # Appending still interprets the element AS a patch (against
            # nothing) so nested directives are consumed, never stored.
            fresh: dict[str, Any] = {}
            strategic_merge_patch(fresh, item)
            merged.append(fresh)
            index[kval] = len(merged) - 1
    return merged


def _reorder_list(
    field: str, merged: list[Any], order: list[Any], live_before: Any
) -> list[Any]:
    """Apply a ``$setElementOrder/<field>`` directive to the merged list.

    apimachinery's normalizeElementOrder: elements named by the directive
    take the directive's order; elements the patch never mentioned
    ("server-only") keep their relative order and slot in by comparing
    live-list indexes against the directive elements. Elements in neither
    the directive nor the live list (lenient here, an error upstream)
    append at the end.
    """
    if not order or not merged:
        return merged
    if all(isinstance(o, Mapping) for o in order):
        key = None
        for cand in _LIST_MERGE_KEYS.get(field, ()) + ("name",):
            if all(cand in o for o in order) and all(
                isinstance(m, Mapping) and cand in m for m in merged
            ):
                key = cand
                break
        if key is None:
            return merged

        def keyfn(item: Any) -> Any:
            return item.get(key) if isinstance(item, Mapping) else None

    else:

        def keyfn(item: Any) -> Any:
            return None if isinstance(item, Mapping) else item

    try:
        pos_in_order: dict[Any, int] = {}
        for i, o in enumerate(order):
            pos_in_order.setdefault(keyfn(o), i)
        live = live_before if isinstance(live_before, list) else []
        live_idx: dict[Any, int] = {}
        for i, item in enumerate(live):
            live_idx.setdefault(keyfn(item), i)
        ordered = sorted(
            (m for m in merged if keyfn(m) in pos_in_order),
            key=lambda m: pos_in_order[keyfn(m)],
        )
        server_only = [m for m in merged if keyfn(m) not in pos_in_order]
    except TypeError:
        # Unhashable keys — leave the merge result's order untouched.
        return merged
    inf = float("inf")
    result: list[Any] = []
    i = j = 0
    while i < len(server_only) and j < len(ordered):
        s_idx = live_idx.get(keyfn(server_only[i]), inf)
        p_idx = live_idx.get(keyfn(ordered[j]), inf)
        if s_idx < p_idx:
            result.append(server_only[i])
            i += 1
        else:
            result.append(ordered[j])
            j += 1
    result.extend(server_only[i:])
    result.extend(ordered[j:])
    return result


#: API groups whose types carry strategic-merge struct tags upstream —
#: i.e. the groups LocalApiServer/FakeCluster store as built-ins. Every
#: other group is CRD-backed and (like a real apiserver) answers 415 to a
#: strategic-merge-patch content type.
_STRATEGIC_GROUPS = frozenset(
    {"", "apps", "apiextensions.k8s.io", "coordination.k8s.io"}
)


def _supports_strategic(data: Mapping[str, Any]) -> bool:
    api_version = data.get("apiVersion") or ""
    group = api_version.rsplit("/", 1)[0] if "/" in api_version else ""
    return group in _STRATEGIC_GROUPS


def _json_pointer_tokens(pointer: str) -> list[str]:
    """RFC 6901: split and unescape a JSON Pointer (``~1`` → ``/``, then
    ``~0`` → ``~``; that order, or ``~01`` would wrongly become ``/``)."""
    if pointer == "":
        return []
    if not pointer.startswith("/"):
        raise BadRequestError(
            f"json patch pointer must start with '/': {pointer!r}"
        )
    return [
        t.replace("~1", "/").replace("~0", "~")
        for t in pointer.split("/")[1:]
    ]


def _jp_index(tok: str, pointer: str, length: int, allow_append: bool) -> int:
    if tok == "-" and allow_append:
        return length
    # RFC 6901 array index: "0" or digits with no leading zero, no sign.
    if not (tok == "0" or (tok.isdigit() and not tok.startswith("0"))):
        raise InvalidError(
            f"json patch path {pointer!r}: invalid array index {tok!r}"
        )
    idx = int(tok)
    limit = length + 1 if allow_append else length
    if idx >= limit:
        raise InvalidError(
            f"json patch path {pointer!r}: index {idx} out of bounds "
            f"for array of length {length}"
        )
    return idx


def _jp_step(cur: Any, tok: str, pointer: str) -> Any:
    if isinstance(cur, Mapping):
        if tok not in cur:
            raise InvalidError(
                f"json patch path {pointer!r} does not exist"
            )
        return cur[tok]
    if isinstance(cur, list):
        return cur[_jp_index(tok, pointer, len(cur), allow_append=False)]
    raise InvalidError(
        f"json patch path {pointer!r} traverses a non-container value"
    )


def _jp_get(doc: Any, pointer: str) -> Any:
    cur = doc
    for tok in _json_pointer_tokens(pointer):
        cur = _jp_step(cur, tok, pointer)
    return cur


def _jp_parent(doc: Any, tokens: list[str], pointer: str) -> tuple[Any, str]:
    """Walk to the container holding the final token (which must exist
    per RFC 6902 for every op — only the *final* location may be new)."""
    cur = doc
    for tok in tokens[:-1]:
        cur = _jp_step(cur, tok, pointer)
    return cur, tokens[-1]


def _jp_root_replace(doc: dict[str, Any], value: Any) -> None:
    if not isinstance(value, Mapping):
        raise InvalidError(
            "json patch cannot replace the document root with a non-object"
        )
    doc.clear()
    doc.update(deep_copy_json(value))


def _jp_add(
    doc: dict[str, Any], pointer: str, value: Any, copy_value: bool = True
) -> None:
    # copy_value=False is for values the caller already exclusively owns
    # (a just-removed ``move`` source) — skips a redundant deepcopy.
    tokens = _json_pointer_tokens(pointer)
    if not tokens:
        _jp_root_replace(doc, value)
        return
    parent, last = _jp_parent(doc, tokens, pointer)
    if copy_value:
        value = deep_copy_json(value)
    if isinstance(parent, Mapping):
        parent[last] = value  # type: ignore[index]
    elif isinstance(parent, list):
        idx = _jp_index(last, pointer, len(parent), allow_append=True)
        parent.insert(idx, value)
    else:
        raise InvalidError(
            f"json patch path {pointer!r}: parent is not a container"
        )


def _jp_remove(doc: dict[str, Any], pointer: str) -> Any:
    tokens = _json_pointer_tokens(pointer)
    if not tokens:
        raise InvalidError("json patch cannot remove the document root")
    parent, last = _jp_parent(doc, tokens, pointer)
    if isinstance(parent, Mapping):
        if last not in parent:
            raise InvalidError(
                f"json patch path {pointer!r} does not exist"
            )
        return parent.pop(last)  # type: ignore[attr-defined]
    if isinstance(parent, list):
        return parent.pop(_jp_index(last, pointer, len(parent), False))
    raise InvalidError(
        f"json patch path {pointer!r}: parent is not a container"
    )


def _json_equal(a: Any, b: Any) -> bool:
    """Deep equality with JSON semantics: bool is its own type (Python's
    ``True == 1`` must not make a ``test`` op pass)."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, Mapping) and isinstance(b, Mapping):
        return a.keys() == b.keys() and all(
            _json_equal(a[k], b[k]) for k in a
        )
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(map(_json_equal, a, b))
    return a == b


def _jp_op_touches_spec(op: Any) -> bool:
    """Whether a JSON-patch op can change ``/spec``: its path (or, for
    ``move``, its source) is the root, ``/spec`` itself, or under it.
    (``copy`` *from* spec reads it without changing it.)"""
    if not isinstance(op, Mapping):
        return False
    pointers = [str(op.get("path", ""))]
    if op.get("op") == "move":
        pointers.append(str(op.get("from", "")))
    return any(
        p == "" or p == "/spec" or p.startswith("/spec/") for p in pointers
    )


def json_patch(target: dict[str, Any], ops: Any) -> dict[str, Any]:
    """Apply an RFC 6902 JSON patch in place (``application/json-patch+json``,
    client-go's types.JSONPatchType — the third patch flavor the real
    apiserver accepts alongside merge and strategic).

    Error mapping mirrors apiserver/pkg/endpoints/handlers/patch.go: a
    malformed patch *document* (not an array, op not an object, unknown op,
    missing value/from, bad pointer syntax) answers 400 BadRequest; an
    *inapplicable* operation (missing path, index out of bounds, failed
    ``test``) answers 422 Invalid/UnprocessableEntity.

    Atomic per RFC 6902: ops apply to a working copy, and ``target`` is
    only updated (in place) once every op succeeded — a failure mid-array
    leaves ``target`` untouched.
    """
    if not isinstance(ops, list):
        raise BadRequestError("json patch must be an array of operations")
    work = deep_copy_json(target)
    for i, op in enumerate(ops):
        if not isinstance(op, Mapping) or not isinstance(op.get("op"), str):
            raise BadRequestError(
                f"json patch operation {i} is not an object with an 'op'"
            )
        name = op["op"]
        pointer = op.get("path")
        if not isinstance(pointer, str):
            raise BadRequestError(
                f"json patch operation {i} ({name}) has no 'path'"
            )
        if name in ("add", "replace", "test") and "value" not in op:
            raise BadRequestError(
                f"json patch operation {i} ({name}) has no 'value'"
            )
        if name in ("move", "copy") and not isinstance(op.get("from"), str):
            raise BadRequestError(
                f"json patch operation {i} ({name}) has no 'from'"
            )
        if name == "add":
            _jp_add(work, pointer, op["value"])
        elif name == "remove":
            _jp_remove(work, pointer)
        elif name == "replace":
            _jp_get(work, pointer)  # must exist (RFC 6902 §4.3)
            if not _json_pointer_tokens(pointer):
                _jp_root_replace(work, op["value"])
            else:
                _jp_remove(work, pointer)
                _jp_add(work, pointer, op["value"])
        elif name == "move":
            src = op["from"]
            src_tokens = _json_pointer_tokens(src)
            dst_tokens = _json_pointer_tokens(pointer)
            if (
                len(src_tokens) < len(dst_tokens)
                and dst_tokens[: len(src_tokens)] == src_tokens
            ):
                raise InvalidError(
                    f"json patch cannot move {src!r} into its own child "
                    f"{pointer!r}"
                )
            moved = _jp_remove(work, src)
            _jp_add(work, pointer, moved, copy_value=False)
        elif name == "copy":
            _jp_add(work, pointer, _jp_get(work, op["from"]))
        elif name == "test":
            actual = _jp_get(work, pointer)
            if not _json_equal(actual, op["value"]):
                raise InvalidError(
                    f"json patch test failed at {pointer!r}: "
                    f"expected {op['value']!r}, found {actual!r}"
                )
        else:
            raise BadRequestError(
                f"json patch operation {i}: unknown op {name!r}"
            )
    target.clear()
    target.update(work)
    return target


def classify_watch_event(
    event_type: str,
    data: Mapping[str, Any],
    old: Optional[Mapping[str, Any]],
    selector,
    fields,
) -> Optional[str]:
    """Classify a store event against a selector scope by old-vs-new state —
    the real watch cache's logic: entering scope is ADDED, leaving it is
    DELETED, staying in is MODIFIED; None = out of scope throughout.
    Stateless, so replayed and live events classify identically. Shared by
    the HTTP apiserver's watch handler and FakeCluster.watch — the
    server-side selector evaluation that keeps scoped watch streams (and
    hub scopes riding them) carrying only in-scope bytes.
    ``fields`` is a :class:`~.selectors.FieldSelector`."""

    def in_scope(obj: Mapping[str, Any]) -> bool:
        meta = obj.get("metadata") or {}
        return selector.matches(meta.get("labels") or {}) and fields.matches(
            obj
        )

    new_matches = event_type != _WATCH_DELETED and in_scope(data)
    old_matches = old is not None and in_scope(old)
    if new_matches and old_matches:
        return _WATCH_MODIFIED
    if new_matches:
        return _WATCH_ADDED
    if old_matches:
        return _WATCH_DELETED
    return None


class WatchFrameSource:
    """The frame source behind a watch stream — everything between the
    cluster's raw event journal and one consumer's ordered frames:
    static scoping (kind + namespace, applied before an event is ever
    queued), journal replay from a resumption ``resourceVersion``,
    selector-scope classification (``classify_watch_event``), and the
    BOOKMARK payload contract. Shared by ``FakeCluster.watch`` (the
    in-process sync generator) and the HTTP apiserver's streaming watch
    (which bridges ``emit`` into its event loop) so both speak one
    protocol — a frame the wire stream sends is byte-for-byte the frame
    the in-process watch would have yielded.

    Usage: ``open(emit, resource_version)`` subscribes and returns the
    classified replay frames; live events arrive through ``emit(
    event_type, data, old)`` (called from the WRITER's thread — keep it
    to an enqueue) and are classified consumer-side via ``classify``;
    ``bookmark()`` builds the resume-point frame; ``close()``
    unsubscribes (idempotent)."""

    def __init__(
        self,
        cluster: "FakeCluster",
        kind: str,
        api_version: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> None:
        self._cluster = cluster
        self.kind = kind
        self.api_version = api_version
        self.namespace = namespace
        if isinstance(label_selector, Mapping):
            self._selector = LabelSelector.from_match_labels(label_selector)
        else:
            self._selector = parse_selector(label_selector)
        self._fields = parse_field_selector(field_selector)
        self._on_event: Optional[Callable] = None

    def in_static_scope(self, data: Mapping[str, Any]) -> bool:
        """The cheap pre-queue filter: kind and namespace only. Selector
        scope needs old-vs-new classification and happens consumer-side
        (``classify``), off the writer's emit path."""
        if data.get("kind") != self.kind:
            return False
        if self.namespace:
            meta = data.get("metadata") or {}
            if meta.get("namespace", "") != self.namespace:
                return False
        return True

    def open(
        self,
        emit: Callable[[str, dict[str, Any], Optional[dict[str, Any]]], None],
        resource_version: Optional[str] = None,
    ) -> list[tuple[str, dict[str, Any]]]:
        """Subscribe ``emit`` for live events (statically pre-scoped) and
        return the classified journal replay since ``resource_version``
        — atomically, so no event between replay and subscription can be
        lost (``subscribe_since``'s contract). Raises
        ``WatchExpiredError`` when the revision fell out of the journal."""

        def on_event(event_type, data, old):
            if self.in_static_scope(data):
                emit(event_type, data, old)

        replay = self._cluster.subscribe_since(on_event, resource_version)
        self._on_event = on_event
        mapped: list[tuple[str, dict[str, Any]]] = []
        for event_type, data, old in replay:
            if not self.in_static_scope(data):
                continue
            frame = self.classify(event_type, data, old)
            if frame is not None:
                mapped.append((frame, data))
        return mapped

    def classify(
        self,
        event_type: str,
        data: Mapping[str, Any],
        old: Optional[Mapping[str, Any]],
    ) -> Optional[str]:
        """Selector-scope classification for one queued event; None =
        out of scope (drop the frame)."""
        return classify_watch_event(
            event_type, data, old, self._selector, self._fields
        )

    def bookmark(self) -> tuple[str, dict[str, Any]]:
        """The BOOKMARK frame: an object of the watched kind carrying
        ONLY ``metadata.resourceVersion`` (the real server's bookmark
        payload). The rv must be read BEFORE the caller re-checks queue
        emptiness — ``_emit`` bumps the rv and enqueues under one lock
        hold, so an rv observed here implies its event is already
        enqueued, and an empty queue then implies it was delivered."""
        return "BOOKMARK", {
            "kind": self.kind,
            "apiVersion": self.api_version,
            "metadata": {
                "resourceVersion": self._cluster.current_resource_version()
            },
        }

    def close(self) -> None:
        on_event = self._on_event
        if on_event is not None:
            self._on_event = None
            self._cluster.unsubscribe(on_event)


class FakeCluster(Client):
    """Thread-safe in-memory object store with apiserver semantics."""

    def __init__(
        self,
        auto_establish_crds: bool = True,
        crd_establish_delay: float = 0.0,
        crd_discovery_delay: float = 0.0,
        enable_owner_gc: bool = True,
    ) -> None:
        self._lock = threading.RLock()
        self._store: dict[tuple[str, str, str], dict[str, Any]] = {}
        #: Kind-bucketed mirror of ``_store`` so list/deleteCollection
        #: scan O(kind bucket) instead of the whole store — the
        #: difference between O(pool) and O(cluster) per list at
        #: 256-node scale. Maintained ONLY via _store_put/_store_del;
        #: ``_store`` stays the source of truth (tests introspect it).
        self._by_kind: dict[str, dict[tuple[str, str, str], dict[str, Any]]] = {}
        #: Owner-reference GC index: owner uid -> keys of (possible)
        #: dependents, with the reverse map for cheap diffs. Synced on
        #: every persisted write (_bump) and on delete — turns the GC's
        #: whole-store dependent scan into an O(dependents) lookup.
        self._owner_index: dict[str, set[tuple[str, str, str]]] = {}
        self._owners_of: dict[tuple[str, str, str], frozenset[str]] = {}
        #: Keys whose object is foreground-terminating (deletionTimestamp
        #: set + ``foregroundDeletion`` finalizer) — the only objects the
        #: GC sweep must visit. Maintained by _store_put/_store_del, the
        #: choke points every store change flows through under the
        #: copy-on-write write discipline; turns the per-delete sweep
        #: from O(store) into O(pending), which is almost always O(0).
        self._fg_pending: set[tuple[str, str, str]] = set()
        self._rv = itertools.count(1)
        self._reactors: list[tuple[str, str, Reactor]] = []
        self._watchers: list[
            Callable[[str, dict[str, Any], Optional[dict[str, Any]]], None]
        ] = []
        # Bounded event journal for watch resumption: (rv, event, object).
        # A watcher resuming from a resourceVersion older than the oldest
        # entry gets a 410 Gone analog (WatchExpiredError), like etcd.
        self._history: deque[
            tuple[int, str, dict[str, Any], Optional[dict[str, Any]]]
        ] = deque(maxlen=4096)
        self._changed = threading.Condition(self._lock)
        self._generation = 0
        # Paginated-list continuations: token id -> (item raws, revision,
        # expiry info). A real apiserver serves every page of one list
        # from the SAME storage snapshot and answers a stale/compacted
        # continue token with 410 reason=Expired; this bounded FIFO cache
        # reproduces both behaviors (eviction = compaction).
        # Owner-reference garbage collection (real-cluster semantics; the
        # reference's envtest runs NO controller-manager, so cascade
        # deletion never happens there — pass False to emulate that).
        self._enable_owner_gc = enable_owner_gc
        self._continues: dict[
            str,
            tuple[list[dict[str, Any]], str, tuple[str, str, str, str]],
        ] = {}
        self._continue_order: deque[str] = deque()
        self._continue_cap = 32
        # Emulate the apiserver's CRD controller: created CRDs gain the
        # Established condition (immediately, or after a delay to exercise
        # wait-for-established logic, reference: pkg/crdutil/crdutil.go:275-319).
        self._auto_establish_crds = auto_establish_crds
        # Sticky flag: set on the first CRD entering the store, never
        # cleared (deletes are rare; a stale True only costs the scan).
        # Lets the admission fallback skip an O(store) scan per custom
        # write on the common schema-less cluster.
        self._crds_ever_stored = False
        self._crd_establish_delay = crd_establish_delay
        # The real apiserver's Established-but-undiscoverable window: a
        # CRD's condition flips before its served versions appear in the
        # discovery document (the race pkg/crdutil/crdutil.go:275-319
        # polls discovery to guard against). >0 reproduces that window.
        self._crd_discovery_delay = crd_discovery_delay
        self._discoverable: dict[str, set[str]] = {}
        #: CRD names with a discovery timer in flight — every CRD write
        #: path runs the discoverability sync, and without this guard a
        #: busy write stream would stack redundant timers per name.
        self._discovery_pending: set[str] = set()
        self._pending_timers: list[threading.Timer] = []
        #: Optional API call log (see start_call_log): (verb, kind, name)
        #: per client call, appended under the store lock. Benches and
        #: tests count traffic with it — load-immune, unlike wall-clock.
        self._call_log: Optional[list[tuple[str, str, str]]] = None

    # -- fault injection ---------------------------------------------------
    def add_reactor(self, verb: str, kind: str, fn: Reactor) -> None:
        """Install a hook run before ``verb`` ("*" matches all) on ``kind``."""
        self._reactors.append((verb, kind, fn))

    # -- call log ----------------------------------------------------------
    def start_call_log(self) -> list[tuple[str, str, str]]:
        """Begin recording every API call as ``(verb, kind, name)`` and
        return the LIVE list (it keeps growing until stop_call_log).
        Restarting truncates. The log records calls the fake *received* —
        including ones a reactor then failed."""
        with self._lock:
            self._call_log = []
            return self._call_log

    def stop_call_log(self) -> list[tuple[str, str, str]]:
        """Stop recording; returns the captured log (empty if never
        started)."""
        with self._lock:
            log, self._call_log = self._call_log, None
            return log if log is not None else []

    def _react(self, verb: str, kind: str, payload: dict[str, Any]) -> None:
        if self._call_log is not None:
            name = payload.get("name") or (
                (payload.get("metadata") or {}).get("name", "")
            )
            self._call_log.append((verb, kind, str(name)))
        for v, k, fn in self._reactors:
            if v in ("*", verb) and k in ("*", kind):
                fn(verb, kind, payload)

    # -- watch -------------------------------------------------------------
    def subscribe(
        self, fn: Callable[[str, dict[str, Any], Optional[dict[str, Any]]], None]
    ) -> None:
        """Register a watcher receiving ``(event_type, object, old_object)``
        on every write — ``old_object`` is the pre-mutation state (None for
        ADDED), which is what lets selector-scoped watches classify
        transitions exactly as the real watch cache does.

        Delivered objects are FROZEN journal references (see ``_emit``):
        read-only by contract. ``watch()`` yields these same frozen
        references (zero copies per delivered event); any consumer that
        hands them to code which may mutate must copy first — the
        informer does so on its own reads, not at delivery."""
        with self._lock:
            self._watchers.append(fn)

    def unsubscribe(self, fn) -> None:
        """Remove a subscribed watcher (no-op when absent — a watch may be
        torn down from a thread racing the subscription)."""
        with self._lock:
            try:
                self._watchers.remove(fn)
            except ValueError:
                pass

    def subscribe_since(
        self,
        fn: Callable[[str, dict[str, Any]], None],
        resource_version: Optional[str] = None,
    ) -> list[tuple[str, dict[str, Any]]]:
        """Atomically subscribe and return the journal entries newer than
        ``resource_version`` — the list-then-watch resumption primitive: no
        event between the caller's list and this subscription can be lost,
        because replay collection and watcher registration happen under the
        same lock every mutation holds while emitting.

        Raises :class:`WatchExpiredError` when ``resource_version`` is
        older than the journal's oldest entry (the 410 Gone analog —
        the client must re-list).
        """
        with self._lock:
            replay: list[tuple[str, dict[str, Any], Optional[dict[str, Any]]]] = []
            if resource_version is not None and resource_version != "":
                try:
                    since = int(resource_version)
                except ValueError:
                    raise InvalidError(
                        f"invalid resourceVersion {resource_version!r}"
                    ) from None
                if self._history and self._history[0][0] > since + 1:
                    raise WatchExpiredError(
                        f"resourceVersion {since} is too old "
                        f"(oldest journaled: {self._history[0][0]})"
                    )
                last_rv = getattr(self, "_last_rv", 0)
                if not self._history and since < last_rv:
                    # Journal fully compacted: nothing to replay, but the
                    # cluster has moved past the caller's revision, so
                    # events WERE lost. Resuming live here would silently
                    # drop them — the real apiserver answers 410 Gone and
                    # the client re-lists (the exact repair the informer's
                    # relist-after-expiry path implements).
                    raise WatchExpiredError(
                        f"resourceVersion {since} is too old "
                        f"(journal compacted; current: {last_rv})"
                    )
                # Journal entries are frozen (copy-on-write store): replay
                # hands out references under the same read-only contract
                # live delivery uses — no per-entry copy on informer resume.
                replay = [
                    (event, data, old)
                    for rv, event, data, old in self._history
                    if rv > since
                ]
            self._watchers.append(fn)
            return replay

    def watch(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
        timeout_seconds: Optional[int] = None,
        resource_version: Optional[str] = None,
        handle=None,
        allow_bookmarks: bool = False,
        bookmark_interval_s: float = 15.0,
    ):
        """In-process watch generator with the same semantics as
        ``RestClient.watch`` against the HTTP apiserver: journal resumption
        from ``resource_version``, selector-scope transitions via
        old-vs-new classification, ``timeout_seconds`` ending the stream.
        ``handle`` accepts a ``WatchHandle``-shaped object; its
        ``cancelled`` flag ends the stream at the next poll tick.
        ``timeout_seconds=None`` applies the same default window as
        RestClient (DEFAULT_WATCH_TIMEOUT_SECONDS) — code tested against
        the fake must see the real client's bounded-stream behavior.
        ``allow_bookmarks`` opts into periodic BOOKMARK events carrying
        only the current collection resourceVersion (the real server's
        watch-bookmark contract): a quiet scoped watch keeps a fresh
        resume point while the shared journal advances under it, instead
        of decaying toward 410 + full re-list."""
        import queue

        if timeout_seconds is None:
            from .rest import DEFAULT_WATCH_TIMEOUT_SECONDS

            timeout_seconds = DEFAULT_WATCH_TIMEOUT_SECONDS

        source = WatchFrameSource(
            self,
            kind,
            KINDS.get(kind, KubeObject).API_VERSION or "v1",
            namespace=namespace,
            label_selector=label_selector,
            field_selector=field_selector,
        )
        events: queue.Queue = queue.Queue(maxsize=1024)

        def emit(event_type, data, old):
            try:
                events.put_nowait((event_type, data, old))
            except queue.Full:
                pass  # in-process consumer this slow has bigger problems

        replay = source.open(emit, resource_version)
        try:
            for mapped, data in replay:
                # Yielded objects are frozen journal references (see
                # _emit) — read-only by contract, same as the shared
                # snapshot every consumer of this generator always
                # got. The informer rides on this: zero copies per
                # delivered event; its own reads copy on the way out.
                yield mapped, wrap(data)
            deadline = (
                time.monotonic() + timeout_seconds
                if timeout_seconds is not None
                else None
            )
            next_bookmark = time.monotonic() + bookmark_interval_s
            while not (handle is not None and handle.cancelled):
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    poll = min(0.2, remaining)
                else:
                    poll = 0.2
                if allow_bookmarks:
                    poll = min(
                        poll, max(0.01, next_bookmark - time.monotonic())
                    )
                try:
                    event_type, data, old = events.get(timeout=poll)
                except queue.Empty:
                    # Bookmark only from a DRAINED queue — the contract is
                    # "every event up to this rv has been delivered"; see
                    # WatchFrameSource.bookmark for the rv-before-recheck
                    # ordering this leans on.
                    if allow_bookmarks and time.monotonic() >= next_bookmark:
                        frame, data = source.bookmark()
                        if events.empty():
                            next_bookmark = (
                                time.monotonic() + bookmark_interval_s
                            )
                            yield frame, wrap(data)
                    continue
                mapped = source.classify(event_type, data, old)
                if mapped is not None:
                    yield mapped, wrap(data)
        finally:
            source.close()

    def _emit(
        self,
        event: str,
        data: dict[str, Any],
        old: Optional[dict[str, Any]] = None,
    ) -> None:
        # Ownership contract (the copy-on-write store discipline): a dict
        # is FROZEN the moment it is stored or emitted — every mutating
        # path works on a private copy and swaps it in via _store_put, so
        # the journal and the subscribers can take both ``data`` (the
        # just-stored object) and ``old`` (the previously-stored object,
        # or the caller's private pre-delete copy) by reference instead
        # of paying a whole-object copy per write. (An old shared by two
        # journal entries — a releasing write's MODIFIED + its DELETED —
        # stays correct for the same reason: nothing mutates it.)
        snapshot = data
        old_snapshot = old
        if old_snapshot is None and event != _WATCH_ADDED:
            # DELETED with no explicit prior: the object itself is the
            # pre-deletion state.
            old_snapshot = snapshot if event == _WATCH_DELETED else None
        try:
            rv = int((snapshot.get("metadata") or {}).get("resourceVersion"))
        except (TypeError, ValueError):
            rv = next(self._rv)  # defensive: journal stays ordered
        # Trace write-origin hook (docs/tracing.md): remember which trace
        # performed this write, keyed by rv. Informer deliveries — over a
        # direct watch, a hub resume replay, or a reconnected wire stream
        # alike — link their span to it, so a reconcile pass can be
        # traced back to the write that woke it. One global read when
        # tracing is off.
        tracing.record_write_origin(rv)
        self._history.append((rv, event, snapshot, old_snapshot))
        for fn in list(self._watchers):
            fn(event, snapshot, old_snapshot)
        with self._changed:
            self._generation += 1
            self._changed.notify_all()

    @property
    def generation(self) -> int:
        """Monotonic write counter; compare across calls to detect changes
        without relying on notification delivery."""
        with self._changed:
            return self._generation

    def wait_for_change(self, timeout: float, after_generation: int = -1) -> int:
        """Block until the write generation exceeds ``after_generation`` (or
        the timeout elapses) and return the current generation. Immune to
        lost notifications: callers track the generation they last saw."""
        deadline = time.monotonic() + timeout
        with self._changed:
            while self._generation <= after_generation:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._changed.wait(remaining)
            return self._generation

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _key(kind: str, namespace: str, name: str) -> tuple[str, str, str]:
        cls = KINDS.get(kind)
        if cls is not None and not cls.NAMESPACED:
            namespace = ""
        return (kind, namespace, name)

    def _bump(self, data: dict[str, Any]) -> None:
        # Revision assignment only: index maintenance lives in
        # _store_put, which every persisted write now reaches (the
        # copy-on-write discipline swaps a fresh dict in per mutation).
        # Deletes _bump a private copy after _store_del — nothing to
        # index there.
        self._last_rv = next(self._rv)
        meta = data.setdefault("metadata", {})
        meta["resourceVersion"] = str(self._last_rv)

    def _sync_owner_index(
        self, key: tuple[str, str, str], data: dict[str, Any]
    ) -> None:
        """Diff the object's ownerReferences into the GC index; caller
        holds the lock."""
        refs = (data.get("metadata") or {}).get("ownerReferences") or []
        new_owners = frozenset(
            r.get("uid") for r in refs if r.get("uid")
        )
        old_owners = self._owners_of.get(key, frozenset())
        if new_owners == old_owners:
            return
        for uid in old_owners - new_owners:
            bucket = self._owner_index.get(uid)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._owner_index[uid]
        for uid in new_owners - old_owners:
            self._owner_index.setdefault(uid, set()).add(key)
        if new_owners:
            self._owners_of[key] = new_owners
        else:
            self._owners_of.pop(key, None)

    @staticmethod
    def _spec_view(data: Mapping[str, Any]) -> dict[str, Any]:
        """Everything outside metadata/status — what generation tracks."""
        return {
            k: v for k, v in data.items() if k not in ("metadata", "status")
        }

    def _sync_generation(
        self, data: dict[str, Any], old: Optional[Mapping[str, Any]]
    ) -> None:
        """metadata.generation is server-owned: 1 on create, +1 whenever
        the desired state (anything outside metadata/status) changes,
        never on status-only writes. One uniform rule for every kind —
        the modern apiserver behavior (CRs with a status subresource,
        apps types); legacy core types that skip generation entirely are
        deliberately not special-cased (PARITY)."""
        meta = data.setdefault("metadata", {})
        if old is None:
            meta["generation"] = 1
            return
        previous = (old.get("metadata") or {}).get("generation", 1)
        if self._spec_view(data) != self._spec_view(old):
            meta["generation"] = previous + 1
        else:
            meta["generation"] = previous

    # -- structural-schema admission (custom resources) --------------------
    def _admit_custom_locked(
        self, data: dict[str, Any], status_only: bool = False
    ) -> None:
        """The apiserver's CR admission: when a stored CRD carries a
        structural schema for this object's group/kind/version, prune
        unknown fields, apply defaults, and validate — 422 on violation.
        Built-in groups and kinds with no stored CRD are untouched, so a
        schema-less cluster behaves exactly as before (the same
        activation rule server-side apply uses)."""
        if data.get("kind") == "CustomResourceDefinition":
            # The CRD itself is admitted too: upstream rejects v1 CRDs
            # whose declared schemas are not structural. Runs at this
            # one chokepoint so every write verb (and its atomicity
            # handling) covers it.
            crd_errors = validate_crd_structural(data)
            if crd_errors:
                name = (data.get("metadata") or {}).get("name", "")
                raise InvalidError(
                    f"CustomResourceDefinition.apiextensions.k8s.io "
                    f"{name!r} is invalid: " + "; ".join(crd_errors)
                )
            return
        if _supports_strategic(data):
            return  # built-in group: typed, never CRD-backed
        api_version = data.get("apiVersion") or ""
        group, _, version = api_version.rpartition("/")
        kind = data.get("kind", "")
        crd = self._crd_for_locked(group, kind)
        if crd is None:
            return
        schema = schema_for_crd_version(crd, version)
        if schema is None:
            return
        errors = schema.admit(data)
        if status_only:
            # ValidateStatusUpdate shape: a status write is judged on
            # its status only — a spec that predates a tightened CRD
            # must not wedge the status-writing controller. Exact root
            # field match: a spec field named "statusHistory" is not
            # "status".
            errors = [
                e for e in errors if error_root_field(e) == "status"
            ]
        if errors:
            name = (data.get("metadata") or {}).get("name", "")
            raise InvalidError(
                f"{kind}.{group} {name!r} is invalid: " + "; ".join(errors)
            )

    def _crd_for_locked(self, group: str, kind: str):
        """The stored CRD backing ``group``/``kind``, or None. Direct
        keyed lookup via the resource registry's plural first; stored
        CRDs themselves are the authoritative fallback mapping for
        unregistered or irregularly-pluralized kinds."""
        try:
            plural = resource_for_kind(kind).plural
        except KeyError:
            pass
        else:
            crd = self._store.get(
                ("CustomResourceDefinition", "", f"{plural}.{group}")
            )
            if crd is not None:
                return crd
        if not self._crds_ever_stored:
            return None  # schema-less cluster: skip the store scan
        for key, stored in self._store.items():
            if key[0] != "CustomResourceDefinition":
                continue
            spec = stored.get("spec") or {}
            if spec.get("group") == group and (
                (spec.get("names") or {}).get("kind") == kind
            ):
                return stored
        return None

    def printer_columns(
        self, kind: str, api_version: str
    ) -> Optional[list[dict[str, Any]]]:
        """The ``additionalPrinterColumns`` a stored CRD declares for
        this kind's served version — what the Table transform renders
        (reference fixture: hack/crd/bases/maintenance.nvidia.com_
        nodemaintenances.yaml:17-31). None for built-ins or unknown
        kinds."""
        group, _, version = api_version.rpartition("/")
        if not group:
            return None
        with self._lock:
            crd = self._crd_for_locked(group, kind)
            if crd is None:
                return None
            for v in (crd.get("spec") or {}).get("versions") or []:
                if v.get("name") == version:
                    cols = v.get("additionalPrinterColumns") or []
                    return deep_copy_json(cols)
        return None

    def current_resource_version(self) -> str:
        """The newest revision assigned — a list's collection
        resourceVersion (what an empty list resumes a watch from).

        Taken UNDER the store lock: a writer assigns the rv (_bump) and
        enqueues the event to watchers (_emit) in one lock hold, so a
        locked read serializes after the whole write — an rv observed
        here implies its event was already delivered to subscriber
        queues. The bookmark path's drained-queue check rides on exactly
        that ordering; a lock-free peek could see the rv of a write
        whose event was still pending and stamp a bookmark that
        overtakes it."""
        with self._lock:
            return str(getattr(self, "_last_rv", 0))

    def _store_put(
        self, key: tuple[str, str, str], data: dict[str, Any]
    ) -> None:
        """Store insert/replace + index maintenance; caller holds the
        lock. Under the copy-on-write write discipline every store
        change flows through here (mutating paths swap in a fresh dict
        rather than editing the stored one), which makes this the single
        place the owner-GC and foreground-pending indexes stay synced."""
        self._store[key] = data
        self._by_kind.setdefault(key[0], {})[key] = data
        self._sync_owner_index(key, data)
        meta = data.get("metadata") or {}
        if meta.get("deletionTimestamp") and "foregroundDeletion" in (
            meta.get("finalizers") or []
        ):
            self._fg_pending.add(key)
        else:
            self._fg_pending.discard(key)

    def _store_del(self, key: tuple[str, str, str]) -> None:
        """Store delete + kind/owner-index maintenance; caller holds
        the lock."""
        self._fg_pending.discard(key)
        del self._store[key]
        bucket = self._by_kind.get(key[0])
        if bucket is not None:
            bucket.pop(key, None)
        for uid in self._owners_of.pop(key, frozenset()):
            owner_bucket = self._owner_index.get(uid)
            if owner_bucket is not None:
                owner_bucket.discard(key)
                if not owner_bucket:
                    del self._owner_index[uid]

    # -- read-only fast paths (simulators / benches) -----------------------
    # These skip the defensive copy, NOT the API semantics: they still run
    # reactors (fault injection sees them) and the call log records them,
    # so a simulated kubelet on the fast path stays subject to the same
    # injected chaos as one on get()/list().
    def contains(self, kind: str, name: str, namespace: str = "") -> bool:
        """Existence check without the defensive copy ``get`` makes —
        the kubelet simulator's per-node per-tick probe."""
        with self._lock:
            self._react("get", kind, {"name": name, "namespace": namespace})
            return self._key(kind, namespace, name) in self._store

    def object_names(self, kind: str, namespace: str = "") -> list[str]:
        """Sorted names of stored objects of ``kind`` (no copies)."""
        with self._lock:
            self._react("list", kind, {"namespace": namespace})
            return sorted(
                name
                for (_, ns, name) in self._by_kind.get(kind, {})
                if not namespace or ns == namespace
            )

    def peek(
        self, kind: str, name: str, namespace: str = ""
    ) -> Optional[dict[str, Any]]:
        """The RAW stored object, no copy, or None. STRICTLY read-only:
        mutating the return value corrupts the store — this exists for
        simulators and benches whose per-tick reads would otherwise copy
        the whole pool; API consumers use get()/list()."""
        with self._lock:
            self._react("get", kind, {"name": name, "namespace": namespace})
            return self._store.get(self._key(kind, namespace, name))

    def list_peek(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
    ) -> list[dict[str, Any]]:
        """RAW stored objects of ``kind``, filtered like ``list``, no
        copies. STRICTLY read-only (``peek``'s contract) — with one
        guarantee the copy-on-write store adds: the returned dicts are
        frozen (a later write swaps in a fresh dict instead of editing
        these), so the result is a consistent point-in-time snapshot,
        not a live view. The snapshot source serves build_state's
        Pod/DaemonSet/ControllerRevision reads from this — kinds the
        upgrade managers never mutate — skipping one whole-object copy
        per object per reconcile pass. Anything that mutates results
        uses list()."""
        if isinstance(label_selector, Mapping):
            selector = LabelSelector.from_match_labels(label_selector)
        else:
            selector = parse_selector(label_selector)
        with self._lock:
            self._react("list", kind, {"namespace": namespace})
            out = []
            for (_, ns, _name), data in sorted(
                self._by_kind.get(kind, {}).items()
            ):
                if namespace and ns != namespace:
                    continue
                labels = (data.get("metadata") or {}).get("labels") or {}
                if selector.matches(labels):
                    out.append(data)
            return out

    def _get_raw(self, kind: str, name: str, namespace: str) -> dict[str, Any]:
        key = self._key(kind, namespace, name)
        data = self._store.get(key)
        if data is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        return data

    @staticmethod
    def _write_becomes_delete(data: dict[str, Any]) -> bool:
        """True when this write empties a terminating object's finalizer
        list: on a real apiserver that update IS the deletion — watchers
        observe one DELETED, never a MODIFIED for the releasing write
        (pinned by the watch vectors in tests/conformance_vectors/)."""
        meta = data.get("metadata", {})
        return bool(meta.get("deletionTimestamp")) and not meta.get(
            "finalizers"
        )

    def _finalize_delete_if_due(
        self, kind: str, name: str, namespace: str, old=None
    ) -> None:
        """Remove a deletionTimestamp-marked object once finalizers are
        gone. Caller holds the lock. ``old`` is the pre-write snapshot of
        the releasing write: its MODIFIED event was suppressed (the write
        IS the deletion, see _write_becomes_delete), so the DELETED event
        must carry the pre-write state or a label-selector watcher whose
        object left scope in that same write would classify the event
        away."""
        key = self._key(kind, namespace, name)
        data = self._store.get(key)
        if data is None:
            return
        meta = data.get("metadata", {})
        if meta.get("deletionTimestamp") and not meta.get("finalizers"):
            self._store_del(key)
            if kind == "CustomResourceDefinition":
                self._discoverable.pop(name, None)
            # The real apiserver bumps rv on delete; without it the
            # DELETED journal entry reuses the object's last revision and
            # a watch resuming from exactly that revision replays PAST the
            # deletion — a lost event. The bump mutates, so it lands on a
            # private copy (the released dict is already journaled).
            data = deep_copy_json(data)
            self._bump(data)
            self._emit(_WATCH_DELETED, data, old=old)
            # A finalizer-released object is as gone as a direct delete:
            # its dependents are collected, and any Foreground owner
            # waiting on IT gets re-checked.
            if self._enable_owner_gc and meta.get("uid"):
                self._gc_on_owner_removed(meta["uid"])

    # -- Client API --------------------------------------------------------
    def get(self, kind: str, name: str, namespace: str = "") -> KubeObject:
        with self._lock:
            self._react("get", kind, {"name": name, "namespace": namespace})
            return wrap(deep_copy_json(self._get_raw(kind, name, namespace)))

    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> list[KubeObject]:
        if isinstance(label_selector, Mapping):
            selector = LabelSelector.from_match_labels(label_selector)
        else:
            selector = parse_selector(label_selector)
        fields = parse_field_selector(field_selector)
        with self._lock:
            self._react("list", kind, {"namespace": namespace})
            out = []
            bucket = self._by_kind.get(kind, {})
            for (_, ns, _name), data in sorted(bucket.items()):
                if namespace and ns != namespace:
                    continue
                labels = (data.get("metadata") or {}).get("labels") or {}
                if not selector.matches(labels):
                    continue
                if not fields.matches(data):
                    continue
                out.append(wrap(deep_copy_json(data)))
            return out

    def delete_collection(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
        propagation_policy: Optional[str] = None,
        dry_run: bool = False,
    ) -> list[KubeObject]:
        """client-go's deleteCollection verb (``kubectl delete --all`` /
        selector-scoped bulk delete): every matching object goes through
        the SAME per-object delete pipeline — finalizers hold objects in
        Terminating, owner-reference GC cascades per
        ``propagation_policy``, dry-run previews without deleting.
        Returns the objects the call addressed (upstream returns the
        deleted items' list)."""
        # Namespacedness from the REST registry first: custom kinds
        # registered via kube.resources.register_resource (the
        # framework's primary CR path) are not in KINDS, and skipping
        # them here silently deleted the kind across ALL namespaces —
        # exactly the over-deletion this guard exists to stop
        # (ADVICE.md). KINDS stays as the fallback for typed kinds a
        # test may use without registering.
        try:
            namespaced = resource_for_kind(kind).namespaced
        except KeyError:
            cls = KINDS.get(kind)
            namespaced = cls.NAMESPACED if cls is not None else False
        if namespaced and not namespace:
            # A real apiserver serves deletecollection only on the
            # namespaced collection of a namespaced resource — the
            # all-namespaces path answers 405. Refusing here keeps fake
            # -validated code from silently over-deleting cluster-wide.
            raise BadRequestError(
                f"deleteCollection on namespaced kind {kind} requires a "
                "namespace (all-namespaces deletecollection is not served "
                "by a real apiserver)"
            )
        matched = self.list(
            kind, namespace,
            label_selector=label_selector, field_selector=field_selector,
        )
        for obj in matched:
            try:
                self.delete(
                    kind, obj.name, obj.namespace,
                    propagation_policy=propagation_policy,
                    dry_run=dry_run,
                )
            except NotFoundError:
                continue  # raced with another deleter: already gone
        return matched

    def list_with_revision(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> tuple[list[KubeObject], str]:
        """``list()`` plus the collection resourceVersion, RestClient
        parity (kube/rest.py list_with_revision): the revision an informer
        seeds its watch from, so the documented no-lost-event resumption
        holds over the fake too — including for an empty list, where there
        are no items to take a revision from. Items and revision are read
        under one lock acquisition (RLock) so a concurrent write cannot
        slip between them."""
        with self._lock:
            items = self.list(kind, namespace, label_selector, field_selector)
            return items, self.current_resource_version()

    def list_delta(
        self,
        kind: str,
        since_resource_version: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
    ) -> Optional[ListDelta]:
        """Deltas-since-rv LIST (the journal-backed fast re-list,
        docs/wire-path.md): when ``since_resource_version`` is inside
        the event journal, answer the CURRENT state of every in-scope
        object touched after it plus the keys that left the collection
        (or the selector scope) — O(what changed), not O(collection).
        Returns ``None`` when the revision fell out of the journal (the
        410 analog — the HTTP layer answers Gone and the client falls
        back to a full snapshot)."""
        try:
            since = int(since_resource_version)
        except (TypeError, ValueError):
            raise InvalidError(
                f"invalid resourceVersion {since_resource_version!r}"
            ) from None
        if isinstance(label_selector, Mapping):
            selector = LabelSelector.from_match_labels(label_selector)
        else:
            selector = parse_selector(label_selector)
        fields = parse_field_selector(field_selector)
        with self._lock:
            self._react("list", kind, {"namespace": namespace})
            last_rv = getattr(self, "_last_rv", 0)
            # Same coverage rules as watch resumption (subscribe_since):
            # a gap between `since` and the oldest journal entry means
            # events were lost to compaction — only a full list repairs.
            if self._history and self._history[0][0] > since + 1:
                return None
            if not self._history and since < last_rv:
                return None
            touched: dict[tuple[str, str], None] = {}
            for rv, _event, data, _old in self._history:
                if rv <= since or data.get("kind") != kind:
                    continue
                meta = data.get("metadata") or {}
                ns = meta.get("namespace", "")
                if namespace and ns != namespace:
                    continue
                touched[(ns, meta.get("name", ""))] = None
            items: list[KubeObject] = []
            deleted: list[tuple[str, str]] = []
            for ns, name in touched:
                data = self._store.get(self._key(kind, ns, name))
                if data is None:
                    deleted.append((ns, name))
                    continue
                labels = (data.get("metadata") or {}).get("labels") or {}
                if not selector.matches(labels) or not fields.matches(data):
                    # Left the selector scope: for this consumer the
                    # object is gone (a never-matching key deletes a
                    # store entry the consumer never had — a no-op).
                    deleted.append((ns, name))
                    continue
                items.append(wrap(deep_copy_json(data)))
            return ListDelta(items, deleted, str(last_rv))

    def list_page(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[str | Mapping[str, str]] = None,
        field_selector: Optional[str] = None,
        limit: int = 0,
        continue_token: str = "",
    ) -> tuple[list[KubeObject], str, str, Optional[int]]:
        """One page of a chunked list — apiserver ``limit``/``continue``
        semantics (client-go reflectors always paginate; API machinery's
        chunking KEP): every page of one list is served from the SAME
        snapshot taken at the first page, the returned revision is that
        snapshot's collection resourceVersion (what the follow-up watch
        resumes from), and a stale/evicted continue token fails with the
        410 reason=Expired the real apiserver emits after compaction —
        client-go's pager then falls back to a full list, and so does
        ``RestClient``.

        Returns ``(items, revision, next_continue, remaining)`` where
        ``next_continue`` is "" on the final page and ``remaining`` is
        the listMeta remainingItemCount (None on single-page results,
        like the real server omitting the field).
        """
        if limit < 0:
            raise BadRequestError(f"limit must be non-negative, got {limit}")
        # The real server never reports remainingItemCount for
        # selector-filtered chunked lists (ListMeta contract).
        selector_used = bool(label_selector) or bool(field_selector)
        signature = (kind, namespace, str(label_selector or ""),
                     str(field_selector or ""))
        with self._lock:
            if continue_token:
                try:
                    token_id, _, offset_s = continue_token.partition(":")
                    offset = int(offset_s)
                except ValueError:
                    raise BadRequestError(
                        f"malformed continue token {continue_token!r}"
                    ) from None
                if token_id not in self._continues:
                    raise WatchExpiredError(
                        "the provided continue parameter is too old: "
                        "a consistent list is no longer possible"
                    )
                raws, revision, token_sig = self._continues[token_id]
                if offset < 0 or offset > len(raws):
                    # Tampered/corrupt token: a real server answers 400.
                    # (A live token never carries these offsets — the
                    # final page returns no token at all.)
                    raise BadRequestError(
                        f"continue token offset {offset} out of range"
                    )
                if token_sig != signature:
                    # Real apiserver: 400 when a continue key is replayed
                    # against a different resource/selector query.
                    raise BadRequestError(
                        "continue key does not match this request's "
                        f"query (issued for {token_sig!r})"
                    )
            else:
                items, revision = self.list_with_revision(
                    kind, namespace, label_selector, field_selector
                )
                raws = [o.raw for o in items]
                offset = 0
                if limit <= 0 or len(raws) <= limit:
                    return items, revision, "", None
                token_id = uuid.uuid4().hex
                self._continues[token_id] = (raws, revision, signature)
                self._continue_order.append(token_id)
                while len(self._continue_order) > self._continue_cap:
                    self._continues.pop(self._continue_order.popleft(), None)
            if limit <= 0:
                limit = len(raws) - offset
            page = raws[offset : offset + limit]
            next_offset = offset + len(page)
            remaining = len(raws) - next_offset
            if remaining <= 0:
                self._continues.pop(token_id, None)
                return (
                    [wrap(deep_copy_json(r)) for r in page], revision, "", None
                )
            return (
                [wrap(deep_copy_json(r)) for r in page],
                revision,
                f"{token_id}:{next_offset}",
                None if selector_used else remaining,
            )

    def expire_continue_tokens(self) -> None:
        """Test hook: the 'compaction' that invalidates every outstanding
        continue token (subsequent pages answer 410 Expired)."""
        with self._lock:
            self._continues.clear()
            self._continue_order.clear()

    def create(
        self,
        obj: KubeObject,
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        kind = obj.raw.get("kind", "")
        if not kind or not obj.name:
            raise InvalidError("object must have kind and metadata.name")
        with self._lock:
            self._react("create", kind, obj.raw)
            key = self._key(kind, obj.namespace, obj.name)
            if key in self._store:
                raise AlreadyExistsError(f"{kind} {obj.name} already exists")
            data = deep_copy_json(obj.raw)
            self._admit_custom_locked(data)
            meta = data.setdefault("metadata", {})
            meta.setdefault("uid", _new_uid())
            meta.setdefault("creationTimestamp", time.time())
            if field_manager and not meta.get("managedFields"):
                # An explicitly-managed create owns every field it wrote
                # (operation Update), so a later apply by someone else
                # sees honest conflicts. Creates that already carry
                # managedFields (create-through-apply) keep them.
                reassign_on_write({}, data, field_manager, rfc3339_now())
            self._sync_generation(data, None)
            if dry_run:
                # dryRun=All: the full admission/defaulting pipeline ran;
                # nothing persists, no events, no revision assigned.
                return wrap(deep_copy_json(data))
            self._bump(data)
            self._store_put(key, data)
            self._emit(_WATCH_ADDED, data)
            if kind == "CustomResourceDefinition":
                self._crds_ever_stored = True
                # A re-created CRD must not inherit a predecessor's
                # discoverability (its served versions may differ).
                self._discoverable.pop(obj.name, None)
                if not self._auto_establish_crds:
                    # Manual-controller mode: honor a pre-set condition.
                    self._sync_crd_discoverability_locked(data)
                elif self._crd_establish_delay > 0:
                    timer = threading.Timer(
                        self._crd_establish_delay, self._establish_crd, (obj.name,)
                    )
                    timer.daemon = True
                    self._pending_timers.append(timer)
                    timer.start()
                else:
                    data = self._establish_crd_locked(data)
            return wrap(deep_copy_json(data))

    def _establish_crd_locked(self, data: dict[str, Any]) -> dict[str, Any]:
        """Returns the (possibly replaced) stored dict — copy-on-write
        means establishing swaps in a new object, and callers that go on
        to build a response from ``data`` need the established one."""
        conds = (data.get("status") or {}).get("conditions") or []
        if not any(c.get("type") == "Established" for c in conds):
            old = data
            data = deep_copy_json(old)
            data.setdefault("status", {}).setdefault(
                "conditions", []
            ).append({"type": "Established", "status": "True"})
            key = self._key(
                "CustomResourceDefinition",
                "",
                (data.get("metadata") or {}).get("name", ""),
            )
            self._bump(data)
            self._store_put(key, data)
            self._emit(_WATCH_MODIFIED, data, old=old)
        self._sync_crd_discoverability_locked(data)
        return data

    def _sync_crd_discoverability_locked(self, data: dict[str, Any]) -> None:
        """An Established CRD becomes discoverable after the configured
        window. Runs on every CRD write path — including manual status
        writes with auto-establishment off, so tests that play the CRD
        controller themselves still reach discoverability."""
        crd = CustomResourceDefinition(data)
        if crd.name in self._discoverable:
            return
        self._schedule_discovery_refresh_locked(data)

    def _schedule_discovery_refresh_locked(self, data: dict[str, Any]) -> None:
        """Refresh the CRD's discoverable-version set (after the window).
        Unlike the sync above this runs even when the CRD is already
        discoverable — the path spec UPDATES take, so already-served
        versions stay served through the window (a real apiserver never
        un-serves v1 while v2 establishes) and the set converges to the
        new served list when the window elapses."""
        crd = CustomResourceDefinition(data)
        if not crd.is_established() or crd.name in self._discovery_pending:
            return
        if self._crd_discovery_delay > 0:
            self._discovery_pending.add(crd.name)
            timer = threading.Timer(
                self._crd_discovery_delay, self._make_discoverable, (crd.name,)
            )
            timer.daemon = True
            self._pending_timers.append(timer)
            timer.start()
        else:
            self._make_discoverable_locked(data)

    def _make_discoverable_locked(self, data: dict[str, Any]) -> None:
        crd = CustomResourceDefinition(data)
        self._discoverable[crd.name] = set(crd.served_versions)

    def _make_discoverable(self, name: str) -> None:
        with self._lock:
            self._discovery_pending.discard(name)
            key = self._key("CustomResourceDefinition", "", name)
            data = self._store.get(key)
            if data is not None:
                self._make_discoverable_locked(data)

    def _establish_crd(self, name: str) -> None:
        with self._lock:
            key = self._key("CustomResourceDefinition", "", name)
            data = self._store.get(key)
            if data is not None:
                self._establish_crd_locked(data)

    def discover(self, group: str, version: str) -> list[dict[str, Any]]:
        """APIResourceList entries for ``group/version`` — built-in kinds
        from the resource registry plus established CRDs whose served
        version has become discoverable. NotFoundError while nothing
        serves the group/version, exactly what a real apiserver's 404
        means to a discovery poller."""
        from .resources import _REGISTRY  # registry is the builtin catalog

        gv = f"{group}/{version}" if group else version
        resources: list[dict[str, Any]] = []
        for info in _REGISTRY.values():
            if info.api_version == gv:
                resources.append(
                    {
                        "name": info.plural,
                        "kind": info.kind,
                        "namespaced": info.namespaced,
                    }
                )
        with self._lock:
            for (kind, _, _), data in list(self._store.items()):
                if kind != "CustomResourceDefinition":
                    continue
                crd = CustomResourceDefinition(data)
                if crd.group != group:
                    continue
                if version not in self._discoverable.get(crd.name, ()):
                    continue
                names = crd.spec.get("names") or {}
                resources.append(
                    {
                        "name": names.get("plural", ""),
                        "kind": names.get("kind", ""),
                        "namespaced": crd.spec.get("scope") != "Cluster",
                    }
                )
        if not resources:
            raise NotFoundError(f"no resources discoverable for {gv}")
        return resources

    def _replace(
        self,
        obj: KubeObject,
        status_only: bool,
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        kind = obj.raw.get("kind", "")
        with self._lock:
            verb = "update_status" if status_only else "update"
            self._react(verb, kind, obj.raw)
            current = self._get_raw(kind, obj.name, obj.namespace)
            sent_rv = obj.resource_version
            if sent_rv and sent_rv != current.get("metadata", {}).get("resourceVersion"):
                raise ConflictError(
                    f"{kind} {obj.name}: resourceVersion {sent_rv} is stale"
                )
            key = self._key(kind, obj.namespace, obj.name)
            # Copy-on-write: ``old`` stays the frozen stored dict; both
            # branches build the replacement privately and swap it in.
            old = current
            if status_only:
                data = deep_copy_json(old)
                data["status"] = deep_copy_json(obj.raw.get("status") or {})
                self._admit_custom_locked(data, status_only=True)
                # statusStrategy semantics: desired state cannot change
                # through the status endpoint — whatever admission
                # pruned/defaulted outside status is restored from the
                # stored object, so generation never moves here.
                for k in [k for k in data
                          if k not in ("metadata", "status")]:
                    del data[k]
                for k, v in old.items():
                    if k not in ("metadata", "status"):
                        data[k] = deep_copy_json(v)
            else:
                data = deep_copy_json(obj.raw)
                # Immutable/server-owned fields survive a replace.
                meta = data.setdefault("metadata", {})
                cur_meta = current.get("metadata", {})
                meta["uid"] = cur_meta.get("uid")
                meta["creationTimestamp"] = cur_meta.get("creationTimestamp")
                if cur_meta.get("deletionTimestamp"):
                    meta["deletionTimestamp"] = cur_meta["deletionTimestamp"]
                # The status subresource is ignored on a main-resource update,
                # as on a real apiserver with subresources enabled.
                if "status" in current:
                    # Deep copy: admission prunes in place, and a rejected
                    # write must not have reached the stored status subtree
                    # through a shared reference.
                    data["status"] = deep_copy_json(current["status"])
                else:
                    data.pop("status", None)
                # Admission before the store swap: a rejected replace
                # must leave the stored object untouched.
                self._admit_custom_locked(data)
            # managedFields is server-owned: ownership moves to the writer
            # for every field this write changed (client-sent managedFields
            # is ignored, like a real apiserver preserving when unset).
            reassign_on_write(
                old,
                data,
                field_manager,
                rfc3339_now(),
                subresource="status" if status_only else "",
            )
            self._sync_generation(data, old)
            if dry_run:
                return wrap(data)
            self._bump(data)
            self._store_put(key, data)
            if not self._write_becomes_delete(data):
                self._emit(_WATCH_MODIFIED, data, old=old)
            if kind == "CustomResourceDefinition":
                if not status_only and self._auto_establish_crds:
                    # An updated CRD stays Established (the real apiserver
                    # re-establishes in place); already-served versions
                    # remain discoverable, and the served set refreshes
                    # to the new spec after the window.
                    data = self._establish_crd_locked(data)
                    self._schedule_discovery_refresh_locked(data)
                else:
                    # Manual-controller mode (or a status write): honor an
                    # Established condition however it got there.
                    self._sync_crd_discoverability_locked(data)
                    if not status_only:
                        self._schedule_discovery_refresh_locked(data)
            self._finalize_delete_if_due(kind, obj.name, obj.namespace, old=old)
            return wrap(deep_copy_json(data))

    def update(
        self,
        obj: KubeObject,
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        return self._replace(
            obj, status_only=False, field_manager=field_manager,
            dry_run=dry_run,
        )

    def update_status(
        self,
        obj: KubeObject,
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        return self._replace(
            obj, status_only=True, field_manager=field_manager,
            dry_run=dry_run,
        )

    def patch(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        patch: Optional[Mapping[str, Any] | list[Any]] = None,
        patch_type: str = "merge",
        field_manager: str = "",
        dry_run: bool = False,
    ) -> KubeObject:
        with self._lock:
            # Private payload copy: the merge engines may graft patch
            # subtrees into the object wholesale, and under the frozen-
            # store contract neither the store nor the journal may alias
            # caller memory. Patches are small; the copy is noise next to
            # the whole-object copies it prevents corrupting.
            payload = deep_copy_json(
                patch if isinstance(patch, list) else dict(patch or {})
            )
            self._react("patch", kind, {"name": name, "namespace": namespace,
                                        "patch": payload,
                                        "patch_type": patch_type})
            key = self._key(kind, namespace, name)
            # Copy-on-write: ``old`` stays the frozen stored dict (the
            # journal will take it by reference); all merging/admission
            # below mutates a private copy that is swapped in on success.
            old = self._get_raw(kind, name, namespace)
            current = deep_copy_json(old)
            if patch_type == "strategic" and not _supports_strategic(current):
                # Real-apiserver semantics: strategic merge patch only
                # exists for built-in typed resources (their Go structs
                # carry the patch tags); custom resources answer 415.
                raise UnsupportedMediaTypeError(
                    "strategic merge patch is not supported for custom "
                    f"resources ({current.get('apiVersion', '?')} {kind})"
                )
            if patch_type == "strategic":
                strategic_merge_patch(current, payload)  # type: ignore[arg-type]
            elif patch_type == "merge":
                merge_patch(current, payload)  # type: ignore[arg-type]
            elif patch_type == "json":
                # A non-list patch is a caller bug json_patch rejects
                # with 400 — matching RestClient's client-side guard, so
                # the two backends never diverge on this.
                json_patch(current, payload)
            else:
                raise InvalidError(
                    f"unsupported patch type {patch_type!r} "
                    "(expected 'merge', 'strategic', or 'json')"
                )
            # A patch cannot rename or unscope the object (a real
            # apiserver answers 422 to attempts; restoring is our lenient
            # equivalent, and keeps the stored key and the object's own
            # metadata consistent).
            meta = current.setdefault("metadata", {})
            meta["name"] = name
            old_ns = (old.get("metadata") or {}).get("namespace")
            if old_ns:
                meta["namespace"] = old_ns
            else:
                meta.pop("namespace", None)
            # A rejected write leaves no trace: ``current`` is private,
            # so admission failure just raises — the store was never
            # touched.
            self._admit_custom_locked(current)
            # Ownership follows the write (managedFields is server-owned;
            # a patch cannot rewrite it directly).
            reassign_on_write(old, current, field_manager, rfc3339_now())
            self._sync_generation(current, old)
            if dry_run:
                return wrap(current)
            self._bump(current)
            self._store_put(key, current)
            if not self._write_becomes_delete(current):
                self._emit(_WATCH_MODIFIED, current, old=old)
            if kind == "CustomResourceDefinition":
                self._sync_crd_discoverability_locked(current)
                touched_spec = (
                    any(_jp_op_touches_spec(op) for op in patch)
                    if isinstance(patch, list)
                    else "spec" in (patch or {})
                )
                if touched_spec:
                    # A spec patch can add served versions — existing ones
                    # stay served; the set refreshes after the window
                    # (same as _replace).
                    self._schedule_discovery_refresh_locked(current)
            self._finalize_delete_if_due(kind, name, namespace, old=old)
            return wrap(deep_copy_json(current))

    def apply(
        self,
        obj: KubeObject | Mapping[str, Any],
        field_manager: str,
        force: bool = False,
        dry_run: bool = False,
    ) -> KubeObject:
        """Server-side apply (``application/apply-patch+yaml``): merge the
        manager's declared intent into the live object, tracking field
        ownership in ``metadata.managedFields``. Creates the object when
        absent. Fields the manager declared on a previous apply and omits
        now are removed (unless co-owned); a field owned by another
        manager with a different value raises ConflictError (409, message
        lists the owners) unless ``force`` — the upstream co-management
        contract (kube/ssa.py).
        """
        applied = deep_copy_json(
            obj.raw if isinstance(obj, KubeObject) else dict(obj)
        )
        kind = applied.get("kind", "")
        meta = applied.setdefault("metadata", {})
        name = meta.get("name", "")
        namespace = meta.get("namespace", "")
        if not kind or not name:
            raise InvalidError("apply requires kind and metadata.name")
        if not field_manager:
            raise BadRequestError(
                "fieldManager is required for apply requests"
            )
        # Server-owned bookkeeping a client may have round-tripped never
        # enters the applied intent.
        for f in (
            "uid",
            "resourceVersion",
            "creationTimestamp",
            "generation",
            "selfLink",
            "deletionTimestamp",
        ):
            meta.pop(f, None)
        with self._lock:
            self._react(
                "apply",
                kind,
                {
                    "name": name,
                    "namespace": namespace,
                    "manager": field_manager,
                    "force": force,
                },
            )
            key = self._key(kind, namespace, name)
            now = rfc3339_now()
            if key not in self._store:
                # Create-through-apply: an empty shell takes the full
                # config, then rides the normal create path (uid, rv,
                # watch ADDED, CRD establishment).
                live: dict[str, Any] = {
                    "apiVersion": applied.get("apiVersion"),
                    "kind": kind,
                    "metadata": {"name": name},
                }
                if namespace:
                    live["metadata"]["namespace"] = namespace
                server_side_apply(live, applied, field_manager, force, now)
                return self.create(wrap(live), dry_run=dry_run)
            # Copy-on-write (see patch): merge into a private copy, swap
            # it in on success; the frozen stored dict becomes ``old``.
            old = self._get_raw(kind, name, namespace)
            current = deep_copy_json(old)
            if "status" in current:
                # Main-resource writes never touch the status subresource
                # (same rule as _replace).
                applied.pop("status", None)
            server_side_apply(current, applied, field_manager, force, now)
            # Same identity pinning as patch.
            cur_meta = current.setdefault("metadata", {})
            cur_meta["name"] = name
            old_ns = (old.get("metadata") or {}).get("namespace")
            if old_ns:
                cur_meta["namespace"] = old_ns
            else:
                cur_meta.pop("namespace", None)
            self._admit_custom_locked(current)
            self._sync_generation(current, old)
            if dry_run:
                return wrap(current)
            self._bump(current)
            self._store_put(key, current)
            if not self._write_becomes_delete(current):
                self._emit(_WATCH_MODIFIED, current, old=old)
            if kind == "CustomResourceDefinition":
                self._sync_crd_discoverability_locked(current)
                if "spec" in applied:
                    self._schedule_discovery_refresh_locked(current)
            self._finalize_delete_if_due(kind, name, namespace, old=old)
            return wrap(deep_copy_json(current))

    def delete(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        grace_period_seconds: Optional[int] = None,
        propagation_policy: Optional[str] = None,
        precondition_uid: Optional[str] = None,
        precondition_resource_version: Optional[str] = None,
        dry_run: bool = False,
    ) -> None:
        """Delete with owner-reference garbage collection.

        ``propagation_policy`` follows DeleteOptions: ``Background``
        (default — dependents are collected after the owner goes),
        ``Foreground`` (the owner lingers with the ``foregroundDeletion``
        finalizer until every dependent is gone), ``Orphan`` (dependents
        survive with the owner's reference stripped). The GC controller
        behavior is ON by default like a real cluster — note the
        reference's envtest has NO controller-manager, so there cascade
        deletion never happens; construct
        ``FakeCluster(enable_owner_gc=False)`` to emulate that.

        ``precondition_uid`` / ``precondition_resource_version`` follow
        DeleteOptions.preconditions: a mismatch answers 409 Conflict —
        the guard against deleting a same-named object that was
        deleted-and-recreated (or changed) since it was last read.
        """
        if propagation_policy not in (
            None, "Background", "Foreground", "Orphan"
        ):
            raise BadRequestError(
                f"invalid propagationPolicy {propagation_policy!r}"
            )
        with self._lock:
            self._react("delete", kind, {"name": name, "namespace": namespace})
            key = self._key(kind, namespace, name)
            data = self._get_raw(kind, name, namespace)
            meta = data.get("metadata") or {}
            if (
                precondition_uid is not None
                and meta.get("uid") != precondition_uid
            ):
                raise ConflictError(
                    f"the UID in the precondition ({precondition_uid}) does "
                    f"not match the UID in record ({meta.get('uid')})"
                )
            if (
                precondition_resource_version is not None
                and str(meta.get("resourceVersion"))
                != str(precondition_resource_version)
            ):
                raise ConflictError(
                    "the ResourceVersion in the precondition "
                    f"({precondition_resource_version}) does not match the "
                    f"record ({meta.get('resourceVersion')})"
                )
            if dry_run:
                # Existence and preconditions verified; nothing deleted.
                return
            uid = meta.get("uid", "")
            gc = self._enable_owner_gc and bool(uid)
            policy = propagation_policy or "Background"
            if gc and policy == "Orphan":
                self._gc_orphan_dependents(uid)
                gc = False  # orphaned: nothing to collect afterwards
            dependents = self._gc_dependents(uid) if gc else []
            if gc and policy == "Foreground" and dependents:
                # Copy-on-write: mark the private copy, swap it in.
                old = data
                data = deep_copy_json(old)
                work_meta = data["metadata"]
                changed = False
                if not work_meta.get("deletionTimestamp"):
                    work_meta["deletionTimestamp"] = time.time()
                    changed = True
                finalizers = work_meta.setdefault("finalizers", [])
                # Appended even on an already-terminating owner — the
                # foreground guarantee must hold regardless of which
                # delete marked the timestamp first.
                if "foregroundDeletion" not in finalizers:
                    finalizers.append("foregroundDeletion")
                    changed = True
                if changed:
                    self._bump(data)
                    self._store_put(key, data)
                    self._emit(_WATCH_MODIFIED, data, old=old)
                for dkind, dns, dname in dependents:
                    # Foreground propagates DOWN the chain (the real GC's
                    # rule): a child must in turn wait for ITS blocking
                    # dependents, so an owner can never finalize while a
                    # blocking grandchild survives.
                    self.delete(
                        dkind, dname, dns, propagation_policy="Foreground"
                    )
                self._gc_foreground_sweep()
                return
            if meta.get("finalizers"):
                if not meta.get("deletionTimestamp"):
                    old = data
                    data = deep_copy_json(old)
                    data["metadata"]["deletionTimestamp"] = time.time()
                    self._bump(data)
                    self._store_put(key, data)
                    self._emit(_WATCH_MODIFIED, data, old=old)
                return
            self._store_del(key)
            if kind == "CustomResourceDefinition":
                self._discoverable.pop(name, None)
            # The DELETED event carries a bumped rv (see
            # _finalize_delete_if_due); the bump mutates, so it lands on
            # a private copy — the stored dict may already be journaled.
            data = deep_copy_json(data)
            self._bump(data)
            self._emit(_WATCH_DELETED, data)
            if gc:
                self._gc_on_owner_removed(uid)

    # -- owner-reference garbage collection (real-cluster semantics) ------

    def _gc_dependents(
        self, uid: str, blocking_only: bool = False
    ) -> list[tuple[str, str, str]]:
        """(kind, namespace, name) of every live object referencing uid;
        ``blocking_only`` restricts to references carrying
        ``blockOwnerDeletion: true`` — the only dependents a Foreground
        owner waits for on a real cluster."""
        out = []
        for key in list(self._owner_index.get(uid, ())):
            data = self._store.get(key)
            if data is None:
                continue
            refs = (data.get("metadata") or {}).get("ownerReferences") or []
            if any(
                r.get("uid") == uid
                and (not blocking_only or r.get("blockOwnerDeletion"))
                for r in refs
            ):
                out.append(key)
        return out

    def _gc_orphan_dependents(self, uid: str) -> None:
        for dkind, dns, dname in self._gc_dependents(uid):
            dkey = self._key(dkind, dns, dname)
            dep = self._store.get(dkey)
            if dep is None:
                continue
            old = dep
            dep = deep_copy_json(old)
            meta = dep["metadata"]
            refs = [
                r for r in meta.get("ownerReferences") or []
                if r.get("uid") != uid
            ]
            if refs:
                meta["ownerReferences"] = refs
            else:
                meta.pop("ownerReferences", None)
            self._bump(dep)
            self._store_put(dkey, dep)
            self._emit(_WATCH_MODIFIED, dep, old=old)

    def _gc_on_owner_removed(self, uid: str) -> None:
        """The GC controller's reaction to a vanished owner: a dependent
        with other live owners keeps the object and only drops the
        dangling reference; a dependent owned solely by the vanished
        owner is collected with a plain delete (recursively) — its
        ownerReferences stay intact while it terminates, exactly as a
        real cluster's watch stream shows."""
        for dkind, dns, dname in self._gc_dependents(uid):
            dkey = self._key(dkind, dns, dname)
            dep = self._store.get(dkey)
            if dep is None:
                continue
            refs = [
                r for r in (dep.get("metadata") or {}).get("ownerReferences")
                or []
                if r.get("uid") != uid
            ]
            if refs:
                old = dep
                dep = deep_copy_json(old)
                dep["metadata"]["ownerReferences"] = refs
                self._bump(dep)
                self._store_put(dkey, dep)
                self._emit(_WATCH_MODIFIED, dep, old=old)
            else:
                self.delete(dkind, dname, dns)
        self._gc_foreground_sweep()

    def _gc_foreground_sweep(self) -> None:
        """Release ``foregroundDeletion`` finalizers whose owners have no
        BLOCKING dependents left (``blockOwnerDeletion: true`` — other
        dependents never hold a foreground owner on a real cluster);
        fully-released owners finalize and cascade. Caller holds the
        lock (re-entrant: the cascade re-enters ``delete``). Visits only
        ``_fg_pending`` — the keys _store_put indexed as
        foreground-terminating — so the per-delete cost is O(pending),
        not O(store)."""
        for key in list(self._fg_pending):
            data = self._store.get(key)
            if data is None:
                continue
            meta = data.get("metadata") or {}
            finalizers = meta.get("finalizers") or []
            if (
                "foregroundDeletion" not in finalizers
                or not meta.get("deletionTimestamp")
                or self._gc_dependents(
                    meta.get("uid", ""), blocking_only=True
                )
            ):
                continue
            old = data
            data = deep_copy_json(old)
            meta = data["metadata"]
            finalizers = [f for f in finalizers if f != "foregroundDeletion"]
            if finalizers:
                meta["finalizers"] = finalizers
                self._bump(data)
                self._store_put(key, data)
                self._emit(_WATCH_MODIFIED, data, old=old)
                continue
            meta.pop("finalizers", None)
            kind, _, name = key
            self._store_del(key)
            if kind == "CustomResourceDefinition":
                self._discoverable.pop(name, None)
            self._bump(data)
            self._emit(_WATCH_DELETED, data, old=old)
            if self._enable_owner_gc and meta.get("uid"):
                self._gc_on_owner_removed(meta["uid"])

    def evict(
        self, pod_name: str, namespace: str = "", dry_run: bool = False
    ) -> None:
        with self._lock:
            self._react("evict", "Pod", {"name": pod_name, "namespace": namespace})
            self.delete("Pod", pod_name, namespace, dry_run=dry_run)

    # -- test conveniences -------------------------------------------------
    def close(self) -> None:
        """Cancel pending delayed-establish timers (test teardown hygiene)."""
        for timer in self._pending_timers:
            timer.cancel()
        self._pending_timers.clear()

    def load(self, *objs: KubeObject) -> list[KubeObject]:
        return [self.create(o) for o in objs]

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)
