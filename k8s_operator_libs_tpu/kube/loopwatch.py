"""Event-loop stall watchdog — the runtime twin of the ASY601 static
pass (docs/static-analysis.md "Async discipline").

The static analyzer proves no *known-blocking* call is reachable on the
wire loop; this watchdog catches what the proof cannot see — unresolved
dispatch, C extensions, pathological CPU-bound callbacks — by measuring
the loop's own heartbeat. A coroutine on the watched loop sleeps
``interval_s`` and measures how late each wakeup arrives **on the loop
itself**: any callback that holds the loop for S seconds delays the
heartbeat by ~S (the loop cannot run the wakeup while a callback
blocks), so the observed lateness IS the worst-case stall every other
task on the loop experienced. Slow-callback instrumentation without
wrapping a single callback, at ~50 no-op wakeups/s.

Exported as the ``tpu_operator_wire_loop_stall_*`` counter/max-seconds
pair through :class:`~..upgrade.metrics.WireMetrics`; the
``http_wire_roll`` and ``report_storm`` bench sections hard-assert zero
stalls over threshold (tools/bench_smoke_baseline.json).

Caveats: resolution is ``interval_s`` (sub-interval stalls read as 0);
whole-process descheduling (machine suspend, a CI runner page-storm)
also delays the heartbeat — the default threshold is chosen well above
scheduler jitter and well below any real blocking call (socket
timeouts, sleeps, subprocess waits are all ≥ hundreds of ms).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from ..utils.lifecycle import lifecycle_resource

#: Default stall threshold: far above GIL/scheduler jitter (tens of ms
#: even on loaded CI runners), far below any genuine blocking call on
#: the wire path (transport timeouts are seconds).
DEFAULT_STALL_THRESHOLD_S = 0.5

#: Heartbeat cadence — the measurement resolution.
DEFAULT_HEARTBEAT_INTERVAL_S = 0.02


@lifecycle_resource(acquire="start", release="stop")
class LoopStallWatchdog:
    """Heartbeat-gap stall detector for one event loop.

    Counters are written on the loop thread and read from any thread
    (single-field int/float reads are GIL-atomic — the wire-counter
    convention of ``kube/rest.py``/``kube/apiserver.py``).
    """

    def __init__(
        self,
        loop: asyncio.AbstractEventLoop,
        threshold_s: float = DEFAULT_STALL_THRESHOLD_S,
        interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    ) -> None:
        self._loop = loop
        self.threshold_s = float(threshold_s)
        self.interval_s = float(interval_s)
        #: Heartbeat wakeups that arrived >= threshold late — each one
        #: is a distinct window in which the loop could not run.
        self.stalls_over_threshold = 0
        #: Worst observed lateness (seconds) since start()/reset().
        self.max_stall_s = 0.0
        self.heartbeats = 0
        self.stopped = False
        self._task: Optional[asyncio.Task] = None
        #: Last heartbeat wakeup (loop clock); loop-thread only, and
        #: refreshed by reset()'s dispatched zeroing so a stall in
        #: flight when reset() lands is not billed to the new window.
        self._last_beat = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "LoopStallWatchdog":
        """Install the heartbeat task; safe from any thread, before or
        after the loop starts running."""

        def _install() -> None:
            """Runs on the watched loop."""
            if not self.stopped:
                self._task = self._loop.create_task(self._beat())

        self._loop.call_soon_threadsafe(_install)
        return self

    def stop(self) -> None:
        self.stopped = True
        task = self._task
        if task is not None:
            try:
                self._loop.call_soon_threadsafe(task.cancel)
            except RuntimeError:
                pass  # loop already closed; the task died with it

    def reset(self, wait_s: float = 5.0) -> None:
        """Zero the counters (benchmark windows measure from here).
        Callable from any thread: the write is dispatched to the
        watched loop — the counters are loop-bound state, so the zeroing
        serializes with the heartbeat instead of racing it (the ASY604
        discipline, applied to the watchdog itself) — and the caller
        blocks until it lands (bounded by ``wait_s``), so counters read
        after ``reset()`` returns never show the previous window."""
        done = threading.Event()

        def _zero() -> None:
            """Runs on the watched loop."""
            self.stalls_over_threshold = 0
            self.max_stall_s = 0.0
            self.heartbeats = 0
            # A stall in flight while reset() was called belongs to the
            # PREVIOUS window: restart the lateness clock from now so
            # the next heartbeat does not bill it to the fresh one.
            self._last_beat = self._loop.time()
            done.set()

        try:
            self._loop.call_soon_threadsafe(_zero)
        except RuntimeError:
            _zero()  # loop already closed: no heartbeat left to race
            return
        done.wait(wait_s)

    async def _beat(self) -> None:
        """Runs on the watched loop."""
        loop = asyncio.get_running_loop()
        self._last_beat = loop.time()
        try:
            while not self.stopped:
                await asyncio.sleep(self.interval_s)
                now = loop.time()
                stall = now - self._last_beat - self.interval_s
                self._last_beat = now
                self.heartbeats += 1
                if stall > self.max_stall_s:
                    self.max_stall_s = stall
                if stall >= self.threshold_s:
                    self.stalls_over_threshold += 1
        except asyncio.CancelledError:
            raise

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        """The ``tpu_operator_wire_loop_stall_*`` feed."""
        return {
            "stalls_over_threshold": self.stalls_over_threshold,
            "max_stall_s": round(self.max_stall_s, 4),
            "threshold_s": self.threshold_s,
            "heartbeats": self.heartbeats,
        }


# -- the shared client wire loop ------------------------------------------

_wire_watchdog: Optional[LoopStallWatchdog] = None
_wire_watchdog_lock = threading.Lock()


def install_wire_loop_watchdog(
    threshold_s: float = DEFAULT_STALL_THRESHOLD_S,
    interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
) -> LoopStallWatchdog:
    """Start (or return) the process-wide watchdog on the shared client
    wire loop (``kube/rest.py``). Idempotent per loop: a second install
    returns the live watchdog with the REQUESTED threshold/interval
    applied (both are live-tunable — the heartbeat reads them per
    wakeup), so the advertised tuning knob works regardless of who
    installed first; callers that need a fresh measurement window use
    :meth:`LoopStallWatchdog.reset`."""
    from .rest import _get_wire_loop

    global _wire_watchdog
    with _wire_watchdog_lock:
        loop = _get_wire_loop()
        watchdog = _wire_watchdog
        if (watchdog is not None and watchdog._loop is loop
                and not watchdog.stopped):
            watchdog.threshold_s = float(threshold_s)
            watchdog.interval_s = float(interval_s)
            return watchdog
        _wire_watchdog = LoopStallWatchdog(
            loop, threshold_s=threshold_s, interval_s=interval_s
        ).start()
        return _wire_watchdog


def wire_loop_stall_stats() -> dict:
    """Stats of the shared wire-loop watchdog; ``{}`` when none is
    installed (WireMetrics renders nothing then)."""
    watchdog = _wire_watchdog
    return watchdog.stats() if watchdog is not None else {}
