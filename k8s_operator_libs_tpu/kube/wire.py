"""Shared wire layer: compact object encoding + content negotiation +
watch-frame protocol, used by both ends of the HTTP data plane
(``rest.RestClient`` and ``apiserver.LocalApiServer``).

A real apiserver negotiates ``application/vnd.kubernetes.protobuf`` next
to JSON: the client lists both in ``Accept`` and the server answers in
the densest encoding it shares with the caller, JSON remaining the
protocol default for anyone who does not ask. This module is that
contract for the library's own data plane, with a self-contained compact
encoding instead of protobuf (no generated descriptors, no vendored
runtime — stdlib only, like the rest of the wire path):

* **Compact encoding** — a binary serialization of the JSON data model
  (None/bool/int/float/str/list/dict) with varint lengths and a
  per-message *key table*: the first occurrence of a dict key travels as
  UTF-8, every repeat as a one-or-two-byte back-reference. Kubernetes
  payloads repeat keys relentlessly (every item in a NodeList carries
  the same ~40 key strings), which is exactly the redundancy protobuf's
  field tags remove — the key table removes the same redundancy without
  a schema.
* **Negotiation** — ``negotiate_encoding`` picks the response encoding
  from the request's ``Accept`` header; ``decode_body`` dispatches on a
  response/request ``Content-Type``. Unknown or absent headers always
  degrade to JSON, so an old JSON-only peer on either side keeps
  working untouched.
* **Watch frames** — one watch event per frame. JSON streams stay
  newline-delimited (the shape ``kubectl get -w`` and the previous
  client consumed); compact streams are length-prefixed
  (4-byte big-endian length, then the compact payload), the standard
  protobuf-over-HTTP watch framing shape.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Iterator, Optional

#: The negotiated compact media type (``;v=1`` so a future layout bump
#: can coexist); matched by prefix on both ends.
COMPACT_CONTENT_TYPE = "application/vnd.tpu-operator.compact;v=1"
_COMPACT_PREFIX = "application/vnd.tpu-operator.compact"
JSON_CONTENT_TYPE = "application/json"

#: What a compact-speaking client sends: prefer compact, accept JSON —
#: an old server that has never heard of the compact type answers JSON
#: and nothing breaks (the negotiation-fallback contract).
CLIENT_ACCEPT_COMPACT = f"{COMPACT_CONTENT_TYPE}, application/json"

# -- type tags -------------------------------------------------------------
_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_LIST = 0x06
_T_DICT = 0x07
_K_DEF = 0x00  # key literal: assigns the next key-table index
_K_REF = 0x01  # key back-reference by index

_pack_float = struct.Struct(">d").pack
_unpack_float = struct.Struct(">d").unpack_from
_FRAME_HEADER = struct.Struct(">I")


class WireDecodeError(ValueError):
    """Malformed compact payload (truncated, bad tag, bad key ref)."""


def _append_varint(buf: bytearray, value: int) -> None:
    while value > 0x7F:
        buf.append((value & 0x7F) | 0x80)
        value >>= 7
    buf.append(value)


def _encode_value(buf: bytearray, value: Any, keys: dict[str, int]) -> None:
    if value is None:
        buf.append(_T_NONE)
    elif value is True:
        buf.append(_T_TRUE)
    elif value is False:
        buf.append(_T_FALSE)
    elif isinstance(value, int):
        buf.append(_T_INT)
        # zigzag so negatives stay short
        _append_varint(buf, value << 1 if value >= 0
                       else ((-value) << 1) - 1)
    elif isinstance(value, float):
        buf.append(_T_FLOAT)
        buf += _pack_float(value)
    elif isinstance(value, str):
        buf.append(_T_STR)
        raw = value.encode("utf-8")
        _append_varint(buf, len(raw))
        buf += raw
    elif isinstance(value, (list, tuple)):
        buf.append(_T_LIST)
        _append_varint(buf, len(value))
        for item in value:
            _encode_value(buf, item, keys)
    elif isinstance(value, dict):
        buf.append(_T_DICT)
        _append_varint(buf, len(value))
        for key, item in value.items():
            if not isinstance(key, str):
                raise TypeError(
                    f"compact encoding requires str keys, got {type(key)}"
                )
            index = keys.get(key)
            if index is None:
                keys[key] = len(keys)
                buf.append(_K_DEF)
                raw = key.encode("utf-8")
                _append_varint(buf, len(raw))
                buf += raw
            else:
                buf.append(_K_REF)
                _append_varint(buf, index)
            _encode_value(buf, item, keys)
    else:
        raise TypeError(
            f"compact encoding cannot serialize {type(value).__name__}"
        )


def encode_compact(obj: Any) -> bytes:
    """Serialize a JSON-model value to the compact wire form."""
    buf = bytearray()
    _encode_value(buf, obj, {})
    return bytes(buf)


class _Reader:
    __slots__ = ("data", "pos", "keys")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0
        self.keys: list[str] = []

    def byte(self) -> int:
        try:
            b = self.data[self.pos]
        except IndexError:
            raise WireDecodeError("truncated compact payload") from None
        self.pos += 1
        return b

    def varint(self) -> int:
        shift = 0
        out = 0
        while True:
            b = self.byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 127:  # bounds a hostile stream; ints are unbounded
                raise WireDecodeError("varint overflow")

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if end > len(self.data):
            raise WireDecodeError("truncated compact payload")
        out = self.data[self.pos:end]
        self.pos = end
        return out


def _decode_value(r: _Reader) -> Any:
    tag = r.byte()
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        z = r.varint()
        return (z >> 1) if not z & 1 else -((z + 1) >> 1)
    if tag == _T_FLOAT:
        (out,) = _unpack_float(r.take(8))
        return out
    if tag == _T_STR:
        return r.take(r.varint()).decode("utf-8")
    if tag == _T_LIST:
        return [_decode_value(r) for _ in range(r.varint())]
    if tag == _T_DICT:
        out = {}
        for _ in range(r.varint()):
            kind = r.byte()
            if kind == _K_DEF:
                key = r.take(r.varint()).decode("utf-8")
                r.keys.append(key)
            elif kind == _K_REF:
                index = r.varint()
                try:
                    key = r.keys[index]
                except IndexError:
                    raise WireDecodeError(
                        f"key back-reference {index} out of range"
                    ) from None
            else:
                raise WireDecodeError(f"bad key tag 0x{kind:02x}")
            out[key] = _decode_value(r)
        return out
    raise WireDecodeError(f"bad type tag 0x{tag:02x}")


def decode_compact(data: bytes) -> Any:
    """Parse a compact payload back into the JSON data model."""
    r = _Reader(data)
    out = _decode_value(r)
    if r.pos != len(data):
        raise WireDecodeError(
            f"{len(data) - r.pos} trailing bytes after compact payload"
        )
    return out


# -- content negotiation ---------------------------------------------------
def is_compact_content_type(content_type: Optional[str]) -> bool:
    return bool(content_type) and content_type.strip().lower().startswith(
        _COMPACT_PREFIX
    )


def negotiate_encoding(accept_header: Optional[str]) -> str:
    """Server-side pick from the request's ``Accept``: ``"compact"``
    only when the caller listed the compact media type, ``"json"``
    otherwise (including no header at all) — JSON stays the protocol
    default, exactly the real apiserver's protobuf posture."""
    for clause in (accept_header or "").split(","):
        if clause.split(";", 1)[0].strip().lower() == _COMPACT_PREFIX:
            return "compact"
        # Parameterized spelling: the ;v=1 travels as a media-type
        # parameter, so the prefix match above already caught it.
    return "json"


def content_type_for(encoding: str) -> str:
    return COMPACT_CONTENT_TYPE if encoding == "compact" else JSON_CONTENT_TYPE


def encode_body(obj: Any, encoding: str) -> bytes:
    if encoding == "compact":
        return encode_compact(obj)
    return json.dumps(obj).encode()


def decode_body(data: bytes, content_type: Optional[str]) -> Any:
    """Decode a request/response body by its ``Content-Type`` — the
    client never guesses what the server sent, and vice versa."""
    if is_compact_content_type(content_type):
        return decode_compact(data)
    return json.loads(data)


# -- watch frame protocol --------------------------------------------------
def encode_watch_frame(event: dict, encoding: str) -> bytes:
    """One watch event as one wire frame. JSON: a newline-delimited
    line (the previous stream shape — old consumers keep reading it).
    Compact: 4-byte big-endian length prefix + compact payload."""
    if encoding == "compact":
        payload = encode_compact(event)
        return _FRAME_HEADER.pack(len(payload)) + payload
    return json.dumps(event).encode() + b"\n"


class FrameDecoder:
    """Incremental watch-frame decoder for one stream direction.

    Feed raw bytes as they arrive (chunk boundaries are transport
    noise — frames may span chunks and chunks may hold many frames);
    iterate decoded events. The encoding is fixed per stream by the
    response ``Content-Type``."""

    def __init__(self, content_type: Optional[str]) -> None:
        self.compact = is_compact_content_type(content_type)
        self._buf = bytearray()

    def feed(self, data: bytes) -> Iterator[dict]:
        self._buf += data
        if self.compact:
            while len(self._buf) >= 4:
                (length,) = _FRAME_HEADER.unpack_from(self._buf)
                if len(self._buf) < 4 + length:
                    return
                payload = bytes(self._buf[4:4 + length])
                del self._buf[:4 + length]
                yield decode_compact(payload)
        else:
            while True:
                newline = self._buf.find(b"\n")
                if newline < 0:
                    return
                line = bytes(self._buf[:newline])
                del self._buf[:newline + 1]
                if line.strip():
                    yield json.loads(line)

    @property
    def pending_bytes(self) -> int:
        """Bytes buffered without a complete frame — nonzero at stream
        end means a truncated tail (the stream died mid-frame)."""
        return len(self._buf)
