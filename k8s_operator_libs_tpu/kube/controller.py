"""Controller runtime — watches feed a rate-limited workqueue feeding
reconcile workers.

This is the layer the reference *assumes around itself*: its public
contract is "call ``BuildState``/``ApplyState`` from your ``Reconcile``
loop" (`/root/reference/pkg/upgrade/upgrade_state.go:35-53`), with watch
predicates shipped for exactly that wiring
(`/root/reference/pkg/upgrade/upgrade_requestor.go:93-159`), and
controller-runtime (`/root/reference/go.mod:5`) supplying the loop:
sources (informers) → event handlers mapping objects to request keys →
a rate-limited workqueue → N workers invoking ``Reconcile(request) ->
(Result, error)``. Here that runtime exists natively so a consumer
operator of this framework gets the same shape:

* ``Request`` — the (namespace, name) reconcile key; hashable, deduped
  by the queue (many events for one object collapse into one pass).
* ``Result`` — ``requeue``/``requeue_after``, with controller-runtime's
  outcome contract: an exception re-queues with per-item exponential
  backoff; ``requeue_after`` schedules a clean timed revisit and resets
  backoff; plain success resets backoff.
* ``Controller.watch(informer, ...)`` — register a source with an
  optional plain-function predicate (the requestor predicates plug in
  unchanged) and an optional mapper (EnqueueRequestForObject is the
  default; a mapper is EnqueueRequestsFromMapFunc).

The workqueue's dirty/processing invariant guarantees a key is never
reconciled concurrently with itself even with ``max_concurrent > 1`` —
the same one-reconcile-at-a-time contract the reference's state
machine depends on (`node_upgrade_state_provider.go:92-99` rationale).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Iterable, NamedTuple, Optional

from .informer import Informer
from .objects import KubeObject
from .workqueue import RateLimitingQueue
from ..utils.log import get_logger

log = get_logger("kube.controller")


class Request(NamedTuple):
    """The reconcile key: controller-runtime's ``reconcile.Request``
    (a NamespacedName). Hashable so the workqueue can dedup it."""

    namespace: str
    name: str


@dataclass(frozen=True)
class Result:
    """controller-runtime ``reconcile.Result``. ``requeue_after > 0``
    wins over ``requeue`` (same precedence as upstream)."""

    requeue: bool = False
    requeue_after: float = 0.0


#: predicate signature: (event_type, obj, old) -> bool — the same plain
#: functions the requestor-mode predicates already use.
Predicate = Callable[[str, KubeObject, Optional[KubeObject]], bool]
#: mapper signature: (event_type, obj, old) -> iterable of Requests.
Mapper = Callable[[str, KubeObject, Optional[KubeObject]], Iterable[Request]]
#: reconciler: Request -> Result | None (None means plain success).
Reconciler = Callable[[Request], Optional[Result]]


class Controller:
    """N workers over a rate-limited queue, fed by informer watches.

    Lifecycle: construct with the reconciler, ``watch()`` sources, then
    ``start()`` (starts any informer not already running, waits for
    their initial sync so the first reconciles see a warm cache) and
    eventually ``stop()`` (drains nothing — in-flight reconciles finish,
    queued keys are dropped, informers this controller started are
    stopped)."""

    def __init__(
        self,
        reconciler: Reconciler,
        *,
        max_concurrent_reconciles: int = 1,
        rate_limiter=None,
        name: str = "controller",
    ) -> None:
        if max_concurrent_reconciles < 1:
            raise ValueError("max_concurrent_reconciles must be >= 1")
        self._reconciler = reconciler
        self.name = name
        self.max_concurrent_reconciles = max_concurrent_reconciles
        self.queue = RateLimitingQueue(rate_limiter)
        self._watches: list[Informer] = []
        # Informers THIS controller started (decided at start() time, not
        # watch() time): only these are stopped on stop(), so an informer
        # shared with other components is never torn down from here.
        self._owned: list[Informer] = []
        self._workers: list[threading.Thread] = []
        self._started = False
        self._lock = threading.Lock()

    # -- wiring ------------------------------------------------------------
    def watch(
        self,
        informer: Informer,
        *,
        predicate: Optional[Predicate] = None,
        mapper: Optional[Mapper] = None,
    ) -> "Controller":
        """Register a source. The default mapping is
        EnqueueRequestForObject — one ``Request`` per event object
        (DELETED included: controllers reconcile absence). A ``mapper``
        overrides it (EnqueueRequestsFromMapFunc), e.g. mapping a Pod
        event to its node's Request. Predicates run first and see
        ``(event_type, obj, old)``."""

        def handler(event: str, obj: KubeObject, old: Optional[KubeObject]):
            if predicate is not None:
                try:
                    if not predicate(event, obj, old):
                        return
                except Exception:  # noqa: BLE001 - predicate owns its errors
                    log.exception("%s: predicate failed; enqueueing anyway",
                                  self.name)
            if mapper is not None:
                try:
                    requests = list(mapper(event, obj, old))
                except Exception:  # noqa: BLE001 - mapper owns its errors
                    log.exception("%s: mapper failed; event dropped",
                                  self.name)
                    return
            else:
                requests = [Request(obj.namespace or "", obj.name)]
            for request in requests:
                self.queue.add(request)

        informer.add_event_handler(handler)
        self._watches.append(informer)
        return self

    def enqueue(self, request: Request) -> None:
        """Manual trigger (the GenericEvent channel analog)."""
        self.queue.add(request)

    def enqueue_after(self, request: Request, delay: float) -> None:
        self.queue.add_after(request, delay)

    # -- lifecycle ---------------------------------------------------------
    def start(self, sync_timeout: Optional[float] = 30.0) -> "Controller":
        with self._lock:
            if self._started:
                raise RuntimeError(f"{self.name} already started")
            self._started = True
        for informer in self._watches:
            if not informer.started:
                informer.start()
                self._owned.append(informer)
        for informer in self._watches:
            if not informer.wait_for_sync(sync_timeout):
                # Unwind cleanly: stop the informers THIS call started
                # (not shared ones) and allow a retry — a half-started
                # controller must not leak watch threads or wedge on
                # "already started".
                for owned in self._owned:
                    owned.stop()
                self._owned = []
                with self._lock:
                    self._started = False
                raise TimeoutError(
                    f"{self.name}: informer for {informer.kind} did not "
                    f"sync within {sync_timeout}s"
                )
        for i in range(self.max_concurrent_reconciles):
            worker = threading.Thread(
                target=self._worker, name=f"{self.name}-worker-{i}",
                daemon=True,
            )
            worker.start()
            self._workers.append(worker)
        return self

    def stop(self, drain_timeout: float = 0.0) -> None:
        """Shut down workers; ``drain_timeout > 0`` lets queued work
        finish first (ShutDownWithDrain)."""
        if drain_timeout > 0:
            self.queue.shutdown_with_drain(drain_timeout)
        self.queue.shutdown()
        for worker in self._workers:
            worker.join(timeout=10)
        for informer in self._owned:
            informer.stop()
        self._owned = []

    def __enter__(self) -> "Controller":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the worker loop ---------------------------------------------------
    def _worker(self) -> None:
        while True:
            item = self.queue.get()
            if item is None:
                return
            try:
                try:
                    result = self._reconciler(item) or Result()
                except Exception:  # noqa: BLE001 - the retry contract
                    log.exception(
                        "%s: reconcile of %s failed (requeue #%d)",
                        self.name, item, self.queue.num_requeues(item) + 1,
                    )
                    self.queue.add_rate_limited(item)
                else:
                    if result.requeue_after > 0:
                        # A timed revisit is not a failure: reset backoff
                        # so the NEXT failure starts from the base delay.
                        self.queue.forget(item)
                        self.queue.add_after(item, result.requeue_after)
                    elif result.requeue:
                        self.queue.add_rate_limited(item)
                    else:
                        self.queue.forget(item)
            finally:
                self.queue.done(item)
