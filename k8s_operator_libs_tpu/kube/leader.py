"""Lease-based leader election for controller daemons.

The reference library is hosted inside a controller-runtime Manager
(SURVEY §1 L6 — consumer operators call BuildState/ApplyState from their
Reconcile loop); managers provide leader election through client-go's
``tools/leaderelection`` package over a ``coordination.k8s.io/v1`` Lease.
This module is that facility for this framework's own controller daemon
(``examples/upgrade_controller.py --leader-elect``): only one replica
reconciles, standbys campaign, and a crashed leader is superseded after
the lease duration.

Semantics mirror client-go's leaderelection.go:

* **Acquire** — create the Lease if absent, or take it over when the
  observed holder has not renewed within ``lease_duration_s`` *as seen by
  this process's own clock* (the "observed record age" rule: followers
  time from when they last SAW the record change, never from the
  renewTime stamp inside it, so wall-clock skew between replicas cannot
  cause a false steal). Takeover increments ``leaseTransitions``.
* **Renew** — the leader updates ``renewTime`` every ``retry_period_s``;
  if no renewal succeeds for ``renew_deadline_s`` the elector reports
  leadership lost. Losing the lease is FATAL to the caller by convention
  (controller-runtime exits the process; the example controller does the
  same) — a deposed leader must never keep reconciling.
* **Release** — graceful stop clears ``holderIdentity`` so a standby can
  acquire immediately instead of waiting out the lease duration
  (client-go's ReleaseOnCancel).

All writes go through optimistic concurrency (update-with-resourceVersion;
``ConflictError`` = lost the race, re-observe next round) — the same
protocol the requestor mode uses for shared NodeMaintenance CRs
(reference: upgrade_requestor.go:320-368).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Any, Callable, Optional

from .client import ApiError, Client, ConflictError, NotFoundError
from .objects import Lease
from ..utils import tracing
from ..utils.faultpoints import fault_point
from ..utils.lifecycle import lifecycle_resource

log = logging.getLogger(__name__)


def _rfc3339_micro(now_wall: float) -> str:
    return (
        datetime.fromtimestamp(now_wall, tz=timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    )


@dataclass
class LeaderElectionConfig:
    """Tuning mirrors client-go's defaults (15s/10s/2s)."""

    name: str
    namespace: str
    identity: str
    lease_duration_s: float = 15.0
    renew_deadline_s: float = 10.0
    retry_period_s: float = 2.0
    on_started_leading: Optional[Callable[[], None]] = None
    on_stopped_leading: Optional[Callable[[], None]] = None
    on_new_leader: Optional[Callable[[str], None]] = None

    def __post_init__(self) -> None:
        if not self.identity:
            raise ValueError("leader election requires a non-empty identity")
        if self.renew_deadline_s >= self.lease_duration_s:
            raise ValueError(
                "renew_deadline_s must be shorter than lease_duration_s "
                "(a leader must notice loss before a standby can steal)"
            )
        if self.retry_period_s >= self.renew_deadline_s:
            raise ValueError(
                "retry_period_s must be shorter than renew_deadline_s"
            )


@dataclass
class _ObservedRecord:
    """What this process last saw in the Lease, and WHEN it saw it (local
    monotonic clock) — the skew-free liveness signal."""

    holder: str = ""
    raw_record: str = ""
    observed_at: float = 0.0
    transitions: int = 0
    resource_version: str = ""
    exists: bool = False


@lifecycle_resource(acquire="start", release="stop")
class LeaderElector:
    """Campaign for, hold, and release a Lease.

    Drive it either with the background thread (``start``/``stop``,
    ``wait_for_leadership``, ``is_leader``) or synchronously in tests via
    :meth:`try_acquire_or_renew` with an injected clock.
    """

    def __init__(
        self,
        client: Client,
        config: LeaderElectionConfig,
        now_fn: Callable[[], float] = time.monotonic,
        wall_fn: Callable[[], float] = time.time,
    ) -> None:
        self._client = client
        self.config = config
        self._now = now_fn
        self._wall = wall_fn
        self._observed = _ObservedRecord()
        self._leader_since: Optional[float] = None
        self._last_renew: float = 0.0
        self._leading = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- observation -------------------------------------------------------

    def _observe(self, lease: Optional[Lease]) -> None:
        """Record the Lease state; restart the liveness clock only when
        the record actually CHANGED (client-go's observedRecord rule)."""
        if lease is None:
            if self._observed.exists:
                self._observed = _ObservedRecord()
            return
        raw_record = "|".join(
            (
                lease.holder_identity,
                lease.renew_time,
                str(lease.lease_transitions),
            )
        )
        if raw_record != self._observed.raw_record:
            self._observed = _ObservedRecord(
                holder=lease.holder_identity,
                raw_record=raw_record,
                observed_at=self._now(),
                transitions=lease.lease_transitions,
                resource_version=lease.resource_version,
                exists=True,
            )
            if (
                self.config.on_new_leader is not None
                and lease.holder_identity
                and lease.holder_identity != self.config.identity
            ):
                self.config.on_new_leader(lease.holder_identity)
        else:
            # Same record, fresher resourceVersion is still worth keeping
            # for the next optimistic write.
            self._observed.resource_version = lease.resource_version

    def _lease_spec(self, acquire: bool) -> dict[str, Any]:
        spec: dict[str, Any] = {
            "holderIdentity": self.config.identity,
            "leaseDurationSeconds": int(self.config.lease_duration_s),
            "renewTime": _rfc3339_micro(self._wall()),
        }
        if acquire:
            spec["acquireTime"] = spec["renewTime"]
            spec["leaseTransitions"] = self._observed.transitions + (
                1 if self._observed.exists else 0
            )
        return spec

    # -- the acquire/renew primitive (client-go tryAcquireOrRenew) ---------

    def try_acquire_or_renew(self) -> bool:
        """One protocol round; returns True iff this identity holds the
        lease afterwards. Never raises on API errors (a flaky apiserver
        must surface as lost renewals, not a crashed elector)."""
        cfg = self.config
        # Lease attribution (docs/tracing.md): one span per protocol
        # round — a roll stalled behind failover shows as a run of
        # held=False lease spans. Null scope when tracing is off.
        with tracing.span(
            "lease.round", category="lease",
            lease=cfg.name, identity=cfg.identity,
        ) as round_span:
            held = self._try_acquire_or_renew()
            if round_span is not None:
                round_span.attrs["held"] = held
            return held

    def _try_acquire_or_renew(self) -> bool:
        cfg = self.config
        if fault_point("lease.round", name=cfg.name,
                       identity=cfg.identity) is not None:
            # Chaos fault point (docs/chaos-harness.md): the schedule
            # fails this protocol round exactly as a lost update race
            # would — the campaign's own retry/deadline machinery is
            # what's under test, so the fault must enter through it.
            return False
        try:
            lease = self._client.get("Lease", cfg.name, cfg.namespace)
        except NotFoundError:
            lease = None
        except ApiError as e:
            log.warning("leader election: get lease failed: %s", e)
            return False
        self._observe(lease)

        if lease is None:
            fresh = Lease.new(cfg.name, namespace=cfg.namespace)
            fresh.raw["spec"] = self._lease_spec(acquire=True)
            try:
                created = self._client.create(fresh)
            except ApiError as e:
                log.info("leader election: create lost the race: %s", e)
                return False
            self._observe(created)
            return True

        holder = lease.holder_identity
        if holder and holder != cfg.identity:
            age = self._now() - self._observed.observed_at
            if age < cfg.lease_duration_s:
                return False  # live leader elsewhere — stand by
            log.info(
                "leader election: lease %s/%s held by %r went stale "
                "(%.1fs unobserved); taking over",
                cfg.namespace, cfg.name, holder, age,
            )

        if holder == cfg.identity:
            # Renewal preserves the acquisition record (client-go keeps
            # acquireTime/leaseTransitions across renewals — only the
            # renewTime moves).
            lease.spec["holderIdentity"] = cfg.identity
            lease.spec["leaseDurationSeconds"] = int(cfg.lease_duration_s)
            lease.spec["renewTime"] = _rfc3339_micro(self._wall())
        else:
            lease.raw["spec"] = self._lease_spec(acquire=True)
        try:
            updated = self._client.update(lease)
        except ConflictError:
            log.info("leader election: renew/steal lost an update race")
            return False
        except ApiError as e:
            log.warning("leader election: update lease failed: %s", e)
            return False
        self._observe(updated)
        return True

    def release(self) -> None:
        """Clear holderIdentity if we hold the lease (ReleaseOnCancel):
        standbys acquire immediately instead of timing the lease out."""
        cfg = self.config
        try:
            lease = self._client.get("Lease", cfg.name, cfg.namespace)
            if lease.holder_identity != cfg.identity:
                return
            lease.spec["holderIdentity"] = ""
            lease.spec["renewTime"] = _rfc3339_micro(self._wall())
            self._client.update(lease)
        except NotFoundError:
            return  # never acquired — nothing to release
        except ApiError as e:
            log.warning("leader election: release failed: %s", e)

    # -- background campaign ----------------------------------------------

    def is_leader(self) -> bool:
        return self._leading.is_set()

    def wait_for_leadership(self, timeout: Optional[float] = None) -> bool:
        return self._leading.wait(timeout)

    def start(self) -> "LeaderElector":
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("elector already started")
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="leader-elector"
            )
            self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        self._stop.set()
        with self._lock:
            thread = self._thread
        if thread is not None:
            thread.join(timeout=30)
        self._leading.clear()
        if release:
            # Unconditionally, not gated on _leading: the campaign thread
            # can be stopped BETWEEN writing the Lease and marking itself
            # leader — release() is identity-guarded and tolerates both a
            # missing lease and another holder, so it is always safe.
            self.release()
        with self._lock:
            self._thread = None

    def _run(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            # Campaign.
            while not self._stop.is_set() and not self.try_acquire_or_renew():
                self._stop.wait(cfg.retry_period_s)
            if self._stop.is_set():
                return
            self._last_renew = self._now()
            self._leader_since = self._last_renew
            self._leading.set()
            log.info(
                "leader election: %r acquired %s/%s",
                cfg.identity, cfg.namespace, cfg.name,
            )
            if cfg.on_started_leading is not None:
                cfg.on_started_leading()
            # Renew until the deadline passes without a success.
            while not self._stop.is_set():
                self._stop.wait(cfg.retry_period_s)
                if self._stop.is_set():
                    return
                if self.try_acquire_or_renew():
                    self._last_renew = self._now()
                elif self._now() - self._last_renew > cfg.renew_deadline_s:
                    break
            self._leading.clear()
            self._leader_since = None
            log.warning(
                "leader election: %r LOST %s/%s (no renewal within %.1fs)",
                cfg.identity, cfg.namespace, cfg.name, cfg.renew_deadline_s,
            )
            if cfg.on_stopped_leading is not None:
                cfg.on_stopped_leading()
