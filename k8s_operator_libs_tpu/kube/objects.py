"""A minimal, dict-backed Kubernetes object model.

Kubernetes objects are JSON documents; this model embraces that instead of
mirroring Go structs. Every object is a thin typed view over its own dict —
round-tripping, deep-copying and merge-patching are therefore exact by
construction, and only the fields the framework actually reads get accessors.

Kinds covered are the ones the reference touches: Node, Pod, DaemonSet,
ControllerRevision, Event (reference: pkg/upgrade), CustomResourceDefinition
(reference: pkg/crdutil), and the external NodeMaintenance CR (reference:
Mellanox maintenance-operator API, used by pkg/upgrade/upgrade_requestor.go).
"""

from __future__ import annotations

import copy
import time
from typing import Any, Iterable, Mapping, MutableMapping, Optional, Type


def deep_copy_json(obj: Any) -> Any:
    """Deep copy for JSON-shaped API objects — ~10x faster than
    ``copy.deepcopy`` (no memo table, no type dispatch). Containers are
    copied recursively; every other value is returned by reference,
    which is only safe because scalars in an API document (str/int/
    float/bool/None) are immutable — anything else would not survive a
    real apiserver round trip either. The in-memory apiserver (fake.py)
    and the cached readers (cache.py, informer.py) ride on this: it is
    the dominant per-call cost of the control plane at 256-node pool
    sizes (ISSUE 4)."""
    t = type(obj)
    if t is dict:
        return {k: deep_copy_json(v) for k, v in obj.items()}
    if t is list:
        return [deep_copy_json(v) for v in obj]
    return obj


def _ensure(d: MutableMapping[str, Any], key: str) -> dict[str, Any]:
    if key not in d or d[key] is None:
        d[key] = {}
    return d[key]


def _ensure_list(d: MutableMapping[str, Any], key: str) -> list[Any]:
    if key not in d or d[key] is None:
        d[key] = []
    return d[key]


class KubeObject:
    """Typed view over a Kubernetes object dict."""

    KIND = ""
    API_VERSION = ""
    NAMESPACED = True

    def __init__(self, data: Optional[dict[str, Any]] = None) -> None:
        self.raw: dict[str, Any] = data if data is not None else {}
        self.raw.setdefault("apiVersion", self.API_VERSION)
        self.raw.setdefault("kind", self.KIND)
        self.raw.setdefault("metadata", {})

    # -- metadata ----------------------------------------------------------
    @property
    def metadata(self) -> dict[str, Any]:
        return _ensure(self.raw, "metadata")

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @name.setter
    def name(self, value: str) -> None:
        self.metadata["name"] = value

    @property
    def namespace(self) -> str:
        return self.metadata.get("namespace", "")

    @namespace.setter
    def namespace(self, value: str) -> None:
        self.metadata["namespace"] = value

    @property
    def uid(self) -> str:
        return self.metadata.get("uid", "")

    @property
    def resource_version(self) -> str:
        return self.metadata.get("resourceVersion", "")

    @property
    def generation(self) -> int:
        """Server-owned desired-state revision: 1 on create, bumped on
        spec-changing writes, untouched by status writes — what a
        controller compares against status.observedGeneration."""
        return self.metadata.get("generation", 0)

    @property
    def labels(self) -> dict[str, str]:
        return _ensure(self.metadata, "labels")

    @property
    def annotations(self) -> dict[str, str]:
        return _ensure(self.metadata, "annotations")

    @property
    def finalizers(self) -> list[str]:
        return _ensure_list(self.metadata, "finalizers")

    @property
    def deletion_timestamp(self) -> Optional[float]:
        return self.metadata.get("deletionTimestamp")

    @property
    def owner_references(self) -> list[dict[str, Any]]:
        # Non-inserting read: a refless object must not grow an empty
        # ``ownerReferences`` list just by being LOOKED at — zero-copy
        # snapshot reads (FakeCluster.list_peek / Informer.list(copy=
        # False)) hand out frozen store dicts, and a lazy insert there
        # would mutate the store outside its lock. Mutators go through
        # add_owner_reference, which ensures the live list explicitly.
        return self.metadata.get("ownerReferences") or []

    def owned_by(self, owner: "KubeObject") -> bool:
        return any(ref.get("uid") == owner.uid for ref in self.owner_references)

    def add_owner_reference(self, owner: "KubeObject", controller: bool = True) -> None:
        _ensure_list(self.metadata, "ownerReferences").append(
            {
                "apiVersion": owner.raw.get("apiVersion", ""),
                "kind": owner.raw.get("kind", ""),
                "name": owner.name,
                "uid": owner.uid,
                "controller": controller,
            }
        )

    # -- common sections ---------------------------------------------------
    @property
    def spec(self) -> dict[str, Any]:
        return _ensure(self.raw, "spec")

    @property
    def status(self) -> dict[str, Any]:
        return _ensure(self.raw, "status")

    # -- plumbing ----------------------------------------------------------
    def deep_copy(self):
        return type(self)(copy.deepcopy(self.raw))

    def to_dict(self) -> dict[str, Any]:
        return copy.deepcopy(self.raw)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        ns = f"{self.namespace}/" if self.namespace else ""
        return f"<{type(self).__name__} {ns}{self.name} rv={self.resource_version}>"

    @classmethod
    def new(
        cls,
        name: str,
        namespace: str = "",
        labels: Optional[Mapping[str, str]] = None,
        annotations: Optional[Mapping[str, str]] = None,
    ):
        obj = cls()
        obj.name = name
        if namespace:
            obj.namespace = namespace
        if labels:
            obj.labels.update(labels)
        if annotations:
            obj.annotations.update(annotations)
        return obj


def condition_status(obj_status: Mapping[str, Any], cond_type: str) -> Optional[str]:
    """Return the status ("True"/"False"/"Unknown") of a condition, if set."""
    for cond in obj_status.get("conditions") or []:
        if cond.get("type") == cond_type:
            return cond.get("status")
    return None


def rfc3339_now() -> str:
    """Current UTC time in the RFC3339 form metav1.Time requires — a real
    apiserver rejects float-epoch timestamps in condition/event fields."""
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def set_condition(
    obj_status: MutableMapping[str, Any],
    cond_type: str,
    status: str,
    reason: str = "",
    message: str = "",
) -> None:
    """Upsert a condition; lastTransitionTime moves only when the status
    actually changes (kube semantics — reason/message refreshes must not
    reset a condition's age)."""
    conds = _ensure_list(obj_status, "conditions")
    for cond in conds:
        if cond.get("type") == cond_type:
            update = {"status": status, "reason": reason, "message": message}
            if cond.get("status") != status:
                update["lastTransitionTime"] = rfc3339_now()
            cond.update(update)
            return
    conds.append(
        {"type": cond_type, "status": status, "reason": reason,
         "message": message, "lastTransitionTime": rfc3339_now()}
    )


class Node(KubeObject):
    KIND = "Node"
    API_VERSION = "v1"
    NAMESPACED = False

    @property
    def unschedulable(self) -> bool:
        # Non-inserting read (see KubeObject.owner_references): safe on
        # frozen zero-copy snapshot objects.
        return bool((self.raw.get("spec") or {}).get("unschedulable", False))

    @unschedulable.setter
    def unschedulable(self, value: bool) -> None:
        self.spec["unschedulable"] = bool(value)

    def is_ready(self) -> bool:
        """Node readiness; an absent Ready condition counts as ready
        (reference: pkg/upgrade/common_manager.go:656-663)."""
        status = condition_status(self.raw.get("status") or {}, "Ready")
        return status is None or status == "True"

    def set_ready(self, ready: bool) -> None:
        set_condition(self.status, "Ready", "True" if ready else "False")


MIRROR_POD_ANNOTATION = "kubernetes.io/config.mirror"


class Pod(KubeObject):
    KIND = "Pod"
    API_VERSION = "v1"

    @property
    def node_name(self) -> str:
        # Non-inserting read (see KubeObject.owner_references): safe on
        # frozen zero-copy snapshot objects.
        return (self.raw.get("spec") or {}).get("nodeName", "")

    @node_name.setter
    def node_name(self, value: str) -> None:
        self.spec["nodeName"] = value

    @property
    def phase(self) -> str:
        return (self.raw.get("status") or {}).get("phase", "")

    @phase.setter
    def phase(self, value: str) -> None:
        self.status["phase"] = value

    def is_ready(self) -> bool:
        return (
            self.phase == "Running"
            and condition_status(self.raw.get("status") or {}, "Ready")
            == "True"
        )

    def is_finished(self) -> bool:
        return self.phase in ("Succeeded", "Failed")

    def is_mirror_pod(self) -> bool:
        return MIRROR_POD_ANNOTATION in (self.metadata.get("annotations") or {})

    def is_daemonset_pod(self) -> bool:
        return any(
            ref.get("kind") == "DaemonSet" and ref.get("controller")
            for ref in self.owner_references
        )

    def has_controller(self) -> bool:
        return any(ref.get("controller") for ref in self.owner_references)

    def has_empty_dir(self) -> bool:
        return any(
            "emptyDir" in (vol or {})
            for vol in (self.raw.get("spec") or {}).get("volumes") or []
        )

    @property
    def container_statuses(self) -> list[dict[str, Any]]:
        return (self.raw.get("status") or {}).get("containerStatuses") or []

    @property
    def init_container_statuses(self) -> list[dict[str, Any]]:
        return (self.raw.get("status") or {}).get(
            "initContainerStatuses"
        ) or []

    def controller_revision_hash(self) -> str:
        """DaemonSet rollout hash from the pod-template label
        (reference: pkg/upgrade/pod_manager.go:84-89)."""
        return (self.metadata.get("labels") or {}).get(
            "controller-revision-hash", ""
        )


class DaemonSet(KubeObject):
    KIND = "DaemonSet"
    API_VERSION = "apps/v1"

    @property
    def match_labels(self) -> dict[str, str]:
        # Non-inserting read (see KubeObject.owner_references): safe on
        # frozen zero-copy snapshot objects.
        return ((self.raw.get("spec") or {}).get("selector") or {}).get(
            "matchLabels"
        ) or {}

    @match_labels.setter
    def match_labels(self, value: Mapping[str, str]) -> None:
        _ensure(self.spec, "selector")["matchLabels"] = dict(value)

    @property
    def desired_number_scheduled(self) -> int:
        # Non-inserting read: build_state evaluates this on zero-copy
        # snapshot DaemonSets; a status-less DS must not grow a
        # ``status: {}`` inside the fake's frozen store.
        return int(
            (self.raw.get("status") or {}).get("desiredNumberScheduled", 0)
        )

    @desired_number_scheduled.setter
    def desired_number_scheduled(self, value: int) -> None:
        self.status["desiredNumberScheduled"] = int(value)

    @property
    def template(self) -> dict[str, Any]:
        return _ensure(self.spec, "template")


class ControllerRevision(KubeObject):
    KIND = "ControllerRevision"
    API_VERSION = "apps/v1"

    @property
    def revision(self) -> int:
        return int(self.raw.get("revision", 0))

    @revision.setter
    def revision(self, value: int) -> None:
        self.raw["revision"] = int(value)

    def hash_label(self) -> str:
        # Non-inserting read: ControllerRevisions are served zero-copy by
        # the snapshot sources.
        return (self.metadata.get("labels") or {}).get(
            "controller-revision-hash", ""
        )


class Event(KubeObject):
    KIND = "Event"
    API_VERSION = "v1"


class Service(KubeObject):
    """Core v1 Service — used by the slice probe gang's headless
    rendezvous Service (tpu/slice_gate.py); only metadata/spec surface."""

    KIND = "Service"
    API_VERSION = "v1"

    @property
    def cluster_ip(self) -> str:
        return (self.raw.get("spec") or {}).get("clusterIP", "")

    def is_headless(self) -> bool:
        return self.cluster_ip == "None"


class ConfigMap(KubeObject):
    """Core v1 ConfigMap — generic key/value payload (consumer operators
    ship upgrade configuration this way; also the canonical co-managed
    object in server-side-apply flows, tests/test_ssa.py)."""

    KIND = "ConfigMap"
    API_VERSION = "v1"

    @property
    def data(self) -> dict[str, str]:
        return _ensure(self.raw, "data")


class Lease(KubeObject):
    """coordination.k8s.io/v1 Lease — the lock object behind leader
    election. The reference library assumes controller-runtime Manager
    hosting (SURVEY §1 L6: consumer operators' Reconcile loops); managers
    take a LeaseLock through k8s.io/client-go/tools/leaderelection, and
    ``kube.leader.LeaderElector`` is this framework's equivalent."""

    KIND = "Lease"
    API_VERSION = "coordination.k8s.io/v1"

    @property
    def holder_identity(self) -> str:
        return self.spec.get("holderIdentity") or ""

    @property
    def lease_duration_seconds(self) -> int:
        return int(self.spec.get("leaseDurationSeconds") or 0)

    @property
    def renew_time(self) -> str:
        return self.spec.get("renewTime") or ""

    @property
    def lease_transitions(self) -> int:
        return int(self.spec.get("leaseTransitions") or 0)


class CustomResourceDefinition(KubeObject):
    KIND = "CustomResourceDefinition"
    API_VERSION = "apiextensions.k8s.io/v1"
    NAMESPACED = False

    @property
    def group(self) -> str:
        return self.spec.get("group", "")

    @property
    def served_versions(self) -> list[str]:
        return [
            v.get("name", "")
            for v in self.spec.get("versions") or []
            if v.get("served", False)
        ]

    @property
    def plural(self) -> str:
        return (self.spec.get("names") or {}).get("plural", "")

    def is_established(self) -> bool:
        return condition_status(self.status, "Established") == "True"


class NodeMaintenance(KubeObject):
    """External maintenance-operator CR (protocol surface, not vendored).

    Field parity with the Mellanox maintenance-operator API v0.3.0 as consumed
    by reference: pkg/upgrade/upgrade_requestor.go:161-174, 497-524.
    """

    KIND = "NodeMaintenance"
    API_VERSION = "maintenance.nvidia.com/v1alpha1"

    CONDITION_READY = "Ready"
    CONDITION_REASON_READY = "Ready"

    @property
    def requestor_id(self) -> str:
        return self.spec.get("requestorID", "")

    @requestor_id.setter
    def requestor_id(self, value: str) -> None:
        self.spec["requestorID"] = value

    @property
    def additional_requestors(self) -> list[str]:
        return _ensure_list(self.spec, "additionalRequestors")

    @additional_requestors.setter
    def additional_requestors(self, value: Iterable[str]) -> None:
        self.spec["additionalRequestors"] = list(value)

    @property
    def node_name(self) -> str:
        return self.spec.get("nodeName", "")

    @node_name.setter
    def node_name(self, value: str) -> None:
        self.spec["nodeName"] = value

    @property
    def node_health(self) -> Optional[dict[str, Any]]:
        """Telemetry surfaced for the external maintenance operator
        (ROADMAP 4c; docs/fleet-telemetry.md): ``{"score": 0-100,
        "trend": improving|stable|degrading}`` stamped by the requestor
        from the node's NodeHealthReport at CR-creation time, so an
        operator that orders its own maintenance queue can go
        degraded-first without consuming the telemetry plane itself.
        None = no telemetry was wired (absence, never a default score:
        an operator must be able to tell "healthy" from "unmeasured")."""
        value = self.spec.get("nodeHealth")
        return value if isinstance(value, dict) else None

    @node_health.setter
    def node_health(self, value: Optional[dict[str, Any]]) -> None:
        if value is None:
            self.spec.pop("nodeHealth", None)
        else:
            self.spec["nodeHealth"] = dict(value)

    @property
    def worst_links(self) -> list[dict[str, Any]]:
        """Sick incident links riding ``spec.nodeHealth.worstLinks``
        (ROADMAP item 5 follow-on; docs/fleet-telemetry.md): each entry
        ``{"peer", "verdict", "gbytesPerS"?, "latencyS"?}`` from the
        requestor's folded-topology localization — so the external
        maintenance operator knows WHICH fabric link degraded the
        score. Empty when the field is absent (no link telemetry, or
        every incident link graded ok)."""
        health = self.node_health or {}
        links = health.get("worstLinks")
        if not isinstance(links, list):
            return []
        return [dict(entry) for entry in links]

    def is_ready(self) -> bool:
        return condition_status(self.status, self.CONDITION_READY) == "True"

    def ready_reason(self) -> str:
        for cond in self.status.get("conditions") or []:
            if cond.get("type") == self.CONDITION_READY:
                return cond.get("reason", "")
        return ""


#: Registry used by clients to construct typed wrappers from raw dicts.
KINDS: dict[str, Type[KubeObject]] = {
    cls.KIND: cls
    for cls in (
        Node,
        Pod,
        DaemonSet,
        ControllerRevision,
        Event,
        Service,
        ConfigMap,
        Lease,
        CustomResourceDefinition,
        NodeMaintenance,
    )
}


def wrap(data: dict[str, Any]) -> KubeObject:
    """Wrap a raw dict in its typed class (falls back to KubeObject)."""
    cls = KINDS.get(data.get("kind", ""), KubeObject)
    return cls(data)
