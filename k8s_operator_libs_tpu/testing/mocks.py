"""Recording mocks for the injectable manager interfaces.

Each mock mirrors one reference mock (reference:
pkg/upgrade/mocks/<Name>.go) and records every call as a :class:`Call` so a
test can assert on exactly what the orchestrator asked for. Outcomes are
configurable per mock — the equivalent of testify's ``.On(...).Return(...)``
— via plain attributes and callables, which is the Python idiom for the same
contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..upgrade.consts import NULL_STRING, UpgradeKeys, UpgradeState


@dataclass
class Call:
    """One recorded invocation: method name + positional summary."""

    method: str
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)


class _Recording:
    def __init__(self) -> None:
        self.calls: list[Call] = []

    def _record(self, method: str, *args, **kwargs) -> None:
        self.calls.append(Call(method, args, kwargs))

    def calls_to(self, method: str) -> list[Call]:
        return [c for c in self.calls if c.method == method]

    def reset(self) -> None:
        self.calls.clear()


class MockCordonManager(_Recording):
    """reference: pkg/upgrade/mocks/CordonManager.go.

    ``fail_on`` is a set of node names whose cordon/uncordon raises.
    """

    def __init__(self, fail_on: Optional[set[str]] = None) -> None:
        super().__init__()
        self.fail_on = fail_on or set()
        self.cordoned: list[str] = []
        self.uncordoned: list[str] = []

    def cordon(self, node) -> None:
        self._record("cordon", node.name)
        if node.name in self.fail_on:
            raise RuntimeError(f"mock cordon failure for {node.name}")
        self.cordoned.append(node.name)
        node.spec["unschedulable"] = True

    def uncordon(self, node) -> None:
        self._record("uncordon", node.name)
        if node.name in self.fail_on:
            raise RuntimeError(f"mock uncordon failure for {node.name}")
        self.uncordoned.append(node.name)
        node.spec.pop("unschedulable", None)


class MockDrainManager(_Recording):
    """reference: pkg/upgrade/mocks/DrainManager.go.

    By default records the request and does nothing (the async contract:
    scheduling is fire-and-forget, outcomes arrive as later state writes).
    Set ``on_schedule`` to drive node states synchronously in a test.
    """

    def __init__(
        self, on_schedule: Optional[Callable[[object], None]] = None
    ) -> None:
        super().__init__()
        self.on_schedule = on_schedule

    def schedule_nodes_drain(self, config) -> None:
        self._record(
            "schedule_nodes_drain", tuple(n.name for n in config.nodes)
        )
        if self.on_schedule is not None:
            self.on_schedule(config)


class MockPodManager(_Recording):
    """reference: pkg/upgrade/mocks/PodManager.go.

    Revision-hash behavior: every pod/daemonset reports ``revision_hash``
    unless the pod's name is listed in ``out_of_sync_pods`` — the same fixed
    "test-hash-12345" device the reference suite uses
    (reference: upgrade_suit_test.go:169-171).
    """

    def __init__(
        self,
        revision_hash: str = "test-hash-12345",
        out_of_sync_pods: Optional[set[str]] = None,
        pod_deletion_filter=None,
    ) -> None:
        super().__init__()
        self.revision_hash = revision_hash
        self.out_of_sync_pods = out_of_sync_pods or set()
        self._pod_deletion_filter = pod_deletion_filter
        self.restarted: list[str] = []

    @property
    def pod_deletion_filter(self):
        return self._pod_deletion_filter

    def get_pod_controller_revision_hash(self, pod) -> str:
        self._record("get_pod_controller_revision_hash", pod.name)
        if pod.name in self.out_of_sync_pods:
            return f"stale-{self.revision_hash}"
        return self.revision_hash

    def get_daemonset_controller_revision_hash(self, daemonset) -> str:
        self._record("get_daemonset_controller_revision_hash", daemonset.name)
        return self.revision_hash

    def schedule_pod_eviction(self, config) -> None:
        self._record(
            "schedule_pod_eviction", tuple(n.name for n in config.nodes)
        )

    def schedule_pods_restart(self, pods) -> None:
        names = tuple(p.name for p in pods)
        self._record("schedule_pods_restart", names)
        self.restarted.extend(names)

    def schedule_check_on_pod_completion(self, config) -> None:
        self._record(
            "schedule_check_on_pod_completion",
            tuple(n.name for n in config.nodes),
        )

    def handle_timeout_on_pod_completions(self, *args, **kwargs) -> None:
        self._record("handle_timeout_on_pod_completions")


class MockValidationManager(_Recording):
    """reference: pkg/upgrade/mocks/ValidationManager.go.

    ``verdicts`` maps node name -> bool; unlisted nodes return ``default``.
    """

    def __init__(
        self, default: bool = True, verdicts: Optional[dict[str, bool]] = None
    ) -> None:
        super().__init__()
        self.default = default
        self.verdicts = verdicts or {}
        self.enabled = True

    def validate(self, node) -> bool:
        self._record("validate", node.name)
        return self.verdicts.get(node.name, self.default)


class MockNodeUpgradeStateProvider(_Recording):
    """reference: pkg/upgrade/mocks/NodeUpgradeStateProvider.go, with the
    suite's stateful behavior baked in: state/annotation writes mutate the
    in-memory node object directly (reference: upgrade_suit_test.go:114-130),
    so state-machine tests assert label transitions without any apiserver.
    """

    def __init__(self, keys: UpgradeKeys, nodes: Optional[dict] = None) -> None:
        super().__init__()
        self.keys = keys
        self.nodes = nodes or {}

    def add_node(self, node) -> None:
        self.nodes[node.name] = node

    def get_node(self, name: str):
        self._record("get_node", name)
        return self.nodes[name]

    def get_upgrade_state(self, node) -> UpgradeState:
        raw = node.labels.get(self.keys.state_label, "")
        try:
            return UpgradeState(raw)
        except ValueError:
            return UpgradeState.UNKNOWN

    def change_node_upgrade_state(self, node, new_state) -> None:
        new_state = UpgradeState(new_state)
        self._record("change_node_upgrade_state", node.name, str(new_state))
        if new_state == UpgradeState.UNKNOWN:
            node.labels.pop(self.keys.state_label, None)
        else:
            node.labels[self.keys.state_label] = str(new_state)

    def change_node_upgrade_annotation(self, node, key: str, value: str) -> None:
        self._record("change_node_upgrade_annotation", node.name, key, value)
        if value == NULL_STRING:
            node.annotations.pop(key, None)
        else:
            node.annotations[key] = value


def install_mocks(
    manager,
    cordon: Optional[MockCordonManager] = None,
    drain: Optional[MockDrainManager] = None,
    pod: Optional[MockPodManager] = None,
    validation: Optional[MockValidationManager] = None,
):
    """Swap a ClusterUpgradeStateManager's node-op managers for mocks — the
    injection point the reference suite uses (reference:
    upgrade_state_test.go:63-68). Returns the installed mocks as a tuple
    ``(cordon, drain, pod, validation)``.
    """
    cordon = cordon or MockCordonManager()
    drain = drain or MockDrainManager()
    pod = pod or MockPodManager()
    validation = validation or MockValidationManager()
    manager.common.cordon_manager = cordon
    manager.common.drain_manager = drain
    manager.common.pod_manager = pod
    manager.common.validation_manager = validation
    return cordon, drain, pod, validation
