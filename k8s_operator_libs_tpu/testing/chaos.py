"""Deterministic chaos harness — seeded fleet-scale fault SCHEDULES
(ROADMAP item 6; docs/chaos-harness.md).

The per-call failure-injection matrix (tests/test_failure_injection.py,
test_fleet.py) proves each API surface absorbs one transient fault. The
failures that break control planes at production scale are *schedules*:
a worker dying between grant and pool-done, a lease stolen mid-apply, a
watch stream lagging the grant ledger, a partition splitting the
orchestrator from half its workers. This module drives the fleet e2e
(fleet/worker.py + fleet/orchestrator.py over a FakeCluster or a
LocalApiServer) under a **seeded, deterministic fault schedule** and
asserts the global invariants under every interleaving explored:

* **budget** — never more than ``maxUnavailablePools`` pools disrupted,
  sampled every step;
* **no grant retired unrolled** — every pool the ledger marks ``done``
  is verifiably rolled (state label, schedulability, pod currency) at
  the moment of the transition;
* **no node lost** — the run ends with every node schedulable, ready,
  and upgrade-done;
* **completeness / incremental==full** — each surviving worker's
  incremental book byte-agrees with a fresh full classification
  (``ClusterUpgradeStateManager.audit_incremental``), and completeness
  aborts stay a bounded counted signal
  (``PassStats.aborted_completeness_races``), never a wedge.

Determinism is an architecture, not a hope:

* **virtual time** — one :class:`~..utils.faultpoints.ChaosClock` feeds
  every elector/claim (``now_fn``/``wall_fn``) and the durable-clock
  helpers (``faultpoints.wall_now``), advanced only by the driver:
  lease expiry and deadline escalation happen when the schedule says,
  not when the test host is slow;
* **step-armed faults** — every fault is armed/disarmed at a schedule
  step, never decided by a racing visit counter, so the decision
  stream is a pure function of (seed, config);
* **settle barriers** — after each step the driver waits until every
  live informer's store byte-matches the cluster truth for its scope
  and nothing is pending dispatch (held/lagged informers exempted
  while their fault is armed), so the next step always starts from one
  well-defined world.

Same seed ⇒ same schedule JSON ⇒ same step trace ⇒ same final cluster
state — pinned by a run-twice test (tests/test_chaos.py) and
reproducible with one command::

    python -m tools.chaos_run --seed S --schedule-json out.json

This is the property-based *runtime* analogue of what ``tools/analyze``
verifies statically (docs/static-analysis.md): the analyzer proves a
policy cannot mutate the cluster; this harness proves the protocols
converge when the cluster mutates under them.
"""

from __future__ import annotations

import hashlib
import json
import random
import time as _time
from dataclasses import asdict, dataclass, field, replace
from typing import Any, Callable, Mapping, Optional, Sequence

from ..api.fleet_v1alpha1 import (
    FLEET_ROLLOUT_KIND,
    POOL_DONE,
    POOL_GRANTED,
    make_fleet_rollout,
    pools_in_phase,
    rollout_spec,
)
from ..api.upgrade_v1alpha1 import (
    CheckpointSpec,
    DrainSpec,
    DriverUpgradePolicySpec,
)
from ..kube.client import ApiError, Client, ConflictError
from ..kube.fake import FakeCluster
from ..kube.objects import KubeObject, Node
from ..kube.sim import CheckpointingWorkloadSimulator, DaemonSetSimulator
from ..upgrade.consts import DeviceClass, UpgradeKeys, UpgradeState
from ..upgrade.state_manager import BuildStateError
from ..utils import faultpoints
from ..utils.faultpoints import (
    DENY,
    HOLD,
    OVERFLOW,
    RAISE,
    ChaosClock,
    FaultAction,
)
from ..utils.intstr import IntOrString
from ..utils.log import get_logger

log = get_logger("testing.chaos")

#: The schedule-drivable fault points (ISSUE 13 acceptance): consulted
#: in production code via ``utils.faultpoints.fault_point`` (the first
#: five) or applied by the driver itself (the last two — process death
#: and TCP teardown have no in-process consult site).
POINT_LEASE = "lease"                # kube/leader.py protocol round
POINT_GRANT_WRITE = "grant_write"    # fleet/orchestrator.py ledger write
POINT_STATUS_WRITE = "status_write"  # fleet/worker.py pool-done report
POINT_WATCH = "watch"                # kube/informer.py delivery hold
POINT_HUB_REPLAY = "hub_replay"      # kube/watchhub.py forced overflow
POINT_PARTITION = "partition"        # per-client request blackholing
POINT_WORKER_KILL = "worker_kill"    # driver: stop + optional restart
#: Graceful termination mid-roll (kubelet SIGTERM → the supervised
#: drain, docs/daemon-lifecycle.md): the worker stops through its real
#: stop path and RELEASES its leases eagerly, so survivors take over
#: with zero TTL wait — the handoff the supervised runtime promises,
#: under the same invariants the crash (worker_kill) point checks.
POINT_SIGTERM = "sigterm"            # driver: graceful stop + optional restart
POINT_WIRE_KILL = "wire_kill"        # driver: LocalApiServer.kill_connections
#: One PATCH in a pipelined write batch fails mid-flush while its
#: batchmates land (upgrade/write_batch.py consults this per entry) —
#: the partial-batch shape a real apiserver produces under contention.
POINT_WRITE_BATCH = "write_batch_partial"
#: The host-local WatchRelay (kube/relay.py) loses every subscriber
#: connection mid-stream (driver: WatchRelay.kill_connections) — each
#: worker's RelayWatchSource must degrade to a bounded direct-watch
#: window and then re-adopt the relay, never going silent.
POINT_RELAY_KILL = "relay_kill"      # driver: WatchRelay.kill_connections
#: A read replica dies mid-storm and is revived on the same port at the
#: window's end (driver: replica stop + rebind) — clients must fail the
#: in-flight read over to the primary inline and keep every watch and
#: lease renewal flowing.
POINT_REPLICA_FAILOVER = "replica_failover"

ALL_POINTS = (
    POINT_LEASE, POINT_GRANT_WRITE, POINT_STATUS_WRITE, POINT_WATCH,
    POINT_HUB_REPLAY, POINT_PARTITION, POINT_WORKER_KILL, POINT_SIGTERM,
    POINT_WIRE_KILL, POINT_WRITE_BATCH, POINT_RELAY_KILL,
    POINT_REPLICA_FAILOVER,
)

SCHEDULE_VERSION = 1

NS = "driver-ns"
LABELS = {"app": "driver"}
ROLLOUT = "chaos-roll"
DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
ORCH_IDENTITY = "orchestrator"


class ChaosServerTimeoutError(ApiError):
    """The injected 504-shaped transient (the failure-injection matrix's
    ServerTimeout, reproduced under schedule control)."""


def pool_of(node_name: str) -> str:
    return node_name.split("-")[0]


# ---------------------------------------------------------------------------
# Schedule: seeded fault specs, byte-stable JSON
# ---------------------------------------------------------------------------


@dataclass
class FaultSpec:
    """One scheduled fault: ``point`` armed for ``duration`` steps from
    ``step``. ``target`` picks the participant (lease name, worker
    identity); ``param`` narrows further (informer kind); ``error``
    picks the injected exception for raise-points; ``count`` bounds how
    many consults fire within the window (0 = every consult)."""

    step: int
    point: str
    duration: int = 1
    target: str = ""
    param: str = ""
    error: str = ""
    count: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, raw: Mapping[str, Any]) -> "FaultSpec":
        return cls(**{k: raw[k] for k in (
            "step", "point", "duration", "target", "param", "error", "count"
        )})


@dataclass
class ChaosConfig:
    """Fleet shape + schedule envelope. Everything that shapes the run
    is HERE (echoed into the schedule JSON) so a schedule file is a
    complete reproduction recipe."""

    pools: int = 16
    hosts: int = 1
    workers: int = 2
    shards: int = 4
    budget: str = "25%"
    max_steps: int = 0          # 0 = derived from pools
    step_dt: float = 0.6
    fault_window: int = 80      # faults arm within the first N steps
    faults_min: int = 2
    faults_max: int = 5
    hub: bool = False           # co-hosted workers behind one WatchHub
    checkpoint: bool = False    # checkpoint-coordinated drains + victims
    checkpoint_timeout_s: int = 120
    wire: bool = False          # run over a LocalApiServer (wire mode)
    #: Co-hosted workers stream their watches through one WatchRelay
    #: (kube/relay.py) instead of per-process upstream streams — the
    #: cross-process sibling of ``hub`` — and arm ``relay_kill``. In
    #: wire mode the relay's upstream is the LocalApiServer socket
    #: (compact-encoded); otherwise it sits directly on the fake.
    relay: bool = False
    #: With ``wire``: start N read-only replicas over the primary's
    #: journal, spread worker reads across them via
    #: ``RestConfig.read_servers``, and arm ``replica_failover``.
    replicas: int = 0
    #: Route worker provider writes through the group-commit batching
    #: tier (upgrade/write_batch.py). The harness stays on the inline
    #: runner, so every stage is a deterministic batch of one — what's
    #: exercised is the stage→flush→rejoin machinery and the
    #: ``write_batch_partial`` fault point, not wall-clock pipelining.
    batch_writes: bool = True
    #: Registered policy composition the pools' upgrade policy runs
    #: (docs/policy-plugins.md); empty = the default policy. The
    #: ``policy_matrix`` corpus (run_policy_matrix) sweeps the shipped
    #: compositions over one seed corpus.
    policy: tuple = ()

    def __post_init__(self) -> None:
        # JSON round-trips the composition as a list; coerce back so a
        # reloaded schedule config compares (and re-serializes) equal.
        self.policy = tuple(self.policy)

    def resolved_max_steps(self) -> int:
        return self.max_steps or (240 + 5 * self.pools)

    def identities(self) -> list[str]:
        return [f"w{i}" for i in range(self.workers)]

    def pool_names(self) -> list[str]:
        return [f"p{i}" for i in range(self.pools)]

    def node_names(self) -> list[str]:
        return [
            f"{pool}-h{h}"
            for pool in self.pool_names()
            for h in range(self.hosts)
        ]

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, raw: Mapping[str, Any]) -> "ChaosConfig":
        return cls(**dict(raw))


@dataclass
class FaultSchedule:
    seed: int
    config: ChaosConfig
    faults: list[FaultSpec] = field(default_factory=list)

    def to_json(self) -> str:
        """Byte-stable serialization: same schedule ⇒ same bytes (the
        repro artifact ``tools/chaos_run.py --schedule-json`` writes)."""
        return json.dumps(
            {
                "version": SCHEDULE_VERSION,
                "seed": self.seed,
                "config": self.config.to_dict(),
                "faults": [f.to_dict() for f in self.faults],
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        raw = json.loads(text)
        if raw.get("version") != SCHEDULE_VERSION:
            raise ValueError(
                f"unsupported schedule version {raw.get('version')!r}"
            )
        return cls(
            seed=int(raw["seed"]),
            config=ChaosConfig.from_dict(raw["config"]),
            faults=[FaultSpec.from_dict(f) for f in raw["faults"]],
        )

    def last_armed_step(self) -> int:
        return max(
            (f.step + max(1, f.duration) for f in self.faults), default=0
        )


def generate_schedule(seed: int, config: ChaosConfig) -> FaultSchedule:
    """Draw a fault schedule from the seed — the ONLY source of
    randomness in a chaos run (``random.Random(seed)``; the run itself
    is deterministic). Drawn within convergence-safe envelopes: fault
    windows close well before ``max_steps``, at most ``workers - 1``
    workers are ever down at once, watch holds are short enough that
    the fake's bounded per-watch queue (1024 events) cannot overflow at
    the configured fleet size, and a restart never lands inside its own
    worker's partition window (the restarted informers must sync)."""
    rng = random.Random(seed)
    cfg = config
    points = [
        POINT_LEASE, POINT_GRANT_WRITE, POINT_STATUS_WRITE,
        POINT_WATCH, POINT_PARTITION, POINT_WORKER_KILL, POINT_SIGTERM,
    ]
    if cfg.hub:
        points.append(POINT_HUB_REPLAY)
    if cfg.wire:
        points.append(POINT_WIRE_KILL)
    if cfg.relay:
        points.append(POINT_RELAY_KILL)
    if cfg.wire and cfg.replicas:
        points.append(POINT_REPLICA_FAILOVER)
    if cfg.batch_writes:
        points.append(POINT_WRITE_BATCH)
    identities = cfg.identities()
    faults: list[FaultSpec] = []
    perma_killed: set[str] = set()
    partition_windows: dict[str, list[tuple[int, int]]] = {}
    kill_windows: dict[str, list[tuple[int, int]]] = {}

    def overlaps(windows, step, duration):
        return any(
            step < end and start < step + duration
            for start, end in windows
        )

    n_faults = rng.randint(cfg.faults_min, cfg.faults_max)
    for _ in range(n_faults):
        point = rng.choice(points)
        step = rng.randint(2, max(3, cfg.fault_window))
        if point == POINT_LEASE:
            shard = rng.randrange(cfg.shards)
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(3, 12),
                target=f"fleet-shard-{shard:02d}",
            ))
        elif point == POINT_GRANT_WRITE:
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(1, 6),
                error=rng.choice(("conflict", "server_timeout")),
                count=rng.randint(1, 4),
            ))
        elif point == POINT_STATUS_WRITE:
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(1, 6),
                target=rng.choice(["", *identities]),
                error=rng.choice(("conflict", "server_timeout")),
                count=rng.randint(1, 4),
            ))
        elif point == POINT_WATCH:
            # Short holds only: events queue upstream while held, and
            # the fake's per-watch queue drops past 1024 — bound the
            # window so a held informer can never silently lose events.
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(2, 6),
                target=rng.choice(identities),
                param=rng.choice(("", "Node", "Pod")),
            ))
        elif point == POINT_HUB_REPLAY:
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(1, 3),
                param=rng.choice(("", "Node", "Pod")),
                count=rng.randint(1, 2),
            ))
        elif point == POINT_PARTITION:
            target = rng.choice([ORCH_IDENTITY, *identities])
            duration = rng.randint(3, 12)
            if overlaps(kill_windows.get(target, []), step, duration):
                continue  # the restart inside would fail its sync
            partition_windows.setdefault(target, []).append(
                (step, step + duration)
            )
            faults.append(FaultSpec(
                step=step, point=point, duration=duration, target=target,
            ))
        elif point in (POINT_WORKER_KILL, POINT_SIGTERM):
            # Same envelope for both exits: at most workers-1 down at
            # once and no restart bracketed by its own partition. The
            # points differ only in HOW the worker leaves — a crash
            # (leases expire) vs the supervised graceful stop (leases
            # released eagerly, the zero-TTL handoff).
            alive = [
                i for i in identities
                if i not in perma_killed
            ]
            if len(alive) <= 1:
                continue  # someone must survive to finish the roll
            target = rng.choice(alive)
            permanent = rng.random() < 0.3
            duration = rng.randint(6, 30)
            if overlaps(
                partition_windows.get(target, []), step + duration, 1
            ):
                continue  # restart would sync through its own partition
            if permanent:
                perma_killed.add(target)
            else:
                # Record the restart instant so a LATER partition draw
                # for this worker cannot bracket it (the other half of
                # the exclusion; the overlaps() check above covers a
                # kill drawn after the partition).
                kill_windows.setdefault(target, []).append(
                    (step + duration, step + duration + 1)
                )
            faults.append(FaultSpec(
                step=step, point=point, duration=duration, target=target,
                param="perma" if permanent else "restart",
            ))
        elif point == POINT_WIRE_KILL:
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(1, 2),
            ))
        elif point == POINT_RELAY_KILL:
            # Same envelope as wire_kill: the relay's subscriber
            # connections die for the window; the relay itself stays
            # up, so resumes race fallbacks — both paths must converge.
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(1, 2),
            ))
        elif point == POINT_REPLICA_FAILOVER:
            # The replica is DOWN for the window and revived (same
            # port) at its end — long enough that reads actually route
            # around it, bounded so the revival is exercised too.
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(3, 10),
                target=str(rng.randrange(cfg.replicas)),
            ))
        elif point == POINT_WRITE_BATCH:
            # Empty target = any node's slot in any flush; a node target
            # narrows to that node's entries only.
            faults.append(FaultSpec(
                step=step, point=point, duration=rng.randint(1, 6),
                target=rng.choice(["", *cfg.node_names()[:4]]),
                error=rng.choice(("conflict", "server_timeout")),
                count=rng.randint(1, 4),
            ))
    faults.sort(key=lambda f: (f.step, f.point, f.target, f.param))
    return FaultSchedule(seed=seed, config=cfg, faults=faults)


# ---------------------------------------------------------------------------
# Plan: the runtime registry fault_point() consults
# ---------------------------------------------------------------------------


class FaultPlan:
    """Armed-window matcher behind ``faultpoints.fault_point``. The
    driver moves :attr:`step`; consults (from any thread) match the
    armed specs — a pure function of (schedule, step, ctx), which is
    what keeps the decision stream replayable."""

    def __init__(self, schedule: FaultSchedule) -> None:
        import threading

        self.schedule = schedule
        self._lock = threading.Lock()
        self.step = -1
        #: spec index -> fires inside its window (count-bounded points).
        self.fires: dict[int, int] = {}
        #: point name -> lifetime fires (sync points land in the trace).
        self.fired: dict[str, int] = {}

    def begin_step(self, step: int) -> None:
        with self._lock:
            self.step = step

    def _armed(self, spec: FaultSpec) -> bool:
        return spec.step <= self.step < spec.step + max(1, spec.duration)

    @staticmethod
    def _error_for(spec: FaultSpec) -> BaseException:
        if spec.error == "conflict":
            return ConflictError("chaos: injected conflict")
        if spec.error == "server_timeout":
            return ChaosServerTimeoutError("chaos: injected server timeout")
        return ApiError("chaos: injected api error")

    def consult(self, point: str, ctx: Mapping[str, Any]):
        with self._lock:
            for idx, spec in enumerate(self.schedule.faults):
                if not self._armed(spec):
                    continue
                action = self._match(spec, point, ctx)
                if action is None:
                    continue
                if spec.count and self.fires.get(idx, 0) >= spec.count:
                    continue
                self.fires[idx] = self.fires.get(idx, 0) + 1
                self.fired[spec.point] = self.fired.get(spec.point, 0) + 1
                return action
        return None

    def _match(
        self, spec: FaultSpec, point: str, ctx: Mapping[str, Any]
    ) -> Optional[FaultAction]:
        if spec.point == POINT_LEASE and point == "lease.round":
            if spec.target in ("", ctx.get("name")):
                return FaultAction(DENY)
        elif spec.point == POINT_GRANT_WRITE and point == "fleet.grant_write":
            return FaultAction(RAISE, self._error_for(spec))
        elif (
            spec.point == POINT_STATUS_WRITE
            and point == "fleet.status_write"
        ):
            if spec.target in ("", ctx.get("identity")):
                return FaultAction(RAISE, self._error_for(spec))
        elif spec.point == POINT_WATCH and point == "watch.deliver":
            # A watch hold REQUIRES a target: informers with the
            # default empty chaos_tag are untargetable by contract
            # (kube/informer.py) — an empty-target spec matching them
            # would silently hold every untagged informer in the
            # process (health sources, unrelated tests).
            if spec.target and spec.target == ctx.get("tag") and (
                spec.param in ("", ctx.get("kind"))
            ):
                return FaultAction(HOLD)
        elif spec.point == POINT_HUB_REPLAY and point == "watchhub.deliver":
            if spec.param in ("", ctx.get("kind")):
                return FaultAction(OVERFLOW)
        elif (
            spec.point == POINT_WRITE_BATCH
            and point == "upgrade.write_batch_partial"
        ):
            if spec.target in ("", ctx.get("node")):
                return FaultAction(RAISE, self._error_for(spec))
        elif spec.point == POINT_PARTITION and point == "wire.partition":
            if spec.target == ctx.get("identity"):
                return FaultAction(
                    RAISE,
                    ApiError(
                        f"chaos: {spec.target} partitioned from the "
                        "apiserver"
                    ),
                )
        return None

    def record_driver_fire(self, point: str) -> None:
        """Driver-applied points (worker_kill, wire_kill) have no
        in-code consult — the driver records their firing here so the
        trace and the pinning tests see them like any other point.
        Driver thread only, hence step-deterministic."""
        with self._lock:
            self.fired[point] = self.fired.get(point, 0) + 1

    # -- driver-side queries ------------------------------------------------
    def held_watch(self, tag: str, kind: str) -> bool:
        """True while a watch-hold fault is armed for this informer —
        the settle barrier exempts it (its store is SUPPOSED to lag)."""
        with self._lock:
            return any(
                self._armed(s)
                and s.point == POINT_WATCH
                and s.target == tag
                and s.param in ("", kind)
                for s in self.schedule.faults
            )

    def partitioned(self, identity: str) -> bool:
        with self._lock:
            return any(
                self._armed(s)
                and s.point == POINT_PARTITION
                and s.target == identity
                for s in self.schedule.faults
            )

    def sync_fire_counts(self) -> dict[str, int]:
        """Cumulative fires of the step-synchronous points (consulted
        only from the driver thread) — safe to embed in the trace. The
        async points (watch hold, hub overflow: consulted from watch/
        pump threads) are reported once per run instead."""
        with self._lock:
            return {
                p: n
                for p, n in sorted(self.fired.items())
                if p not in (POINT_WATCH, POINT_HUB_REPLAY)
            }

    def async_points_engaged(self) -> dict[str, bool]:
        with self._lock:
            return {
                POINT_WATCH: self.fired.get(POINT_WATCH, 0) > 0,
                POINT_HUB_REPLAY: self.fired.get(POINT_HUB_REPLAY, 0) > 0,
            }


class PartitionedClient:
    """Per-participant request blackholing: every API call this client
    carries consults the ``wire.partition`` fault point first, so a
    schedule can split the orchestrator from a subset of workers while
    the cluster itself stays healthy. Established watch streams are
    deliberately NOT cut (the half-open partition: the kernel keeps a
    TCP stream alive while new connections fail) — cutting streams is
    the ``watch``/``wire_kill`` points' job."""

    _INTERCEPTED = frozenset({
        "get", "get_or_none", "list", "list_with_revision", "list_delta",
        "watch", "create", "update", "update_status", "patch", "patch_many",
        "apply", "delete", "delete_collection", "delete_if_exists", "evict",
        "discover",
    })

    def __init__(self, inner: Client, identity: str) -> None:
        self._inner = inner
        self.identity = identity

    def _check(self) -> None:
        act = faultpoints.fault_point(
            "wire.partition", identity=self.identity
        )
        if act is not None:
            raise act.exc if act.exc is not None else ApiError(
                f"chaos: {self.identity} partitioned"
            )

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in self._INTERCEPTED and callable(attr):
            def guarded(*args, **kwargs):
                self._check()
                return attr(*args, **kwargs)

            return guarded
        return attr


# ---------------------------------------------------------------------------
# The harness
# ---------------------------------------------------------------------------


@dataclass
class ChaosResult:
    seed: int
    converged: bool
    steps: int
    #: invariant name -> violation count; ALL must be zero.
    violations: dict[str, int]
    #: per-step observable record (cluster truth only) — byte-compared
    #: by the run-twice determinism pin.
    trace: list[dict]
    fired: dict[str, int]
    async_engaged: dict[str, bool]
    completeness_aborts: int
    final_digest: str
    schedule_json: str
    wall_s: float

    @property
    def total_violations(self) -> int:
        return sum(self.violations.values())

    def summary(self) -> dict:
        return {
            "seed": self.seed,
            "converged": self.converged,
            "steps": self.steps,
            "violations": dict(self.violations),
            "total_violations": self.total_violations,
            "fired": dict(self.fired),
            "async_engaged": dict(self.async_engaged),
            "completeness_aborts": self.completeness_aborts,
            "final_digest": self.final_digest,
            "wall_s": round(self.wall_s, 3),
        }


class _WorkerSlot:
    """One worker identity's lifecycle across kills and restarts."""

    def __init__(self, identity: str) -> None:
        self.identity = identity
        self.worker = None
        self.alive = False
        self.restart_at: Optional[int] = None
        self.ticks = 0
        self.aborts = 0
        #: Lifetime completeness aborts summed over dead incarnations
        #: (each restart builds a fresh manager whose counter restarts).
        self.aborts_retired = 0
        #: Same for checkpoint escalations: an incarnation that
        #: escalated and was then killed must still fail the
        #: no-spurious-escalation invariant.
        self.escalations_retired = 0


class ChaosFleetHarness:
    """Build a fleet (cluster, sim, rollout, N shard workers, one
    orchestrator), run it under a :class:`FaultSchedule`, check the
    global invariants. One harness per run — it owns plan + clock
    installation and tears everything down."""

    def __init__(self, config: ChaosConfig) -> None:
        self.cfg = config
        self.clock = ChaosClock()
        self.cluster: FakeCluster = None  # type: ignore[assignment]
        self.sim: DaemonSetSimulator = None  # type: ignore[assignment]
        self.workload: Optional[CheckpointingWorkloadSimulator] = None
        self.hub = None
        self.server = None
        self.relay = None
        self.replicas: list = []
        self._relay_sources: list = []
        self.orch = None
        self.slots: dict[str, _WorkerSlot] = {}
        self.budget = 0

    # -- construction ------------------------------------------------------
    def _client_for(self, identity: str) -> Client:
        if self.server is not None:
            from ..kube.rest import RestClient, RestConfig

            inner: Client = RestClient(RestConfig(
                server=self.server.url,
                read_servers=tuple(r.url for r in self.replicas),
            ))
        else:
            inner = self.cluster
        return PartitionedClient(inner, identity)

    def _build_cluster(self) -> None:
        if self.cfg.wire:
            from ..kube.apiserver import LocalApiServer

            self.server = LocalApiServer().start()
            self.cluster = self.server.cluster
            # Read replicas share the primary's journal (the in-process
            # stand-in for journal replication); every client built
            # after this spreads its reads across them.
            self.replicas = [
                self.server.read_replica().start()
                for _ in range(self.cfg.replicas)
            ]
        else:
            self.cluster = FakeCluster()
        if self.cfg.relay:
            from ..kube.relay import WatchRelay
            from ..kube.rest import RestConfig

            # In wire mode the relay is a real upstream subscriber
            # (compact-encoded socket client); on the fake it sits
            # directly on the cluster — either way its subscribers
            # speak the ordinary watch wire protocol to its socket.
            upstream = (
                RestConfig(server=self.server.url)
                if self.server is not None else self.cluster
            )
            self.relay = WatchRelay(upstream).start()
        for name in self.cfg.node_names():
            node = Node.new(name)
            node.set_ready(True)
            self.cluster.create(node)
        self.sim = DaemonSetSimulator(
            self.cluster, name="driver", namespace=NS, match_labels=LABELS
        )
        self.sim.settle()
        rollout = make_fleet_rollout(
            ROLLOUT, self.cfg.pool_names(), self.cfg.budget
        )
        self.budget = rollout_spec(rollout).resolved_budget()
        self.cluster.create(KubeObject(rollout))
        if self.cfg.checkpoint:
            self.workload = CheckpointingWorkloadSimulator(
                self.cluster, KEYS, pod_labels={"app": "trainer"}
            )

    def _policy(self) -> DriverUpgradePolicySpec:
        kwargs: dict[str, Any] = {}
        if self.cfg.checkpoint:
            kwargs["drain"] = DrainSpec(
                enable=True, force=True, timeout_seconds=30
            )
            kwargs["checkpoint"] = CheckpointSpec(
                enable=True,
                pod_selector="app=trainer",
                timeout_seconds=self.cfg.checkpoint_timeout_s,
            )
        return DriverUpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=0,
            # The GRANT is the budget in the fleet shape
            # (docs/fleet-control-plane.md).
            max_unavailable=IntOrString("100%"),
            policy=self.cfg.policy,
            **kwargs,
        )

    def _start_worker(self, identity: str):
        from ..fleet.worker import FleetWorkerConfig, ShardWorker

        client = self._client_for(identity)
        watch_hub = self.hub
        if self.relay is not None:
            from ..kube.relay import RelayWatchSource

            # Per-worker source: fallback windows (and their counters)
            # are this worker's own, exactly as in separate processes.
            # Virtual-clock mono keeps the retry-the-relay decision a
            # function of the schedule step, not host speed.
            watch_hub = RelayWatchSource(
                self.relay.url, direct=client, mono=self.clock.now,
            )
            self._relay_sources.append(watch_hub)
        worker = ShardWorker(
            client,
            FleetWorkerConfig(
                identity=identity,
                shards=self.cfg.shards,
                namespace=NS,
                driver_labels=LABELS,
                pool_of=pool_of,
                rollout_name=ROLLOUT,
                workers=tuple(self.cfg.identities()),
                lease_duration_s=3.0,
                renew_deadline_s=2.0,
                retry_period_s=0.5,
                watch_hub=watch_hub,
            ),
            now_fn=self.clock.now,
            wall_fn=self.clock.wall,
        )
        if self.cfg.batch_writes:
            # Batching on the INLINE runner: deterministic batches of
            # one, exercising the stage→flush→rejoin path and the
            # write_batch_partial fault point under every interleaving
            # (ChaosConfig.batch_writes doc).
            worker.mgr.enable_write_batching()
        # Tag the informers so a watch-hold fault can target exactly
        # this worker's streams (kube/informer.py chaos_tag).
        for informer in worker.source._informers.values():
            informer.chaos_tag = identity
        worker.start(sync_timeout=30)
        return worker

    def _build_fleet(self) -> None:
        from ..fleet.orchestrator import FleetOrchestrator

        if self.cfg.hub:
            from ..kube.watchhub import WatchHub

            # The hub rides its own (never-partitioned) client: it
            # models the co-hosted fan-out process, whose upstream is a
            # separate connection from each worker's request path.
            self.hub = WatchHub(self.cluster)
        for identity in self.cfg.identities():
            slot = _WorkerSlot(identity)
            slot.worker = self._start_worker(identity)
            slot.alive = True
            self.slots[identity] = slot
        self.orch = FleetOrchestrator(
            self._client_for(ORCH_IDENTITY), ROLLOUT
        )

    # -- settle barrier ----------------------------------------------------
    def _informer_settled(self, informer) -> bool:
        expected = {
            (obj.namespace, obj.name): str(obj.resource_version)
            for obj in self.cluster.list(
                informer.kind,
                namespace=informer.namespace,
                label_selector=informer.label_selector,
                field_selector=informer.field_selector,
            )
        }
        with informer._lock:
            have = {
                key: str(
                    (raw.get("metadata") or {}).get("resourceVersion", "")
                )
                for key, raw in informer._store.items()
            }
        if have != expected:
            return False
        pending, gone = informer.pending_dispatch()
        return not pending and not gone

    def _settled(self, plan: FaultPlan) -> bool:
        for slot in self.slots.values():
            if not slot.alive:
                continue
            for informer in slot.worker.source._informers.values():
                if plan.held_watch(slot.identity, informer.kind):
                    continue  # lagging by schedule — exempt until heal
                if not self._informer_settled(informer):
                    return False
        return True

    def settle(self, plan: FaultPlan, timeout: float = 30.0) -> bool:
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if self._settled(plan):
                return True
            _time.sleep(0.002)
        return False

    # -- observations ------------------------------------------------------
    def _disrupted_pools(self) -> set[str]:
        out = set()
        for raw in self.cluster.list_peek("Node"):
            node = Node(raw)
            if node.unschedulable or not node.is_ready():
                out.add(pool_of(node.name))
        return out

    def _ledger_phases(self) -> dict[str, list[str]]:
        raw = self.cluster.peek(FLEET_ROLLOUT_KIND, ROLLOUT) or {}
        return {
            "granted": sorted(pools_in_phase(raw, POOL_GRANTED)),
            "done": sorted(pools_in_phase(raw, POOL_DONE)),
        }

    def _node_record(self, name: str) -> tuple:
        raw = self.cluster.peek("Node", name) or {}
        node = Node(raw)
        pod_raw = self.cluster.peek("Pod", self.sim.pod_name(name), NS) or {}
        pod_hash = (
            (pod_raw.get("metadata") or {}).get("labels") or {}
        ).get("controller-revision-hash", "")
        return (
            node.labels.get(KEYS.state_label, ""),
            bool(node.unschedulable),
            node.is_ready(),
            pod_hash,
        )

    def _pool_rolled(self, pool: str) -> bool:
        """Cluster-truth check behind the no-grant-retired-unrolled
        invariant: at the instant a pool flips ``done`` every node must
        be upgrade-done, schedulable, ready, and running a pod at the
        CURRENT template hash."""
        names = [
            f"{pool}-h{h}" for h in range(self.cfg.hosts)
        ]
        for name in names:
            state, unsched, ready, pod_hash = self._node_record(name)
            if state != str(UpgradeState.DONE) or unsched or not ready:
                return False
            if pod_hash != self.sim.current_hash:
                return False
        return True

    def _converged(self, phases: dict) -> bool:
        if len(phases["done"]) != self.cfg.pools:
            return False
        for name in self.cfg.node_names():
            state, unsched, ready, pod_hash = self._node_record(name)
            if state != str(UpgradeState.DONE) or unsched or not ready:
                return False
        return self.sim.all_pods_ready_and_current()

    def final_digest(self) -> str:
        payload = {
            "nodes": {
                name: self._node_record(name)
                for name in self.cfg.node_names()
            },
            "ledger": self._ledger_phases(),
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()
        ).hexdigest()

    # -- driver events -----------------------------------------------------
    def _kill(
        self, identity: str, restart_at: Optional[int],
        graceful: bool = False,
    ) -> None:
        slot = self.slots[identity]
        if not slot.alive:
            return
        log.info("chaos: %s worker %s (restart_at=%s)",
                 "gracefully stopping" if graceful else "killing",
                 identity, restart_at)
        # A crash releases nothing: the leases go stale and are either
        # resumed by the restarted identity or stolen by a survivor.
        # The graceful (sigterm) exit is the supervised drain instead:
        # leases released EAGERLY, so survivors take over with zero TTL
        # wait (docs/daemon-lifecycle.md) — same invariants either way.
        mgr = slot.worker.mgr
        slot.aborts_retired += mgr.completeness_aborts_total
        slot.escalations_retired += (
            mgr.common.checkpoint_manager.totals()["escalations"]
        )
        slot.worker.stop(release=graceful)
        slot.worker = None
        slot.alive = False
        slot.restart_at = restart_at

    def _try_restart(self, identity: str) -> None:
        slot = self.slots[identity]
        try:
            slot.worker = self._start_worker(identity)
            slot.alive = True
            slot.restart_at = None
            log.info("chaos: restarted worker %s", identity)
        except Exception as e:  # noqa: BLE001 - retried next step
            # A restart into a still-armed partition (or any transient)
            # retries next step — a crashed-then-crashing process.
            log.warning("chaos: restart of %s failed (%s); retrying",
                        identity, e)
            if slot.worker is not None:
                try:
                    slot.worker.stop(release=False)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    log.exception(
                        "chaos: half-started %s teardown failed", identity
                    )
                slot.worker = None

    def _apply_driver_events(self, step: int, plan: FaultPlan) -> None:
        for spec in self.schedule.faults:
            if spec.point in (
                POINT_WORKER_KILL, POINT_SIGTERM
            ) and spec.step == step:
                if self.slots[spec.target].alive:
                    plan.record_driver_fire(spec.point)
                restart_at = (
                    None if spec.param == "perma"
                    else step + max(1, spec.duration)
                )
                self._kill(
                    spec.target, restart_at,
                    graceful=spec.point == POINT_SIGTERM,
                )
            elif spec.point == POINT_WIRE_KILL and (
                spec.step <= step < spec.step + max(1, spec.duration)
            ):
                if self.server is not None:
                    if self.server.kill_connections():
                        plan.record_driver_fire(POINT_WIRE_KILL)
            elif spec.point == POINT_RELAY_KILL and (
                spec.step <= step < spec.step + max(1, spec.duration)
            ):
                if self.relay is not None:
                    if self.relay.kill_connections():
                        plan.record_driver_fire(POINT_RELAY_KILL)
            elif spec.point == POINT_REPLICA_FAILOVER:
                idx = int(spec.target or 0)
                if not (0 <= idx < len(self.replicas)):
                    continue
                if spec.step == step:
                    # The replica dies mid-storm: in-flight reads fail
                    # over to the primary inline; the client marks it
                    # down and routes around it.
                    self.replicas[idx].stop()
                    plan.record_driver_fire(POINT_REPLICA_FAILOVER)
                elif step == spec.step + max(1, spec.duration):
                    # Revive on the SAME port — clients hold the URL —
                    # and let the down-mark expiry re-adopt it.
                    assert self.server is not None
                    port = self.replicas[idx].server_address[1]
                    self.replicas[idx] = self.server.read_replica(
                        port=port
                    ).start()
        for slot in self.slots.values():
            if (
                not slot.alive
                and slot.restart_at is not None
                and step >= slot.restart_at
            ):
                self._try_restart(slot.identity)

    # -- the run -----------------------------------------------------------
    def run(self, schedule: FaultSchedule) -> ChaosResult:
        started = _time.perf_counter()
        self.schedule = schedule
        plan = FaultPlan(schedule)
        # Track what THIS run installed: a failed second install (some
        # other owner's plan/clock already registered) must not have
        # the finally below tear down state it never owned.
        plan_installed = clock_installed = False
        violations = {
            "budget": 0,
            "grant_retired_unrolled": 0,
            "node_lost_or_cordoned": 0,
            "incremental_vs_full": 0,
            "checkpoint_spurious_escalations": 0,
            "settle_timeouts": 0,
            "not_converged": 0,
            "completeness_races_unbounded": 0,
            "audit_errors": 0,
        }
        trace: list[dict] = []
        converged = False
        steps = 0
        policy = self._policy()
        try:
            # Install inside the try: a failed clock install (someone
            # else's clock registered) must still roll back the plan
            # this run DID install — and only that.
            faultpoints.install_plan(plan)
            plan_installed = True
            faultpoints.install_clock(self.clock)
            clock_installed = True
            self._build_cluster()
            self._build_fleet()
            plan.begin_step(-1)
            if not self.settle(plan):
                violations["settle_timeouts"] += 1
            self.sim.set_template_hash("v2")
            prev_done: set[str] = set()
            last_armed = schedule.last_armed_step()
            for step in range(self.cfg.resolved_max_steps()):
                steps = step + 1
                plan.begin_step(step)
                self._apply_driver_events(step, plan)
                self.sim.step()
                if self.workload is not None:
                    self.workload.step()
                self.orch.tick()
                for identity in self.cfg.identities():
                    slot = self.slots[identity]
                    if not slot.alive:
                        continue
                    # Quiesce watch delivery before every tick: the
                    # sim/orchestrator writes above (and the previous
                    # worker's apply writes) are otherwise mid-flight,
                    # and whether one lands DURING this build_state
                    # decides a completeness abort by thread timing —
                    # the one wall-clock race the run-twice pin could
                    # lose (observed ~2% of pairs before this barrier).
                    if not self.settle(plan):
                        violations["settle_timeouts"] += 1
                    slot.ticks += 1
                    try:
                        slot.worker.tick(policy)
                    except (ApiError, BuildStateError):
                        # The documented tick contract: a pass aborts,
                        # the next one resumes from labels. Counted —
                        # the bounded-race invariant below.
                        slot.aborts += 1
                self.sim.step()
                if not self.settle(plan):
                    violations["settle_timeouts"] += 1
                disrupted = self._disrupted_pools()
                if len(disrupted) > self.budget:
                    violations["budget"] += 1
                phases = self._ledger_phases()
                newly_done = set(phases["done"]) - prev_done
                for pool in newly_done:
                    node_count = self.cfg.hosts  # nodes per pool
                    if node_count and not self._pool_rolled(pool):
                        violations["grant_retired_unrolled"] += 1
                prev_done = set(phases["done"])
                trace.append({
                    "step": step,
                    "disrupted": sorted(disrupted),
                    "granted": phases["granted"],
                    "done": phases["done"],
                    "alive": sorted(
                        s.identity
                        for s in self.slots.values() if s.alive
                    ),
                    "fired": plan.sync_fire_counts(),
                })
                self.clock.advance(self.cfg.step_dt)
                if step >= last_armed and self._converged(phases):
                    converged = True
                    break
            if not converged:
                violations["not_converged"] += 1
            # -- post-heal invariants (the chaos contract's second half:
            # after every heal, the world must read consistent) --------
            if converged:
                if not self.settle(plan):
                    violations["settle_timeouts"] += 1
                for slot in self.slots.values():
                    if not slot.alive:
                        continue
                    try:
                        violations["incremental_vs_full"] += (
                            slot.worker.mgr.audit_incremental(NS, LABELS)
                        )
                    except BuildStateError:
                        # The audit's own completeness walk raced an
                        # in-flight delivery (only reachable after a
                        # settle timeout): a violation with a name, not
                        # a crashed corpus — the seed stays reportable.
                        violations["audit_errors"] += 1
                    if slot.worker.mgr.completeness_aborts_total >= max(
                        1, slot.ticks
                    ):
                        # Every pass aborting = the wedge the counted
                        # signal exists to catch; tolerated aborts must
                        # stay a bounded minority.
                        violations["completeness_races_unbounded"] += 1
                for name in self.cfg.node_names():
                    state, unsched, ready, _ = self._node_record(name)
                    if state != str(UpgradeState.DONE) or unsched or (
                        not ready
                    ):
                        violations["node_lost_or_cordoned"] += 1
                if self.cfg.checkpoint:
                    for slot in self.slots.values():
                        # Dead incarnations count too (_kill retired
                        # their totals): a spurious escalation must not
                        # vanish with the process that made it.
                        violations["checkpoint_spurious_escalations"] += (
                            slot.escalations_retired
                        )
                        if not slot.alive:
                            continue
                        totals = (
                            slot.worker.mgr.common.checkpoint_manager
                            .totals()
                        )
                        violations["checkpoint_spurious_escalations"] += (
                            totals["escalations"]
                        )
            completeness_aborts = sum(
                s.aborts_retired
                + (
                    s.worker.mgr.completeness_aborts_total
                    if s.alive else 0
                )
                for s in self.slots.values()
            )
            digest = self.final_digest()
            return ChaosResult(
                seed=schedule.seed,
                converged=converged,
                steps=steps,
                violations=violations,
                trace=trace,
                fired=plan.sync_fire_counts(),
                async_engaged=plan.async_points_engaged(),
                completeness_aborts=completeness_aborts,
                final_digest=digest,
                schedule_json=schedule.to_json(),
                wall_s=_time.perf_counter() - started,
            )
        finally:
            if plan_installed:
                faultpoints.clear_plan()
            if clock_installed:
                faultpoints.clear_clock()
            self._teardown()

    def _teardown(self) -> None:
        for slot in self.slots.values():
            if slot.worker is not None:
                try:
                    slot.worker.stop(release=False)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    log.exception("chaos: worker %s teardown failed",
                                  slot.identity)
        for source in self._relay_sources:
            try:
                source.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                log.exception("chaos: relay source teardown failed")
        if self.relay is not None:
            self.relay.stop()
        if self.hub is not None:
            self.hub.stop()
        for replica in self.replicas:
            replica.stop()
        if self.server is not None:
            self.server.stop()


# ---------------------------------------------------------------------------
# Entry points (tools/chaos_run.py + tests + bench)
# ---------------------------------------------------------------------------


def run_schedule(schedule: FaultSchedule) -> ChaosResult:
    """Run one schedule on a fresh fleet (the repro path: a schedule
    JSON is a complete recipe — config rides inside it)."""
    return ChaosFleetHarness(schedule.config).run(schedule)


def run_seed(seed: int, config: Optional[ChaosConfig] = None) -> ChaosResult:
    return run_schedule(generate_schedule(seed, config or ChaosConfig()))


def run_corpus(
    seeds: range,
    config: Optional[ChaosConfig] = None,
    on_result: Optional[Callable[[ChaosResult], None]] = None,
) -> dict:
    """Explore one seed per schedule; returns the corpus summary the CI
    gate floors (``chaos_smoke.schedules_explored``,
    ``chaos_smoke.invariant_violations``)."""
    cfg = config or ChaosConfig()
    results: list[ChaosResult] = []
    fired_points: set[str] = set()
    for seed in seeds:
        result = run_seed(seed, cfg)
        results.append(result)
        fired_points.update(p for p, n in result.fired.items() if n)
        fired_points.update(
            p for p, ok in result.async_engaged.items() if ok
        )
        if on_result is not None:
            on_result(result)
    return {
        "schedules_explored": len(results),
        "invariant_violations": sum(r.total_violations for r in results),
        "not_converged": sum(0 if r.converged else 1 for r in results),
        "fault_points_fired": sorted(fired_points),
        "completeness_aborts": sum(
            r.completeness_aborts for r in results
        ),
        "failing_seeds": [
            r.seed for r in results
            if r.total_violations or not r.converged
        ],
        "wall_s": round(sum(r.wall_s for r in results), 3),
        "violations_by_kind": {
            k: sum(r.violations.get(k, 0) for r in results)
            for k in (results[0].violations if results else {})
        },
    }


def run_policy_matrix(
    seeds: range,
    config: Optional[ChaosConfig] = None,
    compositions: Optional[Sequence[tuple]] = None,
    on_result: Optional[Callable[[ChaosResult], None]] = None,
) -> dict:
    """The ``policy_matrix`` corpus (docs/chaos-harness.md): sweep the
    shipped policy compositions (policy/registry.py
    ``standard_compositions``) over one seed corpus — every
    (composition, seed) cell replays the same schedule shape with the
    pools' upgrade policy composed per docs/policy-plugins.md. The CI
    bench gate floors the aggregate ``budget_violations`` at hard zero:
    no registered composition may widen a disruption past the grant
    budget under ANY explored interleaving."""
    from ..policy import standard_compositions, validate_composition

    cfg = config or ChaosConfig()
    comps = tuple(
        tuple(c) for c in (
            compositions if compositions is not None
            else standard_compositions()
        )
    )
    for comp in comps:
        validate_composition(comp or ("default",))
    cells: dict[str, dict] = {}
    for comp in comps:
        cells["+".join(comp) or "default"] = run_corpus(
            seeds, replace(cfg, policy=comp), on_result=on_result
        )
    summaries = list(cells.values())
    return {
        "compositions": len(comps),
        "schedules_explored": sum(
            c["schedules_explored"] for c in summaries
        ),
        "invariant_violations": sum(
            c["invariant_violations"] for c in summaries
        ),
        "budget_violations": sum(
            c["violations_by_kind"].get("budget", 0) for c in summaries
        ),
        "not_converged": sum(c["not_converged"] for c in summaries),
        "wall_s": round(sum(c["wall_s"] for c in summaries), 3),
        "cells": cells,
    }
