"""First-class test doubles for the five injectable manager interfaces.

Parity surface: the reference ships mockery-generated testify mocks as a
public package (reference: pkg/upgrade/mocks/{CordonManager,DrainManager,
NodeUpgradeStateProvider,PodManager,ValidationManager}.go) so consumer
operators can unit-test their reconcile loops without a cluster. This package
is the same contract, Python-idiomatic: recording mocks with configurable
outcomes plus a stateful provider mock that mutates in-memory node labels the
way the reference suite's fake does (reference: upgrade_suit_test.go:114-130).
"""

from .mocks import (
    Call,
    MockCordonManager,
    MockDrainManager,
    MockNodeUpgradeStateProvider,
    MockPodManager,
    MockValidationManager,
    install_mocks,
)

__all__ = [
    "Call",
    "MockCordonManager",
    "MockDrainManager",
    "MockNodeUpgradeStateProvider",
    "MockPodManager",
    "MockValidationManager",
    "install_mocks",
]
