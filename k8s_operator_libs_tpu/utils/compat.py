"""Stdlib version compatibility shims.

The control-plane modules target the CI interpreter (3.12) but must
import anywhere the operator runs — the deployment image pins an older
Python and cannot pip install backports (the same constraint that makes
tools/lint.py and tools/cover.py stdlib-only).

``StrEnum`` is the one 3.11+ feature the package leans on: the upgrade
state machine, CRD operations and TPU accelerator types are all
string-valued enums whose members must compare and format as their
values (node labels, CLI args, CRD fields). On older interpreters the
fallback below reproduces exactly the two behaviors the codebase
relies on:

* ``UpgradeState.DONE == "upgrade-done"`` (str mixin), and
* ``str(UpgradeState.DONE) == "upgrade-done"`` / f-string
  interpolation yielding the value (3.11 StrEnum defines ``__str__ =
  str.__str__``; a plain ``str``-mixin Enum would render the member
  name).
"""

from __future__ import annotations

import enum

if hasattr(enum, "StrEnum"):  # Python >= 3.11
    StrEnum = enum.StrEnum
else:  # pragma: no cover - exercised only on older interpreters

    class StrEnum(str, enum.Enum):  # type: ignore[no-redef]
        """Minimal backport of :class:`enum.StrEnum` (3.11). All users
        give explicit values, so the ``auto()`` lowercasing hook is
        deliberately omitted."""

        __str__ = str.__str__
        __format__ = str.__format__


__all__ = ["StrEnum"]
