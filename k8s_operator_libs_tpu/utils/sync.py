"""Concurrency primitives for per-node async operations.

Behavioral parity with the reference's upgrade utilities
(reference: pkg/upgrade/util.go:30-89): a thread-safe string set used to
deduplicate in-flight per-node operations, and a keyed mutex that serializes
all state writes for a given node.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Iterator


class StringSet:
    """A thread-safe set of strings (reference: pkg/upgrade/util.go:30-70).

    Used by the drain and pod managers as an "in progress" set so a node whose
    async operation is still running is not scheduled twice
    (reference: pkg/upgrade/drain_manager.go:104, pod_manager.go:160).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._items: set[str] = set()

    def add(self, item: str) -> None:
        with self._lock:
            self._items.add(item)

    def add_if_absent(self, item: str) -> bool:
        """Atomically add ``item`` unless present; True when added.

        The in-progress guard needs test-and-set in ONE lock hold:
        ``has()`` followed by ``add()`` lets two reconcile workers both
        observe the key absent and both schedule the node's operation.
        """
        with self._lock:
            if item in self._items:
                return False
            self._items.add(item)
            return True

    def remove(self, item: str) -> None:
        with self._lock:
            self._items.discard(item)

    def has(self, item: str) -> bool:
        with self._lock:
            return item in self._items

    def __contains__(self, item: object) -> bool:
        return isinstance(item, str) and self.has(item)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def snapshot(self) -> frozenset[str]:
        with self._lock:
            return frozenset(self._items)

    def __iter__(self) -> Iterator[str]:
        """Iterate a point-in-time snapshot (sorted, deterministic):
        concurrent add/remove during iteration neither raises nor leaks
        into the view, matching the reference set's range-over-copy."""
        return iter(sorted(self.snapshot()))

    def clear(self) -> None:
        with self._lock:
            self._items.clear()


class KeyedMutex:
    """A mutex per key (reference: pkg/upgrade/util.go:73-89).

    Serializes state label/annotation writes per node so concurrent async
    managers cannot interleave patches for the same node. Locks are created
    lazily and retained for the lifetime of the instance (bounded by the node
    count of the cluster, as in the reference).
    """

    def __init__(self) -> None:
        self._guard = threading.Lock()
        self._locks: dict[str, threading.Lock] = {}

    def _lock_for(self, key: str) -> threading.Lock:
        with self._guard:
            lock = self._locks.get(key)
            if lock is None:
                lock = threading.Lock()
                self._locks[key] = lock
            return lock

    @contextmanager
    def locked(self, key: str) -> Iterator[None]:
        lock = self._lock_for(key)
        lock.acquire()
        try:
            yield
        finally:
            lock.release()
