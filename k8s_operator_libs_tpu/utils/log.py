"""Logging helpers following the reference's logr verbosity convention.

Reference: pkg/consts/consts.go:24-29 — Error=-2, Warning=-1, Info=0, Debug=1
(zap-compatible numeric levels). We map those onto stdlib logging levels so the
rest of the framework reads the same as the reference while staying idiomatic
Python.
"""

from __future__ import annotations

import logging

LOG_LEVEL_ERROR = -2
LOG_LEVEL_WARNING = -1
LOG_LEVEL_INFO = 0
LOG_LEVEL_DEBUG = 1

_LEVEL_MAP = {
    LOG_LEVEL_ERROR: logging.ERROR,
    LOG_LEVEL_WARNING: logging.WARNING,
    LOG_LEVEL_INFO: logging.INFO,
    LOG_LEVEL_DEBUG: logging.DEBUG,
}


def std_level(logr_level: int) -> int:
    """Translate a logr verbosity into a stdlib logging level.

    Levels above Debug (higher V() = more verbose in logr) stay at DEBUG
    rather than escalating back to INFO.
    """
    if logr_level > LOG_LEVEL_DEBUG:
        return logging.DEBUG
    return _LEVEL_MAP.get(logr_level, logging.INFO)


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"k8s_operator_libs_tpu.{name}")
