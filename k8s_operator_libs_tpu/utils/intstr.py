"""Int-or-percent values, as used by ``maxUnavailable``.

Parity with k8s.io/apimachinery intstr + the reference's scaling use
(reference: pkg/upgrade/upgrade_inplace.go:54-60 — percent of total nodes,
rounded up; api/upgrade/v1alpha1/upgrade_spec.go:39-45 — default "25%").
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Union


@dataclass(frozen=True)
class IntOrString:
    """An absolute int or a percentage string like ``"25%"``."""

    value: Union[int, str]

    def __post_init__(self) -> None:
        if isinstance(self.value, bool):
            raise ValueError("IntOrString value must be int or percent string")
        if isinstance(self.value, str):
            s = self.value.strip()
            if not s.endswith("%"):
                # Tolerate numeric strings ("5") like apimachinery's FromString.
                try:
                    as_int = int(s)
                except ValueError:
                    raise ValueError(f"invalid IntOrString: {self.value!r}") from None
                if as_int < 0:
                    raise ValueError(f"negative IntOrString: {self.value!r}")
                object.__setattr__(self, "value", as_int)
                return
            try:
                pct = int(s[:-1])
            except ValueError:
                raise ValueError(f"invalid percentage: {self.value!r}") from None
            if pct < 0:
                raise ValueError(f"negative percentage: {self.value!r}")
        elif isinstance(self.value, int):
            if self.value < 0:
                raise ValueError(f"negative IntOrString: {self.value!r}")
        else:
            raise ValueError(f"invalid IntOrString type: {type(self.value).__name__}")

    @property
    def is_percent(self) -> bool:
        return isinstance(self.value, str)

    def scaled_value(self, total: int, round_up: bool = True) -> int:
        """Resolve against ``total``; percentages round up by default.

        Mirrors intstr.GetScaledValueFromIntOrPercent as used by the in-place
        strategy (reference: pkg/upgrade/upgrade_inplace.go:54-60).
        """
        if not self.is_percent:
            return int(self.value)
        pct = int(str(self.value).strip()[:-1])
        exact = total * pct / 100.0
        return math.ceil(exact) if round_up else math.floor(exact)

    @staticmethod
    def parse(raw: Union["IntOrString", int, str, None]) -> "IntOrString | None":
        if raw is None:
            return None
        if isinstance(raw, IntOrString):
            return raw
        return IntOrString(raw)

    def to_json(self) -> Union[int, str]:
        return self.value
