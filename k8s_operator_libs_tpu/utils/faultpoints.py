"""Process-wide fault-point registry + virtual clock for the chaos
harness (ROADMAP item 6; docs/chaos-harness.md).

The deterministic schedule driver (``testing/chaos.py``) needs two
things from production code:

* **named fault points** — call sites on the coordination surfaces
  whose *schedules* break fleets (lease protocol rounds, grant-ledger
  writes, watch delivery, hub fan-out) consult :func:`fault_point`
  before acting. With no plan installed (every production deployment,
  every non-chaos test) the consult is one module-global ``None`` check
  — no locks, no allocation, no behavior change;
* **virtualized timers** — annotation-clocked deadlines (checkpoint
  escalation, validation timeout, pod-completion waits) read
  :func:`wall_now` instead of ``time.time`` so a schedule can *drive*
  expiry by advancing a :class:`ChaosClock` instead of sleeping through
  wall-clock timeouts. Components that already take injected
  ``now_fn``/``wall_fn`` (LeaderElector, ShardWorker, the quarantine
  manager) keep that idiom; this hook exists for the durable-clock
  helpers whose call sites have no injection seam.

This module is a LEAF: stdlib only, imported by ``kube/`` and
``upgrade/`` call sites — the full harness (schedule generation,
invariant checks, the fleet driver) lives in ``testing/chaos.py`` and
installs into this registry at run time. Keeping the registry here
avoids the ``kube -> testing -> kube`` import cycle the hooks would
otherwise create.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

#: Action kinds a plan may answer a consult with. Call sites interpret:
#: ``deny``     — fail this protocol round benignly (lease round returns
#:                False, exactly as a lost update race would);
#: ``raise``    — raise ``FaultAction.exc`` at the call site (injected
#:                Conflict/ServerTimeout on a ledger write);
#: ``hold``     — block delivery while the plan keeps answering hold
#:                (a lagging watch stream: events queue upstream, the
#:                consumer's view goes stale, heal releases in order);
#: ``overflow`` — force the hub subscriber buffers over their bound
#:                (stale -> journal self-resume, the replay path).
DENY = "deny"
RAISE = "raise"
HOLD = "hold"
OVERFLOW = "overflow"


@dataclass
class FaultAction:
    """One consult's verdict. ``exc`` is pre-built by the plan (the
    registry itself never imports error types — leaf module)."""

    kind: str
    exc: Optional[BaseException] = None


class ChaosClock:
    """Virtual monotonic + wall time, advanced only by the schedule
    driver — lease expiry, failover probes, and durable-clock deadlines
    all move when the SCHEDULE says time passed, never because the test
    host was slow. Thread-safe: watch/pump threads read it while the
    driver advances."""

    def __init__(
        self, start: float = 100.0, wall_start: float = 1_700_000_000.0
    ) -> None:
        self._lock = threading.Lock()
        self._mono = float(start)
        self._wall = float(wall_start)

    def now(self) -> float:
        """Monotonic reading (LeaderElector/ShardWorker ``now_fn``)."""
        with self._lock:
            return self._mono

    def wall(self) -> float:
        """Wall reading (``wall_fn`` + the durable-clock helpers)."""
        with self._lock:
            return self._wall

    def advance(self, dt: float) -> None:
        with self._lock:
            self._mono += dt
            self._wall += dt


# -- the process-wide registry ---------------------------------------------
# One plan + one clock at a time: the chaos driver owns the whole
# process for a run (it drives every worker in it). Installation is a
# plain attribute swap — consults are lock-free reads of one global.
_plan: Optional[Any] = None
_clock: Optional[ChaosClock] = None


def install_plan(plan: Any) -> None:
    """Install a plan object exposing ``consult(point, ctx) ->
    Optional[FaultAction]``. Refuses to stack plans — overlapping chaos
    runs in one process would attribute faults to the wrong schedule."""
    global _plan
    if _plan is not None and plan is not None:
        raise RuntimeError("a fault plan is already installed")
    _plan = plan


def clear_plan() -> None:
    global _plan
    _plan = None


def install_clock(clock: Optional[ChaosClock]) -> None:
    """Install the virtual clock behind :func:`wall_now`/:func:`mono_now`.
    Same no-stacking rule as plans."""
    global _clock
    if _clock is not None and clock is not None:
        raise RuntimeError("a chaos clock is already installed")
    _clock = clock


def clear_clock() -> None:
    global _clock
    _clock = None


def plan_active() -> bool:
    """True while a fault plan is installed — the cheap pre-check for
    call sites whose CONTEXT computation is itself nontrivial (e.g. a
    per-frame subscriber scan): gate the work on this, then consult.
    Plain consults don't need it; ``fault_point`` is already one
    global read when no plan is installed."""
    return _plan is not None


def fault_point(point: str, **ctx: Any) -> Optional[FaultAction]:
    """Consult the installed plan at a named fault point. ``ctx`` names
    the site's coordinates (lease name, worker identity, informer kind,
    ...) so a schedule can target ONE participant. Returns None — act
    normally — for every consult when no plan is installed."""
    plan = _plan
    if plan is None:
        return None
    return plan.consult(point, ctx)


def wall_now() -> float:
    """``time.time`` unless a chaos clock is installed — THE wall-time
    source for annotation-backed durable clocks (validation timeout,
    checkpoint deadline, pod-completion wait), so deadline escalation is
    schedule-driven under chaos and real-time everywhere else."""
    clock = _clock
    return time.time() if clock is None else clock.wall()


def mono_now() -> float:
    """``time.monotonic`` unless a chaos clock is installed."""
    clock = _clock
    return time.monotonic() if clock is None else clock.now()


def chaos_hold(
    point: str,
    should_abort: Callable[[], bool],
    poll_s: float = 0.002,
    **ctx: Any,
) -> None:
    """Block while the plan answers ``hold`` at ``point`` — the
    delivery-lag primitive (a held watch stream). Returns immediately
    when no plan is installed; ``should_abort`` (the caller's stop
    signal) always wins so a held thread can still shut down."""
    while not should_abort():
        act = fault_point(point, **ctx)
        if act is None or act.kind != HOLD:
            return
        time.sleep(poll_s)
