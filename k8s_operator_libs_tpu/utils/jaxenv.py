"""Hermetic JAX process environments.

The deployment environment may inject an experimental TPU device-plugin
shim into every Python process via ``PYTHONPATH`` (a ``sitecustomize.py``
that registers a PJRT plugin at interpreter startup). When the plugin's
device tunnel is wedged, JAX backend initialization hangs for minutes —
and because the shim hooks backend lookup at startup, flipping
``JAX_PLATFORMS`` afterwards inside the same process is not reliable.

The robust pattern, used by ``bench.py``, ``__graft_entry__.py`` and the
test harness alike, is: probe the default backend in a *subprocess* with
a deadline, and when it is unusable, run the JAX work in a fresh process
whose environment never loaded the shim. A health/validation layer must
always produce a verdict in bounded time — the reference's validation
gate times out rather than hangs (validation_manager.go:71-116,
139-175); these helpers apply the same discipline to backend init.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Mapping, Optional

# Path fragments identifying device-plugin site dirs injected via
# PYTHONPATH. Anything matching is dropped from child environments.
PLUGIN_SITE_MARKERS = (".axon_site",)

_DEVICE_COUNT_FLAG = "--xla_force_host_platform_device_count"


def strip_plugin_paths(pythonpath: str) -> str:
    """Drop device-plugin site dirs from a PYTHONPATH-style string."""
    parts = [p for p in pythonpath.split(os.pathsep) if p]
    kept = [
        p
        for p in parts
        if not any(marker in p for marker in PLUGIN_SITE_MARKERS)
    ]
    return os.pathsep.join(kept)


def plugin_shim_on_path(environ: Optional[Mapping[str, str]] = None) -> bool:
    """True when the ambient environment would load a device-plugin shim
    into a child Python process.

    Deliberately checks only ``PYTHONPATH`` — the one channel a re-exec
    with :func:`hermetic_cpu_env` can actually scrub. A shim installed
    via a site dir or ``.pth`` file would survive the re-exec, so
    detecting it here would only buy a false sense of hermeticity; such
    an installation must be handled by the subprocess *probe* path
    (:func:`probe_default_backend`), which bounds the damage to a
    deadline instead.
    """
    env = os.environ if environ is None else environ
    pythonpath = env.get("PYTHONPATH", "")
    return any(marker in pythonpath for marker in PLUGIN_SITE_MARKERS)


def hermetic_cpu_env(
    n_devices: int = 8, base: Optional[Mapping[str, str]] = None
) -> dict[str, str]:
    """Environment for a subprocess that runs JAX on ``n_devices`` virtual
    host (CPU) devices, immune to ambient device-plugin shims.

    Used for multi-chip sharding validation without multi-chip hardware:
    the same XLA partitioner compiles the sharded program either way.
    """
    env = dict(os.environ if base is None else base)
    pythonpath = strip_plugin_paths(env.get("PYTHONPATH", ""))
    if pythonpath:
        env["PYTHONPATH"] = pythonpath
    else:
        env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith(_DEVICE_COUNT_FLAG)
    ]
    flags.append(f"{_DEVICE_COUNT_FLAG}={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def probe_default_backend(timeout_s: float = 150.0) -> tuple[bool, str]:
    """Probe whether the ambient default JAX backend can initialize and
    list devices within ``timeout_s``, in a throwaway subprocess so a hung
    plugin handshake cannot stall the caller. Returns ``(ok, detail)``
    where ``detail`` is the device list on success or the failure reason.
    """
    code = "import jax; print(','.join(str(d) for d in jax.devices()))"
    try:
        probe = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {timeout_s:.0f}s deadline"
    if probe.returncode != 0:
        tail = (probe.stderr or "").strip().splitlines()[-3:]
        return False, "backend init failed: " + " | ".join(tail)
    return True, (probe.stdout or "").strip()
