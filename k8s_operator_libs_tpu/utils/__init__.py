from .sync import KeyedMutex, StringSet
from .intstr import IntOrString

__all__ = ["KeyedMutex", "StringSet", "IntOrString"]
