from .sync import KeyedMutex, StringSet
from .intstr import IntOrString
from .jaxenv import (
    hermetic_cpu_env,
    plugin_shim_on_path,
    probe_default_backend,
    strip_plugin_paths,
)

__all__ = [
    "KeyedMutex",
    "StringSet",
    "IntOrString",
    "hermetic_cpu_env",
    "plugin_shim_on_path",
    "probe_default_backend",
    "strip_plugin_paths",
]
