"""Lifecycle-resource registration (the LIF8xx literal contract).

Leaf module on purpose: every layer (kube/, fleet/, upgrade/,
runtime/) decorates its background-resource classes here without
creating import cycles through the runtime package. The public surface
re-exports from ``k8s_operator_libs_tpu.runtime``.

Decorating a class with :func:`lifecycle_resource` declares — with
LITERAL method names, readable straight off the AST — which call pair
bounds the class's background footprint (threads, watch streams,
sockets, held Leases). The LIF8xx analyzer
(tools/analyze/lifecycle_discipline.py) scans these decorators the
same way POL704 scans ``@register_policy``: computed names are
invisible and therefore rejected by convention, because a resource the
verifier cannot see is a resource nobody proves gets released.
"""

from __future__ import annotations

from typing import Callable, Iterable, Union

__all__ = ["lifecycle_resource", "registered_resources"]

#: Class name -> (acquire method names, release method names).
_RESOURCES: dict[str, tuple[tuple[str, ...], tuple[str, ...]]] = {}


def lifecycle_resource(
    acquire: Union[str, Iterable[str]] = "start",
    release: Union[str, Iterable[str]] = "stop",
) -> Callable[[type], type]:
    """Class decorator declaring the (acquire, release) method pair
    that bounds the class's background footprint.

    Arguments must be literals (the POL704 literal-registration
    contract). ``acquire="__init__"`` declares construction itself as
    the acquisition — the shape of a class whose ``__init__`` starts
    threads.
    """
    acquires = (acquire,) if isinstance(acquire, str) else tuple(acquire)
    releases = (release,) if isinstance(release, str) else tuple(release)

    def deco(cls: type) -> type:
        _RESOURCES[cls.__name__] = (acquires, releases)
        return cls

    return deco


def registered_resources() -> dict[str, tuple[tuple[str, ...], tuple[str, ...]]]:
    """Snapshot of the runtime registry (class name -> method pairs)."""
    return dict(_RESOURCES)
