"""Process-wide rollout tracing — causally linked spans from grant to
uncordon (docs/tracing.md).

The metric families (``tpu_operator_upgrade_pass_*``, ``_fleet_*``,
``_wire_*``) say *how much* time a roll spent; nothing says *where one
specific roll's* wall time went — orchestrator grant latency vs APF
queueing vs watch-delivery lag vs drain/checkpoint waits. This module is
the leaf span library the whole stack instruments against:

* **spans** — trace id / span id / parent id, wall-clock timestamps from
  :func:`~.faultpoints.wall_now` (``time.time`` in production, the
  virtual ``ChaosClock`` under the chaos harness — which is what makes a
  chaos run's trace export byte-deterministic), a category from the
  attribution taxonomy (``grant``/``lease``/``reconcile``/``wire``/
  ``queue``/``drain``/``checkpoint``/``probe``), free-form attrs, and
  **events** (a per-node state transition with its cause rides the
  bucket span that caused it);
* **a bounded in-memory ring** — finished spans land in a deque with a
  fixed capacity; tracing is flight-recorder-shaped, never a leak;
* **JSONL export** — one span per line; ``deterministic=True``
  renumbers ids in content order so the same execution exports the same
  bytes regardless of thread interleavings (the chaos run-twice pin);
* **wire context** — W3C-style ``traceparent`` strings
  (``00-<trace>-<span>-01``) stamped by ``RestClient`` and parsed by
  ``LocalApiServer``, so a server span joins the client's trace and
  client-observed latency decomposes into APF queue wait vs dispatch;
* **write origins** — the fake apiserver records, per resourceVersion,
  the trace that performed the write; informer deliveries link their
  span to it, so a reconcile pass can be traced back to the write that
  woke it — across watch windows, killed connections, and hub resume
  replays (the origin is keyed by rv, which survives them all).

This module is a LEAF (stdlib only) and follows the ``faultpoints.py``
contract exactly: one process-wide :class:`Tracer`, installed/cleared by
the observer (bench, chaos runner, the example CLI's ``--trace-export``);
with no tracer installed every instrumentation site costs ONE module-
global ``None`` check — no locks, no allocation, no behavior change.
With a tracer installed, a settled pool's reconcile pass still emits
ZERO spans (the pass span is opened lazily, only when the pass has
work) — pinned by the ``settled_pool_noop`` bench and
tests/test_tracing.py.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Iterable, Optional

from .faultpoints import wall_now

#: Attribution taxonomy (docs/tracing.md): every span carries one of
#: these; ``tools/trace_view.py`` buckets the critical path by them and
#: treats anything else as unattributed. ``idle`` is derived (wall time
#: no span covers), never stamped on a span.
CATEGORIES = (
    "grant",      # FleetOrchestrator grant rounds / done reports
    "lease",      # LeaderElector protocol rounds
    "reconcile",  # build_state/apply_state passes + their buckets
    "wire",       # HTTP requests, server dispatch, informer delivery
    "queue",      # APF queue wait at the LocalApiServer
    "drain",      # node drain / eviction waits
    "checkpoint", # checkpoint request→ack→manifest arcs
    "probe",      # validation batteries / restore gates
    "write",      # provider write-batch flushes (upgrade/write_batch.py)
)

#: Default ring capacity: a 64-pool roll at 2 workers produces a few
#: tens of thousands of spans; the flight recorder keeps the most
#: recent window and drops the oldest beyond this.
DEFAULT_CAPACITY = 262_144

#: Bounded write-origin book: rv -> (trace, span, wall). Keyed by the
#: monotonically increasing resourceVersion, so eviction is FIFO.
DEFAULT_ORIGIN_CAPACITY = 16_384

#: Deterministic-export cutoff for chaos runs: the virtual
#: ``ChaosClock`` starts at wall 1.7e9 (``faultpoints.ChaosClock``) and
#: advances by schedule steps (seconds-scale), so anything below this
#: bound is virtual time; spans stamped on REAL time (harness teardown,
#: after the clock retires — outside the deterministic record) sit far
#: above it. One constant, shared by ``tools/chaos_run.py`` and the
#: run-twice determinism pin.
CHAOS_EXPORT_CUTOFF = 1_750_000_000.0


class Span:
    """One in-flight or finished span. Mutation (events, links, attrs)
    is guarded by the owning tracer's lock — bucket fan-out threads
    append state-transition events to one shared bucket span."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "category",
        "start", "end", "attrs", "events", "links",
    )

    def __init__(
        self,
        trace_id: str,
        span_id: str,
        parent_id: str,
        name: str,
        category: str,
        start: float,
        attrs: Optional[dict[str, Any]] = None,
    ) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.category = category
        self.start = start
        self.end: Optional[float] = None
        self.attrs: dict[str, Any] = attrs or {}
        #: (ts, name, attrs) triples — the flight recorder's raw
        #: material (per-node state transitions with cause).
        self.events: list[tuple[float, str, dict[str, Any]]] = []
        #: Trace ids this span is causally linked to beyond its parent
        #: (the writes whose watch deltas woke this reconcile pass).
        self.links: list[str] = []

    def to_record(self) -> dict[str, Any]:
        return {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "category": self.category,
            "start": round(self.start, 6),
            "end": round(self.end if self.end is not None else self.start, 6),
            "attrs": self.attrs,
            "events": [
                {"ts": round(ts, 6), "name": name, "attrs": attrs}
                for ts, name, attrs in self.events
            ],
            "links": list(self.links),
        }


class Tracer:
    """The process-wide span recorder (flight-recorder ring + id
    allocation + the write-origin book). One per process at a time,
    installed via :func:`install_tracer` — the ``faultpoints`` pattern.
    All internal state is guarded by ONE leaf lock; nothing blocks
    under it."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        origin_capacity: int = DEFAULT_ORIGIN_CAPACITY,
    ) -> None:
        self._lock = threading.Lock()
        self._finished: deque[dict[str, Any]] = deque(maxlen=int(capacity))
        self._trace_seq = 0
        self._span_seq = 0
        self.started = 0
        self.finished = 0
        #: rv(str) -> (trace_id, span_id, wall) — the write-origin book
        #: informer deliveries link against. FIFO-bounded.
        self._origins: dict[str, tuple[str, str, float]] = {}
        self._origin_order: deque[str] = deque()
        self._origin_capacity = int(origin_capacity)

    # -- id allocation ------------------------------------------------------
    def new_trace_id(self) -> str:
        with self._lock:
            self._trace_seq += 1
            return f"{self._trace_seq:032x}"

    def _new_span_id_locked(self) -> str:
        self._span_seq += 1
        return f"{self._span_seq:016x}"

    # -- span lifecycle -----------------------------------------------------
    def start_span(
        self,
        name: str,
        category: str = "",
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        start: Optional[float] = None,
        attrs: Optional[dict[str, Any]] = None,
        parent: Optional[Span] = None,
    ) -> Span:
        """Open a span. Parentage, most specific wins: an explicit
        ``parent`` span, else explicit ``trace_id``/``parent_id`` (the
        wire-propagation path), else the calling thread's current span,
        else a fresh root trace."""
        if parent is None and trace_id is None and parent_id is None:
            parent = current_span()
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        with self._lock:
            if trace_id is None:
                self._trace_seq += 1
                trace_id = f"{self._trace_seq:032x}"
            span = Span(
                trace_id,
                self._new_span_id_locked(),
                parent_id or "",
                name,
                category,
                start if start is not None else wall_now(),
                attrs,
            )
            self.started += 1
        return span

    def end_span(self, span: Optional[Span], end: Optional[float] = None) -> None:
        if span is None:
            return
        with self._lock:
            if span.end is not None:
                return  # already finished (idempotent teardown paths)
            span.end = end if end is not None else wall_now()
            self._finished.append(span.to_record())
            self.finished += 1

    def add_span(
        self,
        name: str,
        category: str,
        start: float,
        end: float,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attrs: Optional[dict[str, Any]] = None,
        parent: Optional[Span] = None,
    ) -> None:
        """Record an already-measured interval in one call (the APF
        queue-wait shape: enqueue/dispatch stamps exist before the span
        does)."""
        span = self.start_span(
            name, category, trace_id=trace_id, parent_id=parent_id,
            start=start, attrs=attrs, parent=parent,
        )
        self.end_span(span, end=end)

    def add_event(self, span: Span, name: str, **attrs: Any) -> None:
        with self._lock:
            span.events.append((wall_now(), name, attrs))

    def add_link(self, span: Span, trace_id: str) -> None:
        with self._lock:
            if trace_id and trace_id != span.trace_id and (
                trace_id not in span.links
            ):
                span.links.append(trace_id)

    # -- write origins ------------------------------------------------------
    def record_write_origin(
        self, rv: str, trace_id: str, span_id: str
    ) -> None:
        """Remember which trace performed the write that produced ``rv``
        (called by the fake apiserver's emit choke point under an active
        server/bucket span). Keyed by rv so the link survives watch
        windows, killed connections, and hub journal replays."""
        rv = str(rv)
        with self._lock:
            if rv not in self._origins:
                self._origin_order.append(rv)
                while len(self._origin_order) > self._origin_capacity:
                    self._origins.pop(self._origin_order.popleft(), None)
            self._origins[rv] = (trace_id, span_id, wall_now())

    def write_origin(
        self, rv: str
    ) -> Optional[tuple[str, str, float]]:
        """(trace_id, span_id, write_wall) for a revision, if the write
        happened under a traced context in this process."""
        with self._lock:
            return self._origins.get(str(rv))

    # -- export -------------------------------------------------------------
    def records(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._finished)

    def export_jsonl(
        self, path: str, deterministic: bool = False
    ) -> int:
        """Write one JSON object per finished span; returns the span
        count. ``deterministic=True`` normalizes first (see
        :func:`normalize_records`) so the same execution exports the
        same bytes regardless of thread interleavings — the chaos
        harness's run-twice determinism contract."""
        records = self.records()
        if deterministic:
            records = normalize_records(records)
        with open(path, "w", encoding="utf-8") as f:
            for record in records:
                f.write(json.dumps(record, sort_keys=True) + "\n")
        return len(records)

    def export_bytes(
        self,
        deterministic: bool = True,
        end_before: Optional[float] = None,
    ) -> bytes:
        """The normalized export as bytes — what the chaos runner's
        ``--trace-json`` writes and the run-twice determinism pin
        compares. ``end_before`` drops spans finishing at or past that
        wall time; pass :data:`CHAOS_EXPORT_CUTOFF` for chaos runs
        (teardown happens after the virtual clock retires, on real
        time — those spans are outside the deterministic record)."""
        records = self.records()
        if end_before is not None:
            records = [r for r in records if r["end"] < end_before]
        if deterministic:
            records = normalize_records(records)
        return "".join(
            json.dumps(record, sort_keys=True) + "\n" for record in records
        ).encode()


def _content_key(record: dict[str, Any]) -> str:
    """A span's identity MINUS its allocated ids: what it did, when,
    with which attrs/events. Two runs of the same (virtual-clock)
    execution produce the same content keys whatever order threads
    allocated ids in."""
    return json.dumps(
        [
            record["start"], record["end"], record["name"],
            record["category"], record["attrs"],
            sorted(
                json.dumps(e, sort_keys=True) for e in record["events"]
            ),
        ],
        sort_keys=True,
    )


def normalize_records(records: list[dict]) -> list[dict]:
    """Deterministic export order + id renumbering.

    Spans are sorted by content (start/end/name/category/attrs/events),
    disambiguated through their FULL ancestor chain's content keys —
    two workers' same-shaped bucket spans differ through their pass
    spans' ``worker`` attr, and an ``apf.queue`` under a
    ``server.request`` under an ``http.request`` still reaches the
    distinguishing pass span four levels up. Then trace/span ids are
    renumbered in that order and parent/link references remapped.
    Events within a span are sorted by (ts, name, attrs) — bucket
    fan-out threads append them in arrival order, which is not
    deterministic; their content is."""
    by_span = {r["span"]: r for r in records}
    keys = {r["span"]: _content_key(r) for r in records}

    def lineage_key(record: dict) -> tuple:
        chain = [keys[record["span"]]]
        seen = {record["span"]}
        parent = by_span.get(record["parent"])
        while parent is not None and parent["span"] not in seen:
            seen.add(parent["span"])
            chain.append(keys[parent["span"]])
            parent = by_span.get(parent["parent"])
        return (record["start"], tuple(chain))

    ordered = sorted(records, key=lineage_key)
    trace_map: dict[str, str] = {}
    span_map: dict[str, str] = {}
    for record in ordered:
        if record["trace"] not in trace_map:
            trace_map[record["trace"]] = f"{len(trace_map) + 1:032x}"
        span_map[record["span"]] = f"{len(span_map) + 1:016x}"
    out = []
    for record in ordered:
        fresh = dict(record)
        fresh["trace"] = trace_map[record["trace"]]
        fresh["span"] = span_map[record["span"]]
        # A parent that never finished (or fell off the ring) keeps no
        # id: map it to "" so both runs agree.
        fresh["parent"] = span_map.get(record["parent"], "")
        fresh["links"] = sorted(
            trace_map.get(link, "external") for link in record["links"]
        )
        fresh["events"] = sorted(
            record["events"],
            key=lambda e: (e["ts"], e["name"],
                           json.dumps(e["attrs"], sort_keys=True)),
        )
        out.append(fresh)
    return out


# -- the process-wide registry (the faultpoints pattern) --------------------
_tracer: Optional[Tracer] = None
_ctx = threading.local()


def install_tracer(tracer: Optional[Tracer]) -> None:
    """Install the process-wide tracer. Refuses to stack — overlapping
    observers would interleave unrelated rolls into one flight record."""
    global _tracer
    if _tracer is not None and tracer is not None:
        raise RuntimeError("a tracer is already installed")
    _tracer = tracer


def clear_tracer() -> None:
    global _tracer
    _tracer = None


def tracer() -> Optional[Tracer]:
    """The installed tracer, or None. THE fast path: every
    instrumentation site reads this one global and stops there when
    tracing is off."""
    return _tracer


def current_span() -> Optional[Span]:
    """The calling thread's active span (None when tracing is off or
    nothing is active). One global read on the disabled path."""
    if _tracer is None:
        return None
    return getattr(_ctx, "span", None)


def current_trace_id() -> Optional[str]:
    span = current_span()
    return span.trace_id if span is not None else None


class _Activation:
    """Handle for an explicitly activated span: ``close()`` restores the
    thread's previous current span (the pass-span lifecycle, which
    outlives any single ``with`` block)."""

    __slots__ = ("_previous",)

    def __init__(self, previous: Optional[Span]) -> None:
        self._previous = previous

    def close(self) -> None:
        _ctx.span = self._previous


def activate(span: Optional[Span]) -> _Activation:
    previous = getattr(_ctx, "span", None)
    _ctx.span = span
    return _Activation(previous)


class _UseSpan:
    """Context manager: run a block with ``span`` as the thread's
    current span (cross-thread propagation: TaskRunner installs the
    bucket span in its fan-out workers)."""

    __slots__ = ("_span", "_previous")

    def __init__(self, span: Optional[Span]) -> None:
        self._span = span
        self._previous = None

    def __enter__(self) -> Optional[Span]:
        self._previous = getattr(_ctx, "span", None)
        _ctx.span = self._span
        return self._span

    def __exit__(self, *exc) -> None:
        _ctx.span = self._previous


class _NullScope:
    """The disabled path's context manager: ONE module-level singleton,
    so ``with span(...)`` costs no allocation when tracing is off."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> None:
        return None


_NULL_SCOPE = _NullScope()


def use_span(span: Optional[Span]):
    """``with use_span(s):`` — thread-context propagation. Returns the
    null singleton when there is nothing to install."""
    if span is None:
        return _NULL_SCOPE
    return _UseSpan(span)


class _SpanScope:
    """``with span(...) as s:`` — open on enter (as the thread's
    current), end + restore on exit."""

    __slots__ = ("_tracer", "_name", "_category", "_attrs", "_span",
                 "_previous")

    def __init__(self, tracer: Tracer, name: str, category: str,
                 attrs: dict[str, Any]) -> None:
        self._tracer = tracer
        self._name = name
        self._category = category
        self._attrs = attrs
        self._span: Optional[Span] = None
        self._previous: Optional[Span] = None

    def __enter__(self) -> Span:
        self._span = self._tracer.start_span(
            self._name, self._category, attrs=self._attrs
        )
        self._previous = getattr(_ctx, "span", None)
        _ctx.span = self._span
        return self._span

    def __exit__(self, *exc) -> None:
        _ctx.span = self._previous
        self._tracer.end_span(self._span)


def span(name: str, category: str = "", **attrs: Any):
    """Open a span as a context manager, parented to the thread's
    current span. The disabled path returns the null singleton — one
    global read, zero allocation."""
    t = _tracer
    if t is None:
        return _NULL_SCOPE
    return _SpanScope(t, name, category, attrs)


def add_event(name: str, **attrs: Any) -> None:
    """Attach an event to the thread's current span; no-op (one global
    read) when tracing is off or nothing is active. THE state-transition
    hook: the provider calls this under the bucket span that caused the
    transition, so the flight recorder sees (node, from, to, cause,
    pass) with full causal parentage."""
    t = _tracer
    if t is None:
        return
    span_ = getattr(_ctx, "span", None)
    if span_ is None:
        return
    t.add_event(span_, name, **attrs)


# -- W3C-style wire context -------------------------------------------------

def traceparent() -> Optional[str]:
    """``00-<trace>-<span>-01`` for the thread's current span — what
    RestClient stamps on every request. None when tracing is off or no
    span is active (the header is simply not sent)."""
    span_ = current_span()
    if span_ is None:
        return None
    return f"00-{span_.trace_id}-{span_.span_id}-01"


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: str) -> Optional[tuple[str, str]]:
    """(trace_id, span_id) from a traceparent header; None on anything
    malformed — a bad header must never fail a request."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4 or parts[0] != "00":
        return None
    trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id


def record_write_origin(rv: Any) -> None:
    """Fake-apiserver choke point: remember the current trace context as
    the origin of the write that produced ``rv``. One global read when
    tracing is off; a write outside any span records nothing."""
    t = _tracer
    if t is None:
        return
    span_ = getattr(_ctx, "span", None)
    if span_ is None:
        return
    t.record_write_origin(str(rv), span_.trace_id, span_.span_id)


def iter_jsonl(path: str) -> Iterable[dict[str, Any]]:
    """Yield span records from an exported JSONL file (tools/trace_view
    and tests read through this)."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)
