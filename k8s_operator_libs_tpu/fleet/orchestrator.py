"""Fleet orchestrator — per-pool roll grants under one global budget.

The per-pool planner (tpu/planner.py) orders slices degraded-first
WITHIN a pool; this module generalizes that one tier up (ROADMAP item 1,
Guard in PAPERS.md): many pools, one *global* disruption budget, the
most degraded pool rolls first. Coordination is the FleetRollout CR
(api/fleet_v1alpha1.py) — the orchestrator writes grants into its
status ledger, shard workers (fleet/worker.py) consume grants and
report completions, and every participant is stateless between ticks:
kill any of them mid-roll and its successor resumes from the CR plus
node labels, nothing else.

Budget semantics (the safety property the fleet bench hard-asserts):
a grant is permission to disrupt ONE pool; it stays charged against
``maxUnavailablePools`` from the moment it is issued until the worker
reports the pool ``done`` (all nodes upgrade-done and schedulable
again). A worker dying mid-roll leaves the grant charged — the budget
holds across the lease failover, because the ledger, not the worker,
carries it.

:class:`FleetHealthAggregator` is the fold (ROADMAP item 4d): per-shard
``HealthSource`` maps collapse into per-pool worst-member scores — one
straggler host throttles its pool's collectives, so the pool is only as
healthy as its sickest member, exactly the slice-level rule
``SliceAssessment.effective_score`` applies one tier down — and the
orchestrator consumes the resulting degraded-first queue when granting.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional, Sequence

from ..api.fleet_v1alpha1 import (
    FLEET_ROLLOUT_KIND,
    POOL_DONE,
    POOL_GRANTED,
    POOL_PENDING,
    pools_in_phase,
    rollout_spec,
    set_pool_phase,
)
from ..api.telemetry_v1alpha1 import trend_value
from ..kube.client import ApiError, Client, ConflictError
from ..utils import tracing
from ..utils.faultpoints import fault_point
from ..utils.log import get_logger

log = get_logger("fleet.orchestrator")


class FleetHealthAggregator:
    """Fold N (shard-scoped) HealthSource maps into per-pool scores.

    Sources register via :meth:`add_source`; each exposes the memoized
    ``snapshot()`` mapping (node -> NodeHealth) the telemetry plane
    already maintains, so a fold over a settled fleet costs N dict
    walks of already-built maps — no reads, no parsing. ``pool_of``
    maps a node name to its pool key, the SAME pure mapping the shard
    workers partition by (fleet/worker.py), so the aggregate and the
    partition can never disagree about which pool a node belongs to.
    """

    def __init__(self, pool_of: Callable[[str], str]) -> None:
        self._pool_of = pool_of
        self._lock = threading.Lock()
        self._sources: list[Any] = []

    def add_source(self, source: Any) -> None:
        """Register a HealthSource-shaped object (``snapshot()``)."""
        with self._lock:
            if source not in self._sources:
                self._sources.append(source)

    def pool_health(self) -> dict[str, tuple[float, int]]:
        """pool -> (worst member score, worst member trend). A node
        reported by several sources (a shard mid-failover can appear in
        the old and new owner's scope) folds by worst — duplication can
        only make a pool look sicker, never healthier.

        Scores are LINK-AWARE (ISSUE 12): the per-shard maps merge into
        one fleet view first, the symmetric link-topology fold runs
        over the MERGED map (a cross-shard link's two endpoint reports
        live in different sources — folding per source would miss the
        pair), and every node's score is the worst of its own aggregate
        and its worst incident link. A node named only as a link PEER
        still degrades its pool — link-degraded pools propagate
        degraded-first with no report of their own. Duplicate copies
        of one node merge PER AXIS (worst aggregate score AND, per
        peer, the sicker link observation) — picking one whole report
        could discard a sicker link map riding the higher-score copy."""
        from ..api.telemetry_v1alpha1 import (
            NodeHealth,
            effective_scores,
            sicker_link,
        )

        with self._lock:
            sources = list(self._sources)
        scores: dict[str, float] = {}
        links: dict[str, dict[str, Any]] = {}
        trends: dict[str, int] = {}
        for source in sources:
            for node_name, health in source.snapshot().items():
                previous = scores.get(node_name)
                if previous is None or health.score < previous:
                    scores[node_name] = health.score
                for peer, link in health.links.items():
                    per_node = links.setdefault(node_name, {})
                    current = per_node.get(peer)
                    per_node[peer] = (
                        link if current is None else sicker_link(link, current)
                    )
                trend = trend_value(health.trend)
                trends[node_name] = min(trend, trends.get(node_name, trend))
        merged = {
            name: NodeHealth(name, score=score, links=links.get(name, {}))
            for name, score in scores.items()
        }
        out: dict[str, tuple[float, int]] = {}
        for node_name, score in effective_scores(merged).items():
            try:
                pool = self._pool_of(node_name)
            except Exception:
                # Suppressed ONLY for peer-only ids (a link peer that
                # never published — intra-node device tags, say): they
                # carry no pool signal a strict mapper must resolve.
                # A mapper failure for a node with its OWN report is
                # the pre-PR-12 loud path — swallowing it would
                # silently drop a degraded pool from the fold.
                if node_name in scores:
                    raise
                log.debug(
                    "pool mapping rejected link peer %r; skipped",
                    node_name,
                )
                pool = ""
            if not pool:
                continue
            trend = trends.get(node_name, 0)
            previous = out.get(pool)
            if previous is not None:
                score = min(score, previous[0])
                trend = min(trend, previous[1])
            out[pool] = (score, trend)
        return out

    def candidate_views(self, pools: Iterable[str]) -> list[Any]:
        """Each pool reduced to the policy view: worst-member score and
        trend from the fold (no telemetry = fully healthy 100), the
        cost tier parsed from the pool name. ``disrupted`` is uniformly
        False — granted pools never re-enter the pending set, so the
        default plugin's disrupted-first key component is constant here
        and the pool order stays the pre-plugin ``(score, trend,
        pool)`` byte-identically."""
        from ..policy import CandidateView, tier_of

        health = self.pool_health()
        return [
            CandidateView(
                name=pool,
                score=health.get(pool, (100.0, 0))[0],
                trend=health.get(pool, (100.0, 0))[1],
                tier=tier_of(pool),
            )
            for pool in pools
        ]

    def ordered(
        self, pools: Iterable[str], plugin: Optional[Any] = None
    ) -> list[str]:
        """``pools`` in degraded-first order, delegated to the policy
        plugin's ``order`` (docs/policy-plugins.md): the default keys
        on ascending worst-member score, degrading trend breaking
        score ties, then name — the planner's ``ordered_candidates``
        key (tpu/planner.py), applied at pool grain."""
        from ..policy import for_spec

        if plugin is None:
            plugin = for_spec(())
        return [view.name for view in plugin.order(self.candidate_views(pools))]


class FleetOrchestrator:
    """Grant pool rolls from the FleetRollout CR's pending set.

    Drive it with :meth:`tick` from any reconcile cadence. A tick never
    raises on API errors (the daemon convention ``LeaderElector``
    follows: a flaky apiserver surfaces as a skipped round, not a
    crashed control plane) and is stateless — every decision re-derives
    from the CR, so orchestrator restarts (or replicas behind their own
    leader election) are free.
    """

    def __init__(
        self,
        client: Client,
        rollout_name: str,
        aggregator: Optional[FleetHealthAggregator] = None,
        policy: Sequence[str] = (),
    ) -> None:
        self.client = client
        self.rollout_name = rollout_name
        self.aggregator = aggregator
        #: Rollout-level policy composition (registry names,
        #: docs/policy-plugins.md) ordering the pending queue and
        #: gating grants; a pool with its own ``spec.pools[].policy``
        #: entry overrides it for that pool's admit. Empty = default
        #: policy, byte-identical to the pre-plugin grant behavior.
        self.policy = tuple(policy)
        #: Pools granted by THIS instance, in grant order — bench/debug
        #: introspection (the durable record is the CR's grantedSeq).
        self.grant_order: list[str] = []
        self.grants_issued = 0
        self.budget_denials = 0
        self.ticks = 0
        self.api_errors = 0
        #: Ledger shape after the most recent successful grant round —
        #: what the ``tpu_operator_fleet_*`` exporter reads (budget
        #: headroom, pools per phase) without its own apiserver GET per
        #: scrape (fleet/metrics.py).
        self.last_summary: dict[str, Any] = {}

    def tick(
        self, wake_traces: Optional[Sequence[str]] = None
    ) -> dict[str, Any]:
        """One grant round; returns a summary of the ledger after it.

        ``wake_traces`` carries the trace ids of the watch deliveries
        that woke an event-driven caller (fleet/wakeup.py): the grant
        span LINKS to them, extending the PR-14 causal chain one hop
        upstream — completion report → delivery → this grant round."""
        self.ticks += 1
        try:
            # Grant attribution (docs/tracing.md): one span per round;
            # the ledger write made under it stamps this trace as the
            # write origin, so a worker's next pass LINKS back here —
            # the grant → delta → reconcile causal chain.
            with tracing.span(
                "fleet.grant_round", category="grant",
                rollout=self.rollout_name,
            ) as grant_span:
                if grant_span is not None and wake_traces:
                    tracer = tracing.tracer()
                    if tracer is not None:
                        for trace_id in wake_traces:
                            tracer.add_link(grant_span, trace_id)
                summary = self._grant_round()
                if grant_span is not None:
                    grant_span.attrs.update(
                        grants=len(summary.get("new_grants", []) or []),
                        pending=summary.get("pending", 0),
                    )
                    for pool in summary.get("new_grants", []) or []:
                        tracing.add_event("fleet.grant", pool=pool)
            if "error" not in summary and "missing" not in summary:
                self.last_summary = dict(summary)
            return summary
        except ConflictError:
            # retry_on_conflict exhausted: heavy status contention this
            # round (workers reporting completions). Next tick re-reads.
            self.api_errors += 1
            log.info("fleet orchestrator: grant round lost its conflicts")
            return {"error": "conflict"}
        except ApiError as e:
            self.api_errors += 1
            log.warning("fleet orchestrator: tick failed: %s", e)
            return {"error": str(e)}

    def _grant_round(self) -> dict[str, Any]:
        from ..kube.client import retry_on_conflict
        from ..policy import BudgetView, CandidateView, for_spec, tier_of
        from ..utils.faultpoints import wall_now

        summary: dict[str, Any] = {}
        plugin = for_spec(self.policy)

        def attempt() -> None:
            obj = self.client.get_or_none(FLEET_ROLLOUT_KIND, self.rollout_name)
            if obj is None:
                summary.clear()
                summary["missing"] = True
                return
            raw = obj.raw
            spec = rollout_spec(raw)
            granted = pools_in_phase(raw, POOL_GRANTED)
            done = pools_in_phase(raw, POOL_DONE)
            pending = pools_in_phase(raw, POOL_PENDING)
            budget = spec.resolved_budget()
            slots = budget - len(granted)
            if self.aggregator is not None:
                order = self.aggregator.ordered(pending, plugin=plugin)
            else:
                # No health fold wired: every view reads fully healthy,
                # so the default plugin's order is plain name order —
                # the pre-plugin ``sorted(pending)`` byte-identically.
                order = [
                    view.name
                    for view in plugin.order(
                        [
                            CandidateView(name=pool, tier=tier_of(pool))
                            for pool in pending
                        ]
                    )
                ]
            # Per-grant admission (docs/policy-plugins.md): a pool with
            # its own spec.pools[].policy composition overrides the
            # rollout-level one for its OWN gate. The default admit is
            # unconditional, so a policy-free rollout grants exactly
            # the pre-plugin prefix order[:slots].
            view = BudgetView(
                total=len(spec.pools),
                in_progress=len(granted),
                unavailable=len(granted),
                candidates=len(pending),
                max_parallel=0,
                max_unavailable=budget,
                now=wall_now(),
            )
            grants: list[str] = []
            for pool in order:
                if len(grants) >= max(0, slots):
                    break
                gate = (
                    for_spec(spec.policy_for(pool))
                    if spec.policy_for(pool)
                    else plugin
                )
                decision = gate.admit(
                    CandidateView(name=pool, tier=tier_of(pool)), view
                )
                if not decision.allowed:
                    log.info(
                        "fleet orchestrator: pool %s refused by policy "
                        "%s: %s", pool, gate.name, decision.reason,
                    )
                    continue
                grants.append(pool)
            denied = len(pending) - len(grants)
            summary.clear()
            summary.update(
                {
                    "budget": budget,
                    "granted": len(granted) + len(grants),
                    "done": len(done),
                    "pending": denied,
                    "new_grants": list(grants),
                }
            )
            if not grants:
                # Nothing to write: a settled ledger costs one GET.
                self.budget_denials += denied
                return
            status = raw.setdefault("status", {})
            seq = int(status.get("grantsIssued", 0) or 0)
            for pool in grants:
                seq += 1
                set_pool_phase(raw, pool, POOL_GRANTED, grantedSeq=seq)
            status["grantsIssued"] = seq
            act = fault_point(
                "fleet.grant_write", rollout=self.rollout_name
            )
            if act is not None and act.exc is not None:
                # Chaos fault point (docs/chaos-harness.md): the grant
                # write fails at the one place a real apiserver would
                # fail it — after the decision, before the ledger moved
                # — so the retry path re-derives from a fresh read.
                raise act.exc
            # Optimistic STATUS write (the ledger lives in the status
            # subresource — a plain update would have it stripped, the
            # real-apiserver behavior kube/fake.py mirrors): the read's
            # resourceVersion rides along, so a worker's concurrent
            # completion report conflicts this attempt and the retry
            # re-derives from the fresh ledger.
            self.client.update_status(obj)
            self.grants_issued += len(grants)
            self.grant_order.extend(grants)
            self.budget_denials += denied
            log.info(
                "fleet orchestrator: granted %s (budget=%d granted=%d "
                "done=%d pending=%d)",
                grants, budget, summary["granted"], len(done), denied,
            )

        retry_on_conflict(attempt)
        return summary
