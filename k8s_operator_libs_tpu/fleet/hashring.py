"""Consistent-hash ring — the fleet tier's key partitioner.

The fleet control plane (docs/fleet-control-plane.md) splits pool/node
keys across shards, and shards across workers, with TWO requirements a
plain ``hash(key) % n`` cannot meet:

* **stability across processes** — every worker must compute the same
  owner for the same key with no coordination. Python's builtin ``hash``
  is randomized per process (PYTHONHASHSEED), so the ring hashes with
  BLAKE2b instead: byte-stable everywhere, forever.
* **bounded churn on membership change** — scaling the shard count (or
  losing a worker from a worker-preference ring) must move only the
  keys adjacent to the changed member, never reshuffle the world: a
  reshuffle would invalidate every shard worker's incremental snapshot
  baseline at once (the O(dirty) reconcile economics of PR 5 are the
  whole point of sharding). Classic consistent hashing with virtual
  nodes (``replicas`` points per member) gives ~K/N expected moved keys
  per membership change; ``tests/test_fleet.py`` pins the bound.

The ring is deliberately tiny and dependency-free — the same altitude
as ``kube/workqueue.py``: a primitive the fleet modules compose, not a
framework.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Iterable, Mapping


def stable_hash(key: str) -> int:
    """Process-stable 64-bit hash (BLAKE2b). NEVER the builtin ``hash``:
    two workers disagreeing on a key's owner would double-manage its
    pool (both roll it — the budget can't see the overlap) or orphan it
    (neither rolls it)."""
    digest = hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(digest, "big")


class HashRing:
    """Consistent-hash ring over string members with virtual nodes.

    Thread-safety: membership mutation and ownership lookup take a leaf
    lock (nothing blocks under it); lookups on a settled ring are a
    binary search over a tuple snapshot.
    """

    def __init__(self, members: Iterable[str] = (), replicas: int = 128) -> None:
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self.replicas = int(replicas)
        self._lock = threading.Lock()
        self._members: set[str] = set()
        #: Sorted virtual-node points: (point_hash, member).
        self._points: list[tuple[int, str]] = []
        for member in members:
            self.add(member)

    # -- membership --------------------------------------------------------
    def add(self, member: str) -> None:
        if not member:
            raise ValueError("ring member must be a non-empty string")
        with self._lock:
            if member in self._members:
                return
            self._members.add(member)
            for replica in range(self.replicas):
                point = stable_hash(f"{member}#{replica}")
                bisect.insort(self._points, (point, member))

    def remove(self, member: str) -> None:
        with self._lock:
            if member not in self._members:
                return
            self._members.discard(member)
            self._points = [
                (point, m) for point, m in self._points if m != member
            ]

    def members(self) -> frozenset[str]:
        with self._lock:
            return frozenset(self._members)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    # -- ownership ---------------------------------------------------------
    def owner(self, key: str) -> str:
        """The member owning ``key``: the first virtual node clockwise
        from the key's hash (wrapping at the top). Raises on an empty
        ring — silently returning a default owner would let an
        unconfigured worker claim the whole fleet."""
        with self._lock:
            if not self._points:
                raise ValueError("hash ring has no members")
            index = bisect.bisect_right(self._points, (stable_hash(key), ""))
            if index == len(self._points):
                index = 0
            return self._points[index][1]

    def assignment(self, keys: Iterable[str]) -> Mapping[str, list[str]]:
        """member -> sorted owned keys, every member present (possibly
        empty) — the fleet bench's balance report."""
        out: dict[str, list[str]] = {m: [] for m in self.members()}
        for key in keys:
            out[self.owner(key)].append(key)
        for owned in out.values():
            owned.sort()
        return out
