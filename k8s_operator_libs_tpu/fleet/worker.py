"""Shard workers — N cooperating processes rolling one fleet.

ROADMAP item 1's runtime shape (docs/fleet-control-plane.md): the fleet
is partitioned into a fixed set of **shards**; pool keys hash onto
shards through the consistent ring (fleet/hashring.py), and each shard
is owned by exactly one worker at a time through a per-shard
``coordination.k8s.io`` Lease (``kube/leader.py`` — the same elector the
controller daemon already campaigns with, one instance per shard). A
worker that dies simply stops renewing: its shards go stale, surviving
workers' failover probes claim them, and the new owner resumes from
node labels + the FleetRollout grant ledger — no state lived in the
dead process.

Per tick a worker does four things, all idempotent:

1. **campaign** — renew held shard leases (every ``retry_period_s``),
   acquire preferred shards eagerly, probe non-preferred shards at the
   slower ``failover_probe_s`` cadence (so a healthy fleet converges to
   the ring-preferred balance instead of thundering-herd claiming);
2. **scope** — adopt the claim set into the shard-scoped snapshot
   source (fleet/scope.py); a change invalidates the incremental
   baseline and re-folds the scoped HealthSource;
3. **reconcile** — one ``build_state``/``apply_state`` pass over the
   owned scope, the unmodified upgrade machinery, with the planner
   swapped for :class:`GrantGatedInplaceManager` when a FleetRollout
   ledger is configured: the POOL is the disruption unit and the grant
   is the budget (the slice planner's whole-slice batching, one tier
   up);
4. **report** — granted pools whose every in-scope node is
   upgrade-done, schedulable, and running a current driver pod are
   marked ``done`` in the ledger (optimistic write), freeing global
   budget.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Optional, Sequence

from ..api.fleet_v1alpha1 import (
    FLEET_ROLLOUT_KIND,
    POOL_DONE,
    POOL_GRANTED,
    pool_phase,
    pools_in_phase,
    set_pool_phase,
)
from ..kube.client import ApiError, Client, retry_on_conflict
from ..kube.leader import LeaderElectionConfig, LeaderElector
from ..upgrade.consts import NULL_STRING, DeviceClass, UpgradeState
from ..upgrade.inplace import InplaceNodeStateManager
from ..upgrade.snapshot import DEFAULT_RESYNC_PERIOD_S
from ..upgrade.state_manager import ClusterUpgradeStateManager, StateOptions
from ..upgrade.task_runner import TaskRunner
from ..utils import tracing
from ..utils.faultpoints import fault_point
from ..utils.log import get_logger
from ..utils.lifecycle import lifecycle_resource
from .hashring import HashRing
from .scope import ShardScopedSnapshotSource

log = get_logger("fleet.worker")


def shard_id(index: int) -> str:
    """Canonical shard name: stable, sortable, ring-hashable."""
    return f"shard-{index:02d}"


@dataclass
class FleetWorkerConfig:
    """One shard worker's identity and fleet wiring.

    ``pool_of`` maps a node NAME to its pool key — a pure string
    function (never a store lookup), so every worker, the orchestrator's
    aggregator, and the scoped source compute identical partitions with
    zero coordination. The default (node name = pool key) shards by
    node, the finest grain; fleet deployments pass the
    name-to-nodepool mapping their naming scheme encodes.
    """

    identity: str
    #: Total FIXED shard count for the fleet — every worker must agree
    #: (it defines the ring). More shards than workers = finer failover
    #: grain.
    shards: int
    namespace: str
    driver_labels: Mapping[str, str] = field(default_factory=dict)
    pool_of: Callable[[str], str] = staticmethod(lambda name: name)
    #: FleetRollout CR to consume grants from / report completions to;
    #: "" = standalone sharding (no orchestrator: the worker's own
    #: policy budget governs, scoped to its shards).
    rollout_name: str = ""
    #: Known peer identities: shard preference = consistent-ring
    #: assignment of shards across workers. None (and no explicit
    #: preferred_shards) = prefer everything — the single-worker shape.
    workers: Optional[Sequence[str]] = None
    #: Explicit preference override (e.g. round-robin by index from the
    #: example CLI); wins over ``workers``.
    preferred_shards: Optional[Sequence[str]] = None
    lease_namespace: str = "kube-system"
    lease_name_prefix: str = "fleet"
    lease_duration_s: float = 15.0
    renew_deadline_s: float = 10.0
    retry_period_s: float = 2.0
    #: Cadence for probing NON-preferred shards (failover path); default
    #: one lease duration — a dead peer's shard is reclaimed about one
    #: lease after it went stale, without hammering healthy leases.
    failover_probe_s: Optional[float] = None
    resync_period_s: float = DEFAULT_RESYNC_PERIOD_S
    verify_every_n: int = 0
    #: Run a shard-scoped HealthSource (NodeHealthReport informer
    #: filtered to owned shards) and attach it to every snapshot —
    #: register it with the orchestrator's FleetHealthAggregator for
    #: the global degraded-first fold.
    with_health: bool = False
    #: Shared :class:`~..kube.watchhub.WatchHub` for CO-HOSTED workers:
    #: every informer this worker runs (snapshot source + HealthSource)
    #: subscribes to the hub's multiplexed upstream streams instead of
    #: opening its own — N workers in one process then cost 1 upstream
    #: watch per kind, not N (docs/wire-path.md "Watch hub"). The hub
    #: rides its OWN client; this worker's client keeps carrying lists,
    #: writes, and lease traffic.
    watch_hub: Optional[Any] = None
    device: Optional[DeviceClass] = None
    #: Route this worker's provider writes through the group-commit
    #: batching tier (upgrade/write_batch.py) and fan buckets out with
    #: ``apply_width`` threads so independent-node PATCHes ride one
    #: pipelined round trip. Ignored when an explicit ``manager`` is
    #: passed — its own StateOptions govern then.
    batch_writes: bool = False
    apply_width: int = 8

    def resolved_failover_probe_s(self) -> float:
        return (
            self.failover_probe_s
            if self.failover_probe_s is not None
            else self.lease_duration_s
        )


class _ShardClaim:
    """Synchronous campaign cadence around one shard's LeaderElector.

    The elector's protocol round (``try_acquire_or_renew``) is already
    sync-drivable with injected clocks; this wrapper adds the worker's
    pacing: held/preferred shards renew every retry period, non-preferred
    shards probe at the failover cadence (first probe deferred by one
    full period, so at a clean start the preferred owner wins its shard
    uncontested), and a held claim is surrendered when renewals have
    failed past the renew deadline — the same deadline the threaded
    elector applies.
    """

    def __init__(
        self,
        shard: str,
        elector: LeaderElector,
        preferred: bool,
        retry_period_s: float,
        renew_deadline_s: float,
        failover_probe_s: float,
    ) -> None:
        self.shard = shard
        self.elector = elector
        self.preferred = preferred
        self.held = False
        #: Observability (tpu_operator_fleet_*): lifetime transitions
        #: into held, and the subset that were FAILOVER claims (a
        #: non-preferred shard acquired — i.e. its preferred owner's
        #: lease went stale and this worker stole it).
        self.acquisitions = 0
        self.failover_acquisitions = 0
        self.losses = 0
        self._retry = retry_period_s
        self._deadline = renew_deadline_s
        self._probe = failover_probe_s
        self._last_attempt: Optional[float] = None
        self._last_success: Optional[float] = None

    def tick(self, now: float) -> bool:
        if self._last_attempt is None and not self.preferred:
            self._last_attempt = now  # defer the first failover probe
            return False
        if self._last_attempt is not None:
            cadence = (
                self._retry if (self.preferred or self.held) else self._probe
            )
            if now - self._last_attempt < cadence:
                return self.held
        self._last_attempt = now
        if self.elector.try_acquire_or_renew():
            if not self.held:
                log.info(
                    "worker %r claimed %s",
                    self.elector.config.identity, self.shard,
                )
                self.acquisitions += 1
                if not self.preferred:
                    self.failover_acquisitions += 1
            self.held = True
            self._last_success = now
        elif self.held and (
            self._last_success is None
            or now - self._last_success > self._deadline
        ):
            log.warning(
                "worker %r lost %s (no renewal within %.1fs)",
                self.elector.config.identity, self.shard, self._deadline,
            )
            self.held = False
            self.losses += 1
        return self.held

    def release(self) -> None:
        if self.held:
            self.held = False
            self.elector.release()


class GrantGatedInplaceManager(InplaceNodeStateManager):
    """The fleet planner: start upgrade-required nodes only in pools the
    FleetRollout ledger currently grants — the whole pool at once.

    This is the slice planner's batching rule one tier up: a granted
    pool's disruption window is already charged to the GLOBAL budget, so
    starting its nodes one by one would multiply the windows for zero
    safety gain (tpu/planner.py makes the same argument for hosts in a
    slice). The per-node budget math of the base class deliberately does
    not run here — the grant IS the budget; pass a permissive per-pool
    policy (docs/fleet-control-plane.md, budget math).
    """

    def __init__(
        self,
        common,
        pool_of: Callable[[str], str],
        granted: Callable[[], frozenset],
    ) -> None:
        super().__init__(common)
        self.pool_of = pool_of
        self.granted = granted

    def process_upgrade_required_nodes(self, state, policy) -> None:
        common = self.common
        candidates = state.nodes_in(UpgradeState.UPGRADE_REQUIRED)
        if not candidates:
            return
        granted = self.granted()
        started: dict[str, int] = {}
        with common._bucket_scope("upgrade-start", len(candidates)):
            for ns in candidates:
                node = ns.node
                # The ack of an explicit upgrade request rides the start
                # transition's PATCH when the node starts this pass (the
                # hot path); a node whose pool lacks a grant (or that
                # skips) still gets the ack on its own write, as before.
                ack = (
                    {common.keys.upgrade_requested_annotation: NULL_STRING}
                    if common.is_upgrade_requested(node)
                    else {}
                )
                if self.pool_of(node.name) not in granted:
                    if ack:
                        common.provider.change_node_upgrade_annotation(
                            node, common.keys.upgrade_requested_annotation,
                            NULL_STRING,
                        )
                    continue  # waits for its grant; no delta
                if common.skip_node_upgrade(node):
                    if ack:
                        common.provider.change_node_upgrade_annotation(
                            node, common.keys.upgrade_requested_annotation,
                            NULL_STRING,
                        )
                    log.info("node %s is marked to skip upgrades", node.name)
                    continue
                common.provider.change_node_state_and_annotations(
                    node, UpgradeState.CORDON_REQUIRED, ack
                )
                started[self.pool_of(node.name)] = (
                    started.get(self.pool_of(node.name), 0) + 1
                )
        if started:
            log.info(
                "fleet planner: started %s (granted=%d pools)",
                started, len(granted),
            )


@dataclass
class TickStats:
    """What one :meth:`ShardWorker.tick` did — the example CLI's print
    line and the bench's accounting."""

    owned: frozenset
    reconciled: bool = False
    scope_changed: bool = False
    pools_completed: list[str] = field(default_factory=list)
    state: Any = None


@lifecycle_resource(acquire="start", release="stop")
class ShardWorker:
    """One fleet worker: shard leases + scoped reconciles + grant I/O.

    Pass an existing (already configured) ``manager`` to keep its
    validation hooks / planners; the worker swaps in the scoped
    snapshot source and, when a rollout ledger is configured, the
    grant-gated planner. Clocks are injectable for deterministic
    failover tests (the LeaderElector convention).
    """

    def __init__(
        self,
        client: Client,
        config: FleetWorkerConfig,
        manager: Optional[ClusterUpgradeStateManager] = None,
        now_fn: Callable[[], float] = time.monotonic,
        wall_fn: Callable[[], float] = time.time,
    ) -> None:
        if config.shards < 1:
            raise ValueError("fleet needs at least one shard")
        self.client = client
        self.config = config
        self._now = now_fn
        self.shards = [shard_id(i) for i in range(config.shards)]
        self.pool_ring = HashRing(self.shards)
        self._pool_of = config.pool_of
        self.source = ShardScopedSnapshotSource(
            client,
            config.namespace,
            dict(config.driver_labels),
            shard_of_node=self._shard_of_node,
            resync_period_s=config.resync_period_s,
            verify_every_n=config.verify_every_n,
            watch_hub=config.watch_hub,
        )
        if manager is None:
            if config.batch_writes:
                # Batching needs a real fan-out to coalesce across nodes
                # (a serial caller stages batches of one), so the threaded
                # runner replaces the inline default here.
                manager = ClusterUpgradeStateManager(
                    client,
                    config.device or DeviceClass.tpu(),
                    runner=TaskRunner(),
                    options=StateOptions(
                        apply_width=config.apply_width, batch_writes=True
                    ),
                )
            else:
                manager = ClusterUpgradeStateManager(
                    client,
                    config.device or DeviceClass.tpu(),
                    runner=TaskRunner(inline=True),
                )
        self.mgr = manager
        self.mgr.snapshot_source = self.source
        self.mgr.provider.set_write_through(self.source.record_write)
        self.mgr.common.pod_manager.revision_source = self.source
        # Pass spans carry the worker identity (docs/tracing.md): co-
        # hosted workers' otherwise identical pass spans stay
        # distinguishable in a trace export — and the deterministic
        # normalization needs it to disambiguate same-shaped children.
        self.mgr.trace_attrs = {"worker": config.identity}
        if config.rollout_name:
            if self.mgr.options.use_maintenance_operator:
                # The orchestrator dispatches upgrade-required processing
                # to the REQUESTOR strategy in maintenance-operator mode,
                # which would silently bypass grant gating — every pool
                # would start at once and the global budget would hold
                # nothing. Refuse loudly instead of disrupting a fleet.
                # The two modes are registered policies with a declared
                # conflict (policy/registry.py CONFLICTS), so the
                # registry's composition validator is the one place the
                # refusal — and its typed PolicyCompositionError naming
                # the clashing policies — lives.
                from ..policy import validate_composition

                validate_composition(
                    ("fleet-grant-gate", "requestor-delegation")
                )
                raise AssertionError(
                    "policy registry failed to refuse fleet-grant-gate "
                    "+ requestor-delegation"
                )  # pragma: no cover — validate_composition raises
            self.mgr.inplace = GrantGatedInplaceManager(
                self.mgr.common, self._pool_of, self.granted_pools
            )
        self.health = None
        preferred = self._preferred_shards()
        probe = config.resolved_failover_probe_s()
        self._claims: dict[str, _ShardClaim] = {}
        for shard in self.shards:
            elector = LeaderElector(
                client,
                LeaderElectionConfig(
                    name=f"{config.lease_name_prefix}-{shard}",
                    namespace=config.lease_namespace,
                    identity=config.identity,
                    lease_duration_s=config.lease_duration_s,
                    renew_deadline_s=config.renew_deadline_s,
                    retry_period_s=config.retry_period_s,
                ),
                now_fn=now_fn,
                wall_fn=wall_fn,
            )
            self._claims[shard] = _ShardClaim(
                shard,
                elector,
                preferred=shard in preferred,
                retry_period_s=config.retry_period_s,
                renew_deadline_s=config.renew_deadline_s,
                failover_probe_s=probe,
            )
        self._rollout_raw: Optional[dict] = None
        self.passes = 0
        self.pools_reported_done = 0
        #: Per-shard reconcile coverage (tpu_operator_fleet_*): how many
        #: ticks each shard was reconciled under this worker's lease —
        #: the per-shard pass-rate series the fleet exporter renders.
        self.shard_passes: dict[str, int] = {s: 0 for s in self.shards}

    def _preferred_shards(self) -> frozenset:
        cfg = self.config
        if cfg.preferred_shards is not None:
            unknown = set(cfg.preferred_shards) - set(self.shards)
            if unknown:
                raise ValueError(f"unknown preferred shards {sorted(unknown)}")
            return frozenset(cfg.preferred_shards)
        if cfg.workers:
            if cfg.identity not in cfg.workers:
                raise ValueError(
                    "config.workers must include this worker's identity"
                )
            worker_ring = HashRing(cfg.workers)
            return frozenset(
                s for s in self.shards if worker_ring.owner(s) == cfg.identity
            )
        return frozenset(self.shards)

    def _shard_of_node(self, node_name: str) -> str:
        return self.pool_ring.owner(self._pool_of(node_name))

    # -- lifecycle ---------------------------------------------------------
    def start(self, sync_timeout: float = 30.0) -> "ShardWorker":
        self.source.start(sync_timeout=sync_timeout)
        if self.config.with_health:
            from ..upgrade.health_source import HealthSource

            self.health = HealthSource(
                self.client,
                node_filter=self.source.in_scope,
                watch_hub=self.config.watch_hub,
            )
            self.mgr.with_health_telemetry(
                self.health, sync_timeout=sync_timeout
            )
        return self

    def stop(self, release: bool = True) -> None:
        if release:
            for claim in self._claims.values():
                claim.release()
        if self.health is not None:
            self.health.stop()
        self.source.stop()

    def __enter__(self) -> "ShardWorker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- introspection -----------------------------------------------------
    def owned_shards(self) -> frozenset:
        return frozenset(s for s, c in self._claims.items() if c.held)

    def lease_stats(self) -> dict[str, int]:
        """Lifetime lease-transition counters summed over this worker's
        claims — the ``tpu_operator_fleet_*`` exporter's failover
        signal (fleet/metrics.py)."""
        return {
            "acquisitions": sum(
                c.acquisitions for c in self._claims.values()
            ),
            "failover_acquisitions": sum(
                c.failover_acquisitions for c in self._claims.values()
            ),
            "losses": sum(c.losses for c in self._claims.values()),
        }

    def granted_pools(self) -> frozenset:
        raw = self._rollout_raw
        if raw is None:
            return frozenset()
        return frozenset(pools_in_phase(raw, POOL_GRANTED))

    # -- the tick ----------------------------------------------------------
    def tick(
        self, policy, wake_traces: Optional[Sequence[str]] = None
    ) -> TickStats:
        """Campaign, scope, reconcile, report — one idempotent round.
        Reconcile errors propagate (the caller's loop owns retry policy,
        the build/apply contract); lease and ledger I/O degrade to a
        skipped sub-step, never a crashed worker.

        ``wake_traces``: trace ids of the watch deliveries that woke an
        event-driven caller (fleet/wakeup.py) — typically the
        orchestrator's grant write. They enter the snapshot source's
        wake book so this tick's pass span links grant → pass."""
        if wake_traces:
            for trace_id in wake_traces:
                self.source.note_wake_trace(trace_id)
        now = self._now()
        held = frozenset(
            shard
            for shard, claim in self._claims.items()
            if claim.tick(now)
        )
        stats = TickStats(owned=held)
        stats.scope_changed = self.source.set_owned_shards(held)
        if stats.scope_changed and self.health is not None:
            # The scoped fold must follow the scope: newly owned shards'
            # reports enter the map from the informer store, lost ones
            # leave.
            self.health.refold()
        if self.config.rollout_name:
            try:
                obj = self.client.get_or_none(
                    FLEET_ROLLOUT_KIND, self.config.rollout_name
                )
                self._rollout_raw = obj.raw if obj is not None else None
            except ApiError as e:
                # Keep acting on the last-observed ledger: grants only
                # ever move forward (granted pools stay granted until
                # done), so a stale view can under-roll, never
                # over-disrupt.
                log.warning("fleet ledger read failed: %s", e)
        if not held:
            return stats
        state = self.mgr.build_state(
            self.config.namespace, dict(self.config.driver_labels)
        )
        self.mgr.apply_state(state, policy)
        self.passes += 1
        for shard in held:
            self.shard_passes[shard] = self.shard_passes.get(shard, 0) + 1
        stats.reconciled = True
        stats.state = state
        if self.config.rollout_name and self._rollout_raw is not None:
            stats.pools_completed = self._report_done_pools(state)
        return stats

    # -- completion reporting ----------------------------------------------
    def _live_revision_hash(self, ds, cache: dict) -> str:
        """The driver DaemonSet's latest rollout hash from a LIVE
        apiserver read (cached per uid within one report round).

        Deliberately NOT the informer-backed revision source: marking a
        pool ``done`` is the one IRREVERSIBLE write in the fleet
        protocol, and a worker whose ControllerRevision watch is a
        delivery behind the rollout's new revision would otherwise
        conclude "nothing to roll" and retire the grant without rolling
        — the level-driven machinery heals every other stale read, but
        a retired grant never comes back. One real LIST per pool
        completion is the price of making the irreversible step read
        the source of truth."""
        uid = ds.uid
        if uid in cache:
            return cache[uid]
        from ..kube.objects import ControllerRevision

        revisions = self.client.list(
            "ControllerRevision",
            namespace=self.config.namespace,
            label_selector=dict(ds.match_labels),
        )
        latest = None
        for obj in revisions:
            cr = ControllerRevision(obj.raw)
            if latest is None or cr.revision > latest.revision:
                latest = cr
        hash_value = ""
        if latest is not None:
            hash_value = latest.hash_label() or latest.name.removeprefix(
                f"{ds.name}-"
            )
        cache[uid] = hash_value
        return hash_value

    def _pool_converged(self, entries, hash_cache: dict) -> bool:
        """Every entry: upgrade-done, schedulable, and a ready driver
        pod CURRENT against the live revision hash. The pod-currency
        check is what makes done-reporting safe on a worker's very
        first pass after a grant: a node whose label still says done
        from BEFORE the driver bump must not let the pool report done
        without rolling (see _live_revision_hash for why the hash comes
        from a live read)."""
        common = self.mgr.common
        for bucket, ns in entries:
            if bucket != UpgradeState.DONE or ns.node.unschedulable:
                return False
            try:
                if not common.is_driver_pod_in_sync(ns):
                    return False
                if ns.driver_daemonset is None:
                    return False
                live_hash = self._live_revision_hash(
                    ns.driver_daemonset, hash_cache
                )
                if not live_hash or (
                    ns.driver_pod.controller_revision_hash() != live_hash
                ):
                    return False
            except Exception as e:  # noqa: BLE001 - treat as not-done
                # A missing hash label / transient revision-read error
                # reads as NOT converged: the report retries next tick,
                # and an irreversible done must never ride an error.
                log.debug(
                    "pool convergence check failed for node %s: %s",
                    ns.node.name, e,
                )
                return False
        return True

    def _report_done_pools(self, state) -> list[str]:
        raw = self._rollout_raw
        assert raw is not None
        granted = set(pools_in_phase(raw, POOL_GRANTED))
        if not granted:
            return []
        by_pool: dict[str, list] = {}
        for bucket, node_states in state.node_states.items():
            for ns in node_states:
                pool = self._pool_of(ns.node.name)
                if pool in granted:
                    by_pool.setdefault(pool, []).append((bucket, ns))
        hash_cache: dict = {}
        done = [
            pool
            for pool, entries in by_pool.items()
            if self._pool_converged(entries, hash_cache)
        ]
        # A granted pool with ZERO nodes in its shard's scope is
        # vacuously converged — and only its shard's owner may say so
        # (for every other worker "no nodes" just means "not my shard").
        # Without this, a ghost pool (an operator typo in spec.pools, or
        # a pool whose nodes were deleted after its grant) would hold a
        # global budget slot forever; enough ghosts would deadlock the
        # whole rollout. The worker's informers are synced (start()
        # blocks on it), so the scoped store is authoritative for owned
        # shards.
        owned = self.source.owned_shards()
        for pool in granted:
            if pool not in by_pool and self.pool_ring.owner(pool) in owned:
                log.warning(
                    "granted pool %r has no nodes in its shard; retiring "
                    "the grant as vacuously done", pool,
                )
                done.append(pool)
        if not done:
            return []

        report_scope = tracing.span(
            "fleet.report_done", category="grant",
            worker=self.config.identity, pools=sorted(done),
        )

        def report() -> None:
            act = fault_point(
                "fleet.status_write",
                rollout=self.config.rollout_name,
                identity=self.config.identity,
            )
            if act is not None and act.exc is not None:
                # Chaos fault point (docs/chaos-harness.md): the
                # pool-done report fails mid-protocol — completion must
                # stay level-derived (re-reported next tick), never
                # lost with the failed write.
                raise act.exc
            obj = self.client.get(
                FLEET_ROLLOUT_KIND, self.config.rollout_name
            )
            changed = False
            for pool in done:
                if pool_phase(obj.raw, pool) == POOL_GRANTED:
                    changed = set_pool_phase(
                        obj.raw, pool, POOL_DONE,
                        completedBy=self.config.identity,
                    ) or changed
            if changed:
                # Status subresource: the ledger lives in status; a
                # plain update would strip it (real-apiserver + fake
                # behavior alike).
                self.client.update_status(obj)

        try:
            with report_scope:
                retry_on_conflict(report)
        except ApiError as e:
            # Reported again next tick — completion is level-derived
            # from node labels + pod currency, not from this write.
            log.warning("fleet completion report failed: %s", e)
            return []
        self.pools_reported_done += len(done)
        log.info(
            "worker %r reported pools done: %s", self.config.identity, done
        )
        return done
