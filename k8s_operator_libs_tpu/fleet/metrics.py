"""``tpu_operator_fleet_*`` — the fleet control plane's metric family.

ROADMAP item 1b (the fleet tier's observability gap, closed by ISSUE
12): the sharded control plane (docs/fleet-control-plane.md) had rich
internal counters — orchestrator grants/denials, per-shard leases,
worker pass counts — and exported none of them. This collector renders
them through the shared exposition emitter (upgrade/metrics.py
``render_rows``/``render_samples``) and serves off the existing
``MetricsServer`` like every other family:

* **ledger** (from ``FleetOrchestrator``): grants / budget denials /
  ticks / api errors as counters, plus the last grant round's ledger
  shape — resolved budget, pools granted / done / pending, and the
  derived **budget headroom** (slots the next round could grant);
* **leases** (from each registered ``ShardWorker``): per-worker owned
  shards, lifetime lease acquisitions and the FAILOVER subset (a
  non-preferred shard stolen from a stale owner — the fleet's
  alert-worthy number), lease losses;
* **passes**: per-worker reconcile passes and the per-shard coverage
  series (``shard_passes{shard=...}``) — a shard whose pass counter
  flatlines while its lease is held is a wedged worker.

Both halves are duck-typed: the orchestrator side needs
``grants_issued``/``budget_denials``/``ticks``/``api_errors``/
``last_summary``; the worker side needs ``config.identity``,
``owned_shards()``, ``passes``, ``shard_passes`` and ``lease_stats()``.
Either can be absent — a worker-only process exports the lease/pass
half, the orchestrator daemon exports the ledger half.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ..upgrade.metrics import merge_label, prom_label, render_rows, render_samples

_PREFIX = "tpu_operator_fleet"


class FleetMetrics:
    """Render the fleet tier's counters for the shared MetricsServer."""

    def __init__(
        self,
        orchestrator: Optional[Any] = None,
        workers: Optional[list] = None,
    ) -> None:
        self._orchestrator = orchestrator
        self._lock = threading.Lock()
        self._workers: list[Any] = list(workers or [])

    def add_worker(self, worker: Any) -> None:
        with self._lock:
            if worker not in self._workers:
                self._workers.append(worker)

    def render(self) -> str:
        out: list[str] = []
        orch = self._orchestrator
        if orch is not None:
            summary = getattr(orch, "last_summary", None) or {}
            budget = int(summary.get("budget", 0) or 0)
            granted = int(summary.get("granted", 0) or 0)
            done = int(summary.get("done", 0) or 0)
            rows = [
                ("grants_total", "counter",
                 "Pool roll grants issued by this orchestrator",
                 orch.grants_issued),
                ("budget_denials_total", "counter",
                 "Pending pools deferred by the global disruption budget",
                 orch.budget_denials),
                ("orchestrator_ticks_total", "counter",
                 "Grant rounds attempted", orch.ticks),
                ("orchestrator_api_errors_total", "counter",
                 "Grant rounds lost to API errors/conflicts",
                 orch.api_errors),
            ]
            if summary:
                rows.extend([
                    ("budget_pools", "gauge",
                     "Resolved maxUnavailablePools of the active rollout",
                     budget),
                    ("pools_granted", "gauge",
                     "Pools currently granted (disruption charged)",
                     granted),
                    ("pools_done", "gauge",
                     "Pools reported done by their shard owners", done),
                    ("pools_pending", "gauge",
                     "Pools still waiting for a grant",
                     int(summary.get("pending", 0) or 0)),
                    ("budget_headroom", "gauge",
                     "Grant slots available to the next round "
                     "(budget - (granted - done))",
                     max(0, budget - max(0, granted - done))),
                ])
            out.append(render_rows(_PREFIX, "", rows))
        with self._lock:
            workers = list(self._workers)
        if workers:
            def worker_label(worker) -> str:
                return prom_label(
                    "worker", str(getattr(worker.config, "identity", ""))
                )

            lease_stats = [(w, w.lease_stats()) for w in workers]
            out.append(render_samples(_PREFIX, [
                ("worker_owned_shards", "gauge",
                 "Shards currently leased per worker",
                 [(worker_label(w), len(w.owned_shards()))
                  for w in workers]),
                ("worker_passes_total", "counter",
                 "Reconcile passes per worker",
                 [(worker_label(w), w.passes) for w in workers]),
                ("lease_acquisitions_total", "counter",
                 "Lifetime shard-lease acquisitions per worker",
                 [(worker_label(w), s["acquisitions"])
                  for w, s in lease_stats]),
                ("lease_failovers_total", "counter",
                 "Acquisitions of NON-preferred shards (stolen from a "
                 "stale owner) per worker — alert on sustained growth",
                 [(worker_label(w), s["failover_acquisitions"])
                  for w, s in lease_stats]),
                ("lease_losses_total", "counter",
                 "Held leases lost past the renew deadline per worker",
                 [(worker_label(w), s["losses"]) for w, s in lease_stats]),
                ("shard_passes_total", "counter",
                 "Reconcile passes per shard (under whichever worker "
                 "held its lease) — a flatline under a held lease is a "
                 "wedged worker",
                 [
                     (merge_label(worker_label(w), "shard", shard), count)
                     for w in workers
                     for shard, count in sorted(w.shard_passes.items())
                 ]),
            ]))
        return "".join(out)
