"""Event-driven tick wakeups for the fleet control plane.

The grant→first-cordon chain used to pay one fixed poll interval per
hop: a worker reported a completion, the orchestrator noticed it on its
NEXT cadence tick, granted, and each worker noticed the grant on ITS
next cadence tick. :class:`WatchWake` replaces the cadence with watch
delivery — one daemon thread per kind follows the stream (a
``WatchHub`` subscription when the fleet shares one, a plain client
watch otherwise) and releases waiters the moment a frame lands, so a
tick starts one delivery after its cause instead of up to one poll
interval later.

Wake→action links ride the PR-14 wake-trace edges: each delivery's
``resourceVersion`` is looked up in the tracer's write-origin book, and
the originating trace ids are handed to the woken tick —
``FleetOrchestrator.tick(wake_traces=...)`` links its grant span to
them, and a worker feeds them to
``IncrementalSnapshotSource.note_wake_trace`` so its pass span links
back to the grant. The chain is measured, not assumed
(docs/tracing.md; the ``grant_latency`` bench floors it).

The loops here are wall-clock threads, so the deterministic chaos
harness does not use them — it drives ticks synchronously. Wakeups are
opt-in wiring for the bench, the example CLI, and real deployments.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from ..kube.client import Client, WatchExpiredError
from ..utils import tracing
from ..utils.log import get_logger
from ..utils.lifecycle import lifecycle_resource

log = get_logger("fleet.wakeup")

#: Bounded watch windows keep the threads responsive to stop();
#: re-watching from the last seen revision sees no gap (journal resume).
WATCH_WINDOW_SECONDS = 5


@lifecycle_resource(acquire="__init__", release="stop")
class WatchWake:
    """Wake an event-driven tick loop on watch delivery for any of
    ``kinds``. One instance per tick loop; ``wait()`` from the loop
    thread, everything else is internal.

    The wake is level-triggered (an Event, not a queue): N deliveries
    between two waits coalesce into ONE wake, which is exactly the
    reconcile contract — a tick re-derives everything from current
    state, so it needs to know *that* something changed, never *what*.
    """

    def __init__(
        self,
        client: Client,
        kinds: Sequence[str],
        namespace: str = "",
        window_seconds: int = WATCH_WINDOW_SECONDS,
    ) -> None:
        self._client = client
        self._namespace = namespace
        self._window = window_seconds
        self._event = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._traces: list[str] = []
        #: Deliveries observed / wakes granted (a wake can carry many
        #: deliveries) — the grant_latency bench's sanity counters.
        self.deliveries = 0
        self.wakes = 0
        self._threads = [
            threading.Thread(
                target=self._follow,
                args=(kind,),
                name=f"watch-wake-{kind.lower()}",
                daemon=True,
            )
            for kind in kinds
        ]
        for t in self._threads:
            t.start()

    # -- consumer side ------------------------------------------------------
    def wait(self, timeout: Optional[float]) -> bool:
        """Block until a delivery lands (or ``timeout``, the fallback
        cadence — wakeups REPLACE the fast poll, the slow resync stays
        as the safety net). True = woken by a delivery."""
        fired = self._event.wait(timeout)
        if fired:
            self._event.clear()
            self.wakes += 1
        return fired

    def consume_traces(self) -> list[str]:
        """Drain the trace ids of the writes whose deliveries woke us
        since the last drain (empty whenever tracing is off)."""
        with self._lock:
            if not self._traces:
                return []
            out, self._traces = self._traces, []
            return out

    def poke(self) -> None:
        """Release the current :meth:`wait` immediately without a
        delivery — the supervisor's drain uses this so a loop parked on
        the fallback cadence notices stop now, not one interval later."""
        self._event.set()

    def stop(self, join_timeout: Optional[float] = None) -> None:
        self._stop.set()
        if join_timeout is not None:
            # A drained daemon must show ZERO watch traffic after stop
            # returns: joining (bounded) closes the race where a
            # follower passed its stop check just before the flag set
            # and would issue one more window.
            deadline = time.monotonic() + join_timeout
            for t in self._threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            return
        # Without a join budget don't join: the threads exit at their
        # next window boundary (bounded by window_seconds) and are
        # daemons regardless.

    # -- follower thread ----------------------------------------------------
    def _follow(self, kind: str) -> None:
        resource_version: Optional[str] = None
        while not self._stop.is_set():
            try:
                for _etype, obj in self._client.watch(
                    kind,
                    namespace=self._namespace,
                    timeout_seconds=self._window,
                    resource_version=resource_version,
                    allow_bookmarks=True,
                ):
                    rv = obj.resource_version
                    if rv:
                        resource_version = rv
                    if _etype == "BOOKMARK":
                        continue  # resume-point only, nothing changed
                    self.deliveries += 1
                    self._note_origin(rv)
                    self._event.set()
                    if self._stop.is_set():
                        return
            except WatchExpiredError:
                # Fell out of the journal window: restart from now. The
                # skipped deltas still wake the loop (this IS a wake —
                # state moved), and ticks re-derive from current state.
                resource_version = None
                self._event.set()
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                if self._stop.is_set():
                    return
                log.warning("watch-wake %s: stream failed: %s", kind, e)
                resource_version = None
                # Back off one window so a hard-down server isn't spun on.
                self._stop.wait(self._window)

    def _note_origin(self, rv: str) -> None:
        tracer = tracing.tracer()
        if tracer is None or not rv:
            return
        origin = tracer.write_origin(rv)
        if origin is None:
            return
        trace_id = origin[0]
        with self._lock:
            if len(self._traces) < 64 and trace_id not in self._traces:
                self._traces.append(trace_id)
