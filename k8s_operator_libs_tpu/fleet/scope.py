"""Shard-scoped snapshots — one worker's window onto the fleet.

A shard worker (fleet/worker.py) reconciles only the pools whose keys
hash to its shards, but its informers watch the FLEET (the shard set a
worker owns changes on failover — a watch-level selector cannot follow
a lease). This module scopes the READ side instead:
:class:`ShardScopedSnapshotSource` extends the incremental source
(upgrade/snapshot.py) so that ``build_state`` sees exactly the owned
shards' world:

* ``nodes()`` / ``pods()`` / ``pods_on_node()`` filter by the node's
  shard (``shard_of_node`` — a pure, name-based mapping through the
  pool ring, so every surface agrees with zero lookups);
* the **completeness invariant** is re-scoped: the DaemonSet's
  ``desiredNumberScheduled`` is rewritten to the in-scope node count
  (event-maintained per shard, re-anchored by ``prime()`` exactly like
  the per-DS pod book), and ``ds_pod_count`` serves the owned-shard
  slice of a per-(uid, shard) twin of the pod book — a missing driver
  pod on an OWNED node still aborts the pass, while another shard's
  drain can never wedge this worker's delta passes;
* **ownership changes invalidate**: acquiring or losing a shard forces
  a full rebuild, because the cached classification was built for a
  different scope (newly owned pools must enter the state, lost ones
  must leave).

Scope limitation, stated plainly: the desired-count rewrite assumes the
driver DaemonSet targets every fleet node (the device-driver deployment
shape on dedicated accelerator pools — and the only shape the upgrade
machinery itself models). A DS whose nodeSelector splits the fleet
would need per-scope eligibility counting here.
"""

from __future__ import annotations

from typing import Callable, Mapping

from ..kube.client import Client
from ..kube.objects import DaemonSet, Node, Pod
from ..upgrade.snapshot import (
    DEFAULT_RESYNC_PERIOD_S,
    IncrementalSnapshotSource,
)
from ..utils.log import get_logger

log = get_logger("fleet.scope")

#: Reserved shard for keys the mapping cannot place (an empty node name,
#: a crashing mapper). Owned by NO worker: an unmappable node escapes
#: every scope — loudly logged, never silently adopted by all workers
#: at once (double management is the worse failure).
UNMAPPED_SHARD = ""


class ShardScopedSnapshotSource(IncrementalSnapshotSource):
    """Incremental snapshot source filtered to a dynamic shard set."""

    def __init__(
        self,
        client: Client,
        namespace: str,
        driver_labels: Mapping[str, str],
        shard_of_node: Callable[[str], str],
        resync_period_s: float = DEFAULT_RESYNC_PERIOD_S,
        verify_every_n: int = 0,
        watch_hub=None,
    ) -> None:
        # Scope state first: super().__init__ registers the event
        # handlers this subclass overrides, and they read these fields.
        self._shard_of_node = shard_of_node
        #: node name -> shard memo. The mapping is pure and the pool
        #: ring is fixed for the source's lifetime, so every surface —
        #: including the `_delta_lock` critical sections pod events run
        #: in — pays a dict hit instead of a ring-lock + bisect per
        #: call; entries are bounded by node names seen. Benign under
        #: concurrent writers (both compute the same value).
        self._shard_memo: dict[str, str] = {}
        self._owned_shards: frozenset[str] = frozenset()
        #: shard -> live node count (event-maintained; prime re-anchors).
        self._node_count_by_shard: dict[str, int] = {}
        #: (owner uid, shard) -> live pod count — the location-keyed twin
        #: of the base per-DS pod book (see _bump_ds_pod_count_locked).
        self._ds_pod_counts_by_shard: dict[tuple[str, str], int] = {}
        super().__init__(
            client,
            namespace,
            driver_labels,
            resync_period_s=resync_period_s,
            verify_every_n=verify_every_n,
            watch_hub=watch_hub,
        )

    # -- shard mapping -----------------------------------------------------
    def shard_of(self, node_name: str) -> str:
        if not node_name:
            return UNMAPPED_SHARD
        shard = self._shard_memo.get(node_name)
        if shard is not None:
            return shard
        try:
            shard = self._shard_of_node(node_name) or UNMAPPED_SHARD
        except Exception:  # noqa: BLE001 - mapper owns its errors
            log.exception("shard mapping failed for node %s", node_name)
            return UNMAPPED_SHARD  # not memoized: a transient error heals
        self._shard_memo[node_name] = shard
        return shard

    def in_scope(self, node_name: str) -> bool:
        return self.shard_of(node_name) in self._owned_shards

    def owned_shards(self) -> frozenset[str]:
        return self._owned_shards

    def set_owned_shards(self, shards: frozenset[str]) -> bool:
        """Adopt a new claim set; returns True (and invalidates the
        incremental baseline) when it changed — the cached state was
        classified for a different scope. Reconcile-thread only, like
        every other cached-state surface of the base class."""
        shards = frozenset(shards)
        if shards == self._owned_shards:
            return False
        self._owned_shards = shards
        self.invalidate()
        return True

    # -- event-maintained scoped books -------------------------------------
    def _on_node_event(self, event_type: str, obj, old) -> None:
        super()._on_node_event(event_type, obj, old)
        if event_type not in ("ADDED", "DELETED"):
            return
        delta = 1 if event_type == "ADDED" else -1
        shard = self.shard_of(obj.name)
        with self._delta_lock:
            self._node_count_by_shard[shard] = (
                self._node_count_by_shard.get(shard, 0) + delta
            )

    def _bump_ds_pod_count_locked(
        self, uid: str, node_name: str, delta: int
    ) -> None:
        super()._bump_ds_pod_count_locked(uid, node_name, delta)
        key = (uid, self.shard_of(node_name))
        self._ds_pod_counts_by_shard[key] = (
            self._ds_pod_counts_by_shard.get(key, 0) + delta
        )

    def _rebase_pod_counts(self, raws: list) -> None:
        """prime()'s settled-store re-anchor, extended to the shard twin
        (both books rebuilt from ONE settled snapshot — re-anchoring
        them from different reads could disagree with each other)."""
        counts: dict[str, int] = {}
        by_shard: dict[tuple[str, str], int] = {}
        for raw in raws:
            refs = (raw.get("metadata") or {}).get("ownerReferences") or []
            uid = refs[0].get("uid") if refs else None
            if not uid:
                continue
            counts[uid] = counts.get(uid, 0) + 1
            node = (raw.get("spec") or {}).get("nodeName") or ""
            key = (uid, self.shard_of(node))
            by_shard[key] = by_shard.get(key, 0) + 1
        with self._delta_lock:
            self._ds_pod_counts = counts
            self._ds_pod_counts_by_shard = by_shard

    def _rebase_node_counts(self, raws: list) -> None:
        counts: dict[str, int] = {}
        for raw in raws:
            name = (raw.get("metadata") or {}).get("name", "")
            shard = self.shard_of(name)
            counts[shard] = counts.get(shard, 0) + 1
        with self._delta_lock:
            self._node_count_by_shard = counts

    def prime(self, state, assignment) -> None:
        super().prime(state, assignment)
        self._informers["Node"].with_settled_store(self._rebase_node_counts)

    # -- scoped reads ------------------------------------------------------
    def scoped_node_count(self) -> int:
        with self._delta_lock:
            return sum(
                self._node_count_by_shard.get(s, 0)
                for s in self._owned_shards
            )

    def nodes(self) -> dict[str, Node]:
        return {
            name: node
            for name, node in super().nodes().items()
            if self.in_scope(name)
        }

    def pods(self, namespace: str, labels: Mapping[str, str]) -> list[Pod]:
        # A pod with no node yet (Pending) belongs to no shard and is
        # dropped: the scoped completeness check counts NODES, and the
        # placement event dirty-marks the node the moment it lands.
        return [
            p
            for p in super().pods(namespace, labels)
            if p.node_name and self.in_scope(p.node_name)
        ]

    def daemonsets(
        self, namespace: str, labels: Mapping[str, str]
    ) -> list[DaemonSet]:
        """Fleet DaemonSets with ``desiredNumberScheduled`` rewritten to
        the in-scope node count — the completeness invariant at shard
        grain (module docstring states the every-node assumption). The
        store's frozen raws are never touched: the rewrite lands on a
        fresh top-level + status dict."""
        scoped_desired = self.scoped_node_count()
        out: list[DaemonSet] = []
        for ds in super().daemonsets(namespace, labels):
            raw = dict(ds.raw)
            raw["status"] = dict(raw.get("status") or {})
            raw["status"]["desiredNumberScheduled"] = scoped_desired
            out.append(DaemonSet(raw))
        return out

    def ds_pod_count(self, uid: str) -> int:
        with self._delta_lock:
            return sum(
                self._ds_pod_counts_by_shard.get((uid, s), 0)
                for s in self._owned_shards
            )

    def pods_on_node(self, name: str) -> list[Pod]:
        # An out-of-scope dirty node (fleet-wide informers mark every
        # node) reclassifies to ZERO entries — update_node drops it from
        # the cached state, which for a never-present node is a no-op.
        if not self.in_scope(name):
            return []
        return super().pods_on_node(name)
