"""Fleet tier: sharded multi-pool control plane
(docs/fleet-control-plane.md; ROADMAP item 1).

Everything below this package reconciles ONE pool from one process; the
fleet tier composes those single-pool units — consistent-hash key
partitioning (hashring), per-shard Lease ownership with automatic
failover and shard-scoped snapshots (worker/scope), and a global
disruption budget coordinated through the FleetRollout grant ledger
(orchestrator; contract in api/fleet_v1alpha1.py) — into N cooperating
workers rolling many pools, degraded-first, without any worker holding
fleet state in memory.
"""

from .hashring import HashRing, stable_hash
from .metrics import FleetMetrics
from .orchestrator import FleetHealthAggregator, FleetOrchestrator
from .scope import ShardScopedSnapshotSource
from .wakeup import WatchWake
from .worker import (
    FleetWorkerConfig,
    GrantGatedInplaceManager,
    ShardWorker,
    TickStats,
    shard_id,
)

__all__ = [
    "FleetHealthAggregator",
    "FleetMetrics",
    "FleetOrchestrator",
    "FleetWorkerConfig",
    "GrantGatedInplaceManager",
    "HashRing",
    "ShardScopedSnapshotSource",
    "ShardWorker",
    "TickStats",
    "WatchWake",
    "shard_id",
    "stable_hash",
]
