"""k8s_operator_libs_tpu — a TPU-first Kubernetes operator library.

A ground-up re-design of the capabilities of NVIDIA's ``k8s-operator-libs``
(reference: /root/reference, pure Go): a cluster-wide rolling-upgrade state
machine for node-resident driver/runtime DaemonSets, plus a device-agnostic
CRD apply/delete utility — extended with a first-class **TPU device class**:

* GKE TPU node-pool detection and ICI slice topology modelling,
* slice-aligned upgrade grouping (unavailability budgets measured in ICI
  slices, not bare nodes),
* a libtpu DaemonSet manager,
* an ICI link-health validation gate that runs real JAX collectives across
  the slice as the post-upgrade health check.

Layout:

* ``api``      — upgrade policy types (reference: api/upgrade/v1alpha1).
* ``kube``     — minimal Kubernetes object model, client interface, in-memory
  apiserver for tests, drain helper, REST client for real clusters.
* ``upgrade``  — the rolling-upgrade state machine (reference: pkg/upgrade).
* ``crdutil``  — CRD apply/delete utility (reference: pkg/crdutil).
* ``tpu``      — the TPU device class (new; no reference analog).
* ``parallel`` — TPU topology and jax.sharding Mesh construction.
* ``ops``      — probe ops: ICI collectives, MXU matmul (Pallas).
* ``models``   — burn-in workloads used by the health gate.
* ``utils``    — concurrency primitives, int-or-percent, logging.
"""

__version__ = "0.1.0"
