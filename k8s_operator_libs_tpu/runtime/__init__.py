"""Supervised daemon runtime (docs/daemon-lifecycle.md).

The deployable-process layer ROADMAP item 1a asks for: every background
component behind one :class:`Component` protocol, owned by a
:class:`Supervisor` that starts producers-first, stops consumers-first
(the LIF804 stop-order DAG), handles SIGTERM/SIGINT by only setting an
event (LIF805), drains within per-component budgets, and releases held
Leases eagerly on clean stop. The LIF8xx analyzer
(tools/analyze/lifecycle_discipline.py) statically verifies the same
contracts this package upholds by construction.
"""

from .component import (
    Component,
    FuncComponent,
    ThreadComponent,
    lifecycle_resource,
    registered_resources,
)
from .daemon import OrchestratorDaemon
from .supervisor import StopReport, Supervisor, SupervisorError

__all__ = [
    "Component",
    "FuncComponent",
    "OrchestratorDaemon",
    "StopReport",
    "Supervisor",
    "SupervisorError",
    "ThreadComponent",
    "lifecycle_resource",
    "registered_resources",
]
