"""The supervision tree: ordered start, reverse-ordered bounded drain.

One :class:`Supervisor` owns every background component of a daemon
process behind the :class:`Component` protocol (runtime/component.py).
Components register with ``add(component, depends_on=...)`` naming
their producers; start order is a deterministic topological sort of
that DAG (producers first), stop order is its exact reverse — a
consumer never outlives what feeds it (the LIF804 stop-order rule,
docs/daemon-lifecycle.md).

Signals are events, not control flow: ``install_signal_handlers``
registers a handler that ONLY sets a ``threading.Event`` — no locks,
no I/O, no event-loop touches — which is the LIF805 contract by
construction. The main loop observes ``stop_requested``/``wait`` and
runs the drain from ordinary code.

The drain is bounded twice over: one overall deadline for the whole
tree and a per-component budget within it. Each ``stop`` runs on a
daemon helper thread joined with a timeout, so one wedged component
costs its budget and nothing more — the report (:class:`StopReport`)
records who overran instead of letting them stall the process.
"""

from __future__ import annotations

import signal
import threading
import time
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

from ..utils.log import get_logger
from .component import Component, lifecycle_resource

log = get_logger("runtime.supervisor")

__all__ = ["Supervisor", "SupervisorError", "StopReport"]


class SupervisorError(RuntimeError):
    """Bad supervision wiring: duplicate name, unknown or cyclic deps."""


@dataclass
class StopReport:
    """How one component's drain went — the shutdown audit record."""

    name: str
    seconds: float
    ok: bool = True
    timed_out: bool = False
    error: str = ""


@dataclass
class _Entry:
    component: Component
    depends_on: tuple[str, ...] = ()
    started: bool = False


@lifecycle_resource(acquire="start", release="stop")
class Supervisor:
    """Own, order, and drain a daemon's background components."""

    def __init__(
        self,
        drain_timeout_s: float = 30.0,
        component_timeout_s: float = 10.0,
        mono=time.monotonic,
    ) -> None:
        self._drain_timeout_s = drain_timeout_s
        self._component_timeout_s = component_timeout_s
        self._mono = mono
        self._entries: dict[str, _Entry] = {}
        self._add_order: list[str] = []
        self._started = False
        self._stop_event = threading.Event()
        self._prev_handlers: dict[int, object] = {}
        #: Per-component drain records from the most recent stop().
        self.stop_reports: list[StopReport] = []

    # -- wiring -------------------------------------------------------------
    def add(
        self, component: Component, depends_on: Iterable[str] = ()
    ) -> Component:
        """Register ``component``; ``depends_on`` names its producers
        (components it consumes), which start before it and stop after
        it. Forward references are fine — the DAG is validated when
        :meth:`start` sorts it."""
        name = component.name
        if name in self._entries:
            raise SupervisorError(f"duplicate component name {name!r}")
        self._entries[name] = _Entry(component, tuple(depends_on))
        self._add_order.append(name)
        return component

    def adopt(
        self, component: Component, depends_on: Iterable[str] = ()
    ) -> Component:
        """Register a component that is ALREADY running (the example-CLI
        shape: acquisition interleaves with sync waits, so the setup
        code starts components itself and hands the supervisor
        ownership of the drain). The component joins the stop order
        immediately — :meth:`stop` drains it in reverse dependency
        order even if :meth:`start` is never called, so a signal
        landing mid-setup still drains everything adopted so far."""
        self.add(component, depends_on)
        self._entries[component.name].started = True
        return component

    def component(self, name: str) -> Component:
        return self._entries[name].component

    def names(self) -> tuple[str, ...]:
        return tuple(self._add_order)

    def _toposort(self) -> list[str]:
        """Deterministic Kahn's sort: producers first, ties broken by
        registration order."""
        for name in self._add_order:
            for dep in self._entries[name].depends_on:
                if dep not in self._entries:
                    raise SupervisorError(
                        f"component {name!r} depends on unknown {dep!r}"
                    )
        indeg = {
            name: len(set(self._entries[name].depends_on))
            for name in self._add_order
        }
        consumers: dict[str, list[str]] = {n: [] for n in self._add_order}
        for name in self._add_order:
            for dep in set(self._entries[name].depends_on):
                consumers[dep].append(name)
        ready = [n for n in self._add_order if indeg[n] == 0]
        order: list[str] = []
        while ready:
            name = ready.pop(0)
            order.append(name)
            for consumer in consumers[name]:
                indeg[consumer] -= 1
                if indeg[consumer] == 0:
                    ready.append(consumer)
        if len(order) != len(self._add_order):
            cyclic = sorted(n for n in self._add_order if indeg[n] > 0)
            raise SupervisorError(f"dependency cycle through {cyclic}")
        return order

    def _drain_order(self) -> list[str]:
        """Reverse-dependency drain order over the STARTED entries:
        consumers before producers. Tolerant where :meth:`_toposort` is
        strict — unknown deps are ignored and a cycle degrades to
        registration order — because stop() must drain everything it
        owns no matter how the wiring ended up."""
        indeg: dict[str, int] = {}
        consumers: dict[str, list[str]] = {n: [] for n in self._add_order}
        for name in self._add_order:
            deps = {
                d for d in self._entries[name].depends_on
                if d in self._entries
            }
            indeg[name] = len(deps)
            for dep in deps:
                consumers[dep].append(name)
        ready = [n for n in self._add_order if indeg[n] == 0]
        order: list[str] = []
        while ready:
            name = ready.pop(0)
            order.append(name)
            for consumer in consumers[name]:
                indeg[consumer] -= 1
                if indeg[consumer] == 0:
                    ready.append(consumer)
        placed = set(order)
        order.extend(n for n in self._add_order if n not in placed)
        return [n for n in reversed(order) if self._entries[n].started]

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Supervisor":
        """Start every not-yet-running component, producers first
        (adopted components are skipped — they are already running). A
        failed start drains whatever is running (in reverse) and
        re-raises."""
        if self._started:
            raise RuntimeError("supervisor already started")
        self._stop_event.clear()
        order = self._toposort()
        self._started = True
        for name in order:
            entry = self._entries[name]
            if entry.started:
                continue
            try:
                entry.component.start()
            except BaseException:
                log.error("supervisor: start of %r failed; draining", name)
                self.stop()
                raise
            entry.started = True
        return self

    def stop(self, timeout: Optional[float] = None) -> list[StopReport]:
        """Drain every running component, consumers before producers,
        under one overall deadline with per-component budgets. Never
        raises: failures and overruns are recorded in the returned
        :class:`StopReport` list (also kept on ``self.stop_reports``)."""
        overall = self._drain_timeout_s if timeout is None else timeout
        deadline = self._mono() + overall
        reports: list[StopReport] = []
        for name in self._drain_order():
            entry = self._entries[name]
            remaining = max(0.0, deadline - self._mono())
            budget = min(self._component_timeout_s, remaining)
            reports.append(self._stop_one(entry, budget))
            entry.started = False
        self._started = False
        self._stop_event.set()
        self.stop_reports = reports
        return reports

    def _stop_one(self, entry: _Entry, budget: float) -> StopReport:
        """Run one component's stop on a daemon helper joined with the
        budget — a wedged release costs its budget, not the drain."""
        component = entry.component
        failure: list[BaseException] = []

        def _invoke() -> None:
            try:
                component.stop(budget)
            except BaseException as e:  # noqa: BLE001 - recorded, drain goes on
                failure.append(e)

        began = self._mono()
        helper = threading.Thread(
            target=_invoke, name=f"stop-{component.name}", daemon=True
        )
        helper.start()
        helper.join(timeout=budget)
        seconds = self._mono() - began
        if helper.is_alive():
            log.warning(
                "supervisor: component %r overran its %.1fs stop budget",
                component.name, budget,
            )
            return StopReport(component.name, seconds, ok=False,
                              timed_out=True)
        if failure:
            log.warning(
                "supervisor: component %r stop raised: %s",
                component.name, failure[0],
            )
            return StopReport(component.name, seconds, ok=False,
                              error=str(failure[0]))
        return StopReport(component.name, seconds)

    def __enter__(self) -> "Supervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- health -------------------------------------------------------------
    def healthy(self) -> bool:
        """True when at least one component is running and every
        running component reports healthy — the daemon's single
        liveness answer."""
        running = [e for e in self._entries.values() if e.started]
        if not running:
            return False
        return all(e.component.healthy() for e in running)

    # -- signals (LIF805-clean by construction) ------------------------------
    def _on_signal(self, signum, frame) -> None:
        # ONLY set the event: no locks, no I/O, no loop touches — the
        # main loop observes stop_requested and runs the actual drain.
        self._stop_event.set()

    def install_signal_handlers(
        self, signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT)
    ) -> None:
        """Route ``signals`` to the stop event. Main thread only (a
        CPython restriction); previous handlers are kept for
        :meth:`restore_signal_handlers`."""
        for signum in signals:
            self._prev_handlers[signum] = signal.signal(
                signum, self._on_signal
            )

    def restore_signal_handlers(self) -> None:
        while self._prev_handlers:
            signum, prev = self._prev_handlers.popitem()
            signal.signal(signum, prev)

    def request_stop(self) -> None:
        self._stop_event.set()

    @property
    def stop_requested(self) -> bool:
        return self._stop_event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until stop is requested (or ``timeout``); True when
        the stop event fired — the daemon main loop's sleep."""
        return self._stop_event.wait(timeout)
