"""The fleet orchestrator as a supervised daemon (ROADMAP item 1a).

:class:`OrchestratorDaemon` packages a :class:`FleetOrchestrator`
behind the Component protocol: its own ``fleet-orchestrator`` leader
election (exactly one grant-issuer per fleet, N standbys), watch-driven
tick wakeups (fleet/wakeup.py), and one non-daemon tick-loop thread.
Deploy shape: N worker processes (``examples/upgrade_controller.py
--shards N --shard-index i``) plus any number of orchestrator replicas
(``--orchestrate``) against one apiserver — replicas campaign for the
lease and only the holder ticks, so an orchestrator crash fails over
like a worker crash: the successor resumes from the FleetRollout
ledger, nothing else.

Stop order inside :meth:`stop` is the reverse dependency DAG (LIF804):
the tick loop (consumer) first, then the wakeup streams that feed it,
then the lease — released EAGERLY so a successor acquires immediately
instead of waiting out the TTL (docs/daemon-lifecycle.md).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

from ..fleet.orchestrator import FleetHealthAggregator, FleetOrchestrator
from ..fleet.wakeup import WATCH_WINDOW_SECONDS, WatchWake
from ..kube.client import Client
from ..kube.leader import LeaderElectionConfig, LeaderElector
from ..utils.log import get_logger
from .component import lifecycle_resource

log = get_logger("runtime.daemon")

__all__ = ["OrchestratorDaemon"]


@lifecycle_resource(acquire="start", release="stop")
class OrchestratorDaemon:
    """Supervised, leader-elected FleetOrchestrator tick loop."""

    def __init__(
        self,
        client: Client,
        rollout_name: str,
        namespace: str = "default",
        identity: str = "",
        interval_s: float = 2.0,
        aggregator: Optional[FleetHealthAggregator] = None,
        policy: Sequence[str] = (),
        lease_name: str = "fleet-orchestrator",
        lease_duration_s: float = 15.0,
        renew_deadline_s: float = 10.0,
        retry_period_s: float = 2.0,
        use_wakeups: bool = True,
        wake_window_s: int = WATCH_WINDOW_SECONDS,
        join_timeout_s: float = 10.0,
    ) -> None:
        self.name = "fleet-orchestrator"
        self._client = client
        self._namespace = namespace
        self._interval_s = interval_s
        self._use_wakeups = use_wakeups
        self._wake_window_s = wake_window_s
        self._join_timeout_s = join_timeout_s
        self.orchestrator = FleetOrchestrator(
            client, rollout_name, aggregator=aggregator, policy=policy
        )
        self.elector = LeaderElector(
            client,
            LeaderElectionConfig(
                name=lease_name,
                namespace=namespace,
                identity=identity or f"orchestrator-{os.getpid()}",
                lease_duration_s=lease_duration_s,
                renew_deadline_s=renew_deadline_s,
                retry_period_s=retry_period_s,
            ),
        )
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wake: Optional[WatchWake] = None
        #: Ticks issued while holding the lease — liveness introspection.
        self.led_ticks = 0

    # -- Component ----------------------------------------------------------
    def start(self) -> "OrchestratorDaemon":
        if self._thread is not None:
            raise RuntimeError("orchestrator daemon already started")
        self.elector.start()
        if self._use_wakeups:
            self._wake = WatchWake(
                self._client,
                ("FleetRollout",),
                namespace=self._namespace,
                window_seconds=self._wake_window_s,
            )
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-orchestrator", daemon=False
        )
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Reverse-DAG drain: tick loop, then wakeup streams, then the
        lease — released eagerly so a standby acquires with zero TTL
        wait."""
        budget = self._join_timeout_s if timeout is None else timeout
        self._stop_event.set()
        wake = self._wake
        if wake is not None:
            wake.poke()  # release a wait() in progress immediately
        thread = self._thread
        if thread is not None:
            thread.join(timeout=budget)
        self._thread = None
        if wake is not None:
            wake.stop()
        self._wake = None
        self.elector.stop(release=True)

    def healthy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- the loop -----------------------------------------------------------
    def is_leader(self) -> bool:
        return self.elector.is_leader()

    def _loop(self) -> None:
        while not self._stop_event.is_set():
            if self.elector.is_leader():
                traces = (
                    self._wake.consume_traces()
                    if self._wake is not None else []
                )
                self.orchestrator.tick(wake_traces=traces or None)
                self.led_ticks += 1
            if self._stop_event.is_set():
                return
            if self._wake is not None:
                # Event-driven cadence: a ledger delivery (or a stop
                # poke) releases the wait early; interval is the resync
                # safety net, exactly the worker loop's contract.
                self._wake.wait(self._interval_s)
            else:
                self._stop_event.wait(self._interval_s)
