"""The Component protocol and the lifecycle-resource registry.

Everything that owns a background footprint — a thread, a watch
stream, a listening socket, a held Lease — participates in the
supervision tree (docs/daemon-lifecycle.md) behind one three-method
surface: ``start`` acquires, ``stop`` releases within a budget,
``healthy`` answers the liveness probe. The :class:`Supervisor`
(runtime/supervisor.py) owns the ordering; components only ever manage
their OWN footprint.

:func:`lifecycle_resource` is the registration half of the LIF8xx
contract (tools/analyze/lifecycle_discipline.py): decorating a class
with literal ``acquire``/``release`` method names tells the analyzer
which call pairs bound that class's background footprint, the same
literal-registration pattern ``@register_policy`` uses for POL704.
Computed names are invisible to the analyzer and rejected by
convention — a resource the verifier cannot see is a resource nobody
proves gets released.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Protocol, runtime_checkable

from ..utils.lifecycle import lifecycle_resource, registered_resources

__all__ = [
    "Component",
    "FuncComponent",
    "ThreadComponent",
    "lifecycle_resource",
    "registered_resources",
]


@runtime_checkable
class Component(Protocol):
    """One supervised background component (docs/daemon-lifecycle.md).

    ``stop`` takes the remaining drain budget in seconds (None = use
    the component's own default); it must be idempotent and must never
    raise — a failed release is logged and reported, never allowed to
    abort the rest of the drain.
    """

    name: str

    def start(self) -> None: ...

    def stop(self, timeout: Optional[float] = None) -> None: ...

    def healthy(self) -> bool: ...


class FuncComponent:
    """Adapt plain callables to the :class:`Component` protocol.

    ``stop`` is a thunk — bind any arguments (release flags, budgets)
    at construction. The supervisor's per-component timeout is enforced
    OUTSIDE the thunk (supervisor drain helper), so a thunk that blocks
    cannot stall the rest of the drain.
    """

    def __init__(
        self,
        name: str,
        start: Optional[Callable[[], object]] = None,
        stop: Optional[Callable[[], object]] = None,
        healthy: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.name = name
        self._start = start
        self._stop_fn = stop
        self._healthy = healthy

    def start(self) -> None:
        if self._start is not None:
            self._start()

    def stop(self, timeout: Optional[float] = None) -> None:
        if self._stop_fn is not None:
            self._stop_fn()

    def healthy(self) -> bool:
        if self._healthy is None:
            return True
        return bool(self._healthy())


@lifecycle_resource(acquire="start", release="stop")
class ThreadComponent:
    """Own ONE non-daemon thread running ``run(stop_event)``.

    The canonical worker-loop shape: ``run`` must poll (or wait on) the
    event and return promptly once it is set; ``stop`` sets the event
    and joins within the budget — always with a timeout, so shutdown
    stays bounded (LIF803).
    """

    def __init__(
        self,
        name: str,
        run: Callable[[threading.Event], object],
        join_timeout_s: float = 10.0,
    ) -> None:
        self.name = name
        self._run = run
        self._join_timeout_s = join_timeout_s
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError(f"component {self.name!r} already started")
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop_event,),
            name=self.name, daemon=False,
        )
        self._thread.start()

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop_event.set()
        thread = self._thread
        if thread is not None:
            budget = self._join_timeout_s if timeout is None else timeout
            thread.join(timeout=budget)
        self._thread = None

    def healthy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()
