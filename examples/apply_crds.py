"""apply-crds: apply or delete CRDs from YAML files/directories.

CLI parity with reference: examples/apply-crds/main.go:34-61 (flags
``--crds-path`` (repeatable) and ``--operation apply|delete``), extended with
``--demo`` which runs against the in-memory cluster — the zero-dependency
end-to-end path (BASELINE config #1 analog without a kind cluster).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

# Allow running straight from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from k8s_operator_libs_tpu.crdutil import (
    CRDOperation,
    CRDProcessingError,
    process_crds,
)
from k8s_operator_libs_tpu.kube import FakeCluster


def build_client(args: argparse.Namespace):
    if args.demo:
        return FakeCluster(crd_establish_delay=0.05)
    try:
        from k8s_operator_libs_tpu.kube.rest import RestClient

        return RestClient.from_environment()
    except Exception as e:  # RestConfigError / ImportError until rest lands
        raise SystemExit(
            f"no cluster access configured ({e}); use --demo for the "
            "in-memory cluster"
        )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="apply-crds", description=__doc__)
    parser.add_argument(
        "--crds-path",
        action="append",
        required=True,
        help="file or directory with CRD YAML (repeatable, recursed)",
    )
    parser.add_argument(
        "--operation",
        choices=[op.value for op in CRDOperation],
        default=CRDOperation.APPLY.value,
    )
    parser.add_argument(
        "--no-wait",
        action="store_true",
        help="do not wait for CRDs to become established",
    )
    parser.add_argument(
        "--demo",
        action="store_true",
        help="run against an in-memory cluster (no kubeconfig needed)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")

    client = build_client(args)
    try:
        count = process_crds(
            client, args.crds_path, args.operation, wait=not args.no_wait
        )
    except CRDProcessingError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"{args.operation}: processed {count} CRD(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
