"""upgrade-controller: a reconcile-loop daemon over the upgrade library.

The reference is consumed by operators that call BuildState()/ApplyState()
from their controller's Reconcile() (SURVEY.md §1 L6; reference:
pkg/upgrade/upgrade_state.go:35-53). This example is that consumer as a
standalone daemon: every interval it snapshots the cluster, runs one
idempotent pass of the state machine, and prints the per-state node counts.

``--demo`` runs the whole thing end to end with zero dependencies: an
in-memory v5e-16 GKE pool (4 hosts), a simulated libtpu DaemonSet, a version
bump, and the ICI health gate (real JAX probes on visible devices) gating
each uncordon — the BASELINE config #5 shape, watchable from a terminal.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

# Allow running straight from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from k8s_operator_libs_tpu.api import DriverUpgradePolicySpec
from k8s_operator_libs_tpu.runtime import FuncComponent, Supervisor
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    TaskRunner,
)


#: Whole-world workqueue key for triggers that cannot be scoped to one
#: node (DaemonSet/ControllerRevision rollout deltas, the periodic
#: resync fallback, an unplaceable NodeMaintenance CR).
RESYNC_KEY = "__resync__"


def parse_selector(raw: str) -> dict[str, str]:
    labels = {}
    for part in filter(None, raw.split(",")):
        key, _, value = part.partition("=")
        labels[key.strip()] = value.strip()
    return labels


def load_policy(path: str | None) -> DriverUpgradePolicySpec:
    if path is None:
        return DriverUpgradePolicySpec(auto_upgrade=True)
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    return DriverUpgradePolicySpec.from_dict(doc)


def state_counts(state) -> str:
    parts = []
    for name, nodes in sorted(state.node_states.items()):
        parts.append(f"{name or 'unknown'}={len(nodes)}")
    return " ".join(parts) if parts else "(no managed nodes)"


def build_demo(args):
    """In-memory v5e-16 pool + simulated libtpu DaemonSet mid-upgrade."""
    from k8s_operator_libs_tpu.kube import FakeCluster, Node
    from k8s_operator_libs_tpu.kube.sim import DaemonSetSimulator
    from k8s_operator_libs_tpu.parallel.topology import (
        GKE_NODEPOOL_LABEL,
        GKE_TPU_ACCELERATOR_LABEL,
        GKE_TPU_TOPOLOGY_LABEL,
    )

    cluster = FakeCluster()
    node_names = []
    for i in range(4):
        node = Node.new(
            f"v5e-16-pool-{i}",
            labels={
                GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
                GKE_TPU_TOPOLOGY_LABEL: "4x4",
                GKE_NODEPOOL_LABEL: "v5e-16-pool",
            },
        )
        node.set_ready(True)
        cluster.create(node)
        node_names.append(node.name)
    if args.fleet_rollout:
        # Fleet demo: seed the FleetRollout ledger the orchestrator
        # grants from. The worker's default pool_of is node-name =
        # pool-key, so each host is its own "pool"; a 50% budget makes
        # the grant waves visible — two pools roll, their completions
        # free budget, the orchestrator grants the next two.
        from k8s_operator_libs_tpu.api import make_fleet_rollout
        from k8s_operator_libs_tpu.kube.objects import KubeObject

        cluster.create(
            KubeObject(
                make_fleet_rollout(args.fleet_rollout, node_names, "50%")
            )
        )
    sim = DaemonSetSimulator(
        cluster,
        name="libtpu-installer",
        namespace=args.namespace,
        match_labels=parse_selector(args.selector),
        initial_hash="libtpu-v1",
    )
    sim.settle()
    sim.set_template_hash("libtpu-v2")  # the update the controller must roll
    return cluster, sim


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="upgrade-controller", description=__doc__
    )
    parser.add_argument("--device", choices=["tpu", "nvidia"], default="tpu")
    parser.add_argument("--namespace", default="kube-system")
    parser.add_argument(
        "--selector",
        default="app=libtpu-installer",
        help="driver DaemonSet labels, k=v[,k=v...]",
    )
    parser.add_argument(
        "--policy", help="YAML file with a DriverUpgradePolicySpec", default=None
    )
    parser.add_argument("--interval", type=float, default=30.0)
    parser.add_argument(
        "--watch",
        action="store_true",
        help="reconcile on watch events (informers over Nodes, driver "
        "Pods, and NodeMaintenance CRs) instead of a fixed interval; "
        "the interval becomes the resync fallback. Events feed a "
        "client-go-style rate-limited workqueue keyed per node, and the "
        "snapshot source maintains the cluster state incrementally "
        "(O(dirty) reconciles)",
    )
    parser.add_argument(
        "--verify-every-n",
        type=int,
        default=0,
        help="with --watch: every n-th reconcile cross-checks the "
        "incremental cluster state against a full rebuild, repairing "
        "and counting divergences (0 = off)",
    )
    parser.add_argument(
        "--once", action="store_true", help="one reconcile pass, then exit"
    )
    parser.add_argument(
        "--demo",
        action="store_true",
        help="roll a simulated v5e-16 libtpu upgrade in-memory, no cluster",
    )
    parser.add_argument(
        "--slice-aware",
        action="store_true",
        help="ICI-slice-aware planning (whole slice per disruption window)",
    )
    parser.add_argument(
        "--ici-gate",
        action="store_true",
        help="gate uncordon on the JAX ICI/MXU health probes",
    )
    parser.add_argument(
        "--validation-pod",
        action="store_true",
        help="validate via framework-provisioned probe pods on each node "
        "(the production shape) instead of in-process probes",
    )
    parser.add_argument(
        "--requestor",
        action="store_true",
        help="delegate cordon/drain to a maintenance operator over "
        "NodeMaintenance CRs (simulated in --demo)",
    )
    parser.add_argument(
        "--post-maintenance",
        action="store_true",
        help="with --requestor: route Ready nodes through "
        "post-maintenance-required (XLA cache warm-up while drained) and "
        "count maintenance states in the upgrade budget",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="serve Prometheus metrics on this port (0 = disabled)",
    )
    parser.add_argument(
        "--metrics-host",
        default="0.0.0.0",
        help="metrics bind address (default 0.0.0.0: in-cluster scrape)",
    )
    parser.add_argument(
        "--shards",
        type=int,
        default=0,
        help="fleet mode (docs/fleet-control-plane.md): total shard count "
        "for the fleet; this process campaigns for per-shard Leases and "
        "reconciles only the node keys hashing to its shards. Run N "
        "processes against one apiserver (e.g. kube.apiserver) with the "
        "same --shards and distinct --shard-index to roll a fleet from N "
        "cooperating workers; a killed worker's shards fail over via "
        "lease expiry. 0 = classic single-owner mode",
    )
    parser.add_argument(
        "--shard-index",
        type=int,
        default=0,
        help="with --shards: this worker's index (its PREFERRED shard); "
        "other shards are probed at the failover cadence only",
    )
    parser.add_argument(
        "--fleet-rollout",
        default="",
        help="with --shards: FleetRollout CR name to consume pool-roll "
        "grants from (the fleet orchestrator's global disruption "
        "budget); empty = standalone sharding under this worker's own "
        "policy budget",
    )
    parser.add_argument(
        "--pool-prefix-sep",
        default="",
        metavar="SEP",
        help="with --shards: map a node NAME to its pool key by taking "
        "everything before the LAST occurrence of SEP (e.g. '-' maps "
        "s12-h3 to pool s12) — the pure-string pool partition every "
        "worker and the orchestrator must agree on. Empty (default) = "
        "node name is the pool key, the finest grain",
    )
    parser.add_argument(
        "--orchestrate",
        action="store_true",
        help="also run the fleet orchestrator in this process as a "
        "supervised daemon (docs/daemon-lifecycle.md): campaigns for "
        "the 'fleet-orchestrator' Lease and, while leading, issues "
        "pool-roll grants against --fleet-rollout's global disruption "
        "budget. Run it on any number of replicas — only the lease "
        "holder ticks, and a stopped holder releases the lease eagerly "
        "so a standby takes over with zero TTL wait. Requires "
        "--fleet-rollout",
    )
    parser.add_argument(
        "--leader-elect",
        action="store_true",
        help="campaign for a coordination.k8s.io Lease before reconciling "
        "(the controller-runtime Manager default for the reference's "
        "consumer operators); losing the lease is fatal",
    )
    parser.add_argument(
        "--leader-elect-id",
        default="",
        help="holder identity for --leader-elect "
        "(default: <hostname>_<pid>, the client-go convention)",
    )
    parser.add_argument(
        "--leader-elect-lease",
        default="",
        help="Lease name (default: upgrade-controller-<device>)",
    )
    parser.add_argument(
        "--trace-export",
        default="",
        metavar="PATH",
        help="install the rollout tracer (docs/tracing.md) for this "
        "controller's lifetime and export the span trace JSONL to PATH "
        "on exit — inspect with `python -m tools.trace_view PATH`",
    )
    parser.add_argument(
        "--watch-relay",
        default="",
        metavar="URL",
        help="route this worker's watch streams through a WatchRelay at "
        "URL (docs/wire-path.md): N workers on one host share ONE "
        "upstream watch stream per kind instead of N. The relay speaks "
        "the ordinary watch wire protocol, so a dead relay degrades "
        "this worker to direct upstream watches for a bounded window "
        "and then retries — never silence",
    )
    parser.add_argument(
        "--stats-json",
        default="",
        metavar="PATH",
        help="write pass-count/wall-time/transport stats JSON to PATH on "
        "exit — the bench harness sums passes across worker processes "
        "to measure aggregate scaling",
    )
    args = parser.parse_args(argv)
    if args.orchestrate and not args.fleet_rollout:
        parser.error("--orchestrate requires --fleet-rollout")
    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")

    # Graceful termination as data, not control flow
    # (docs/daemon-lifecycle.md): the Supervisor owns every background
    # component this process acquires and drains them in reverse
    # dependency order, each under a bounded budget. SIGTERM/SIGINT are
    # routed to a plain Event — the handler takes no locks and touches
    # no loop (the LIF805 contract) — and the reconcile loop observes
    # ``stop_requested`` and returns; the one finally below runs the
    # drain. A terminating controller pod (kubelet sends SIGTERM)
    # releases its Leases EAGERLY on the way down, so a standby takes
    # over immediately instead of waiting out the lease TTL.
    sup = Supervisor(drain_timeout_s=30.0, component_timeout_s=10.0)
    sup.install_signal_handlers()

    # One try spanning ALL resource acquisition: components are adopted
    # into the supervisor the moment they start, so a signal landing
    # mid-setup still drains everything acquired so far.
    elector = None
    queue = None
    worker = None
    tracer = None
    if args.trace_export:
        from k8s_operator_libs_tpu.utils import tracing

        tracer = tracing.Tracer()
        tracing.install_tracer(tracer)
    try:
        device = DeviceClass.tpu() if args.device == "tpu" else DeviceClass.nvidia()
        policy = load_policy(args.policy)
        selector = parse_selector(args.selector)

        sim = None
        if args.demo:
            client, sim = build_demo(args)
        else:
            try:
                from k8s_operator_libs_tpu.kube.rest import RestClient

                client = RestClient.from_environment()
            except Exception as e:  # RestConfigError when unconfigured
                raise SystemExit(
                    f"no cluster access configured ({e}); use --demo for the "
                    "in-memory pool"
                )

        relay_source = None
        if args.watch_relay and not args.demo:
            from k8s_operator_libs_tpu.kube import RelayWatchSource

            # All informers below stream through the relay (one upstream
            # watch per kind, shared across every worker process on the
            # host); writes and LISTs keep going direct. The source owns
            # a transport to the relay, so the drain closes it only
            # after the consumers that name it in depends_on stop.
            relay_source = RelayWatchSource(args.watch_relay, direct=client)
            sup.adopt(FuncComponent("relay-source", stop=relay_source.close))

        mgr = ClusterUpgradeStateManager(
            client, device, runner=TaskRunner(inline=args.demo)
        )
        validation_pod_sim = None
        if args.validation_pod:
            from k8s_operator_libs_tpu.tpu import (
                SliceProbeSpec,
                ValidationPodManager,
                ValidationPodSpec,
                make_validation_provisioner,
            )

            if args.slice_aware:
                # Production default for slice-aware TPU pools: one probe GANG
                # per multi-host slice (jax.distributed world spanning every
                # host, cross-host ICI links in the battery, one shared
                # verdict); single-host slices fall back to per-node pods.
                provisioner = make_validation_provisioner(
                    client, SliceProbeSpec(namespace=args.namespace)
                )
            else:
                spec = ValidationPodSpec(namespace=args.namespace)
                provisioner = ValidationPodManager(client, spec)
            mgr.with_validation_enabled(pod_provisioner=provisioner)
            if args.demo:
                # The demo has no kubelet; simulate one running the probe pods.
                from k8s_operator_libs_tpu.kube.sim import ValidationPodSimulator

                validation_pod_sim = ValidationPodSimulator(
                    client, namespace=args.namespace
                )
        elif args.ici_gate or (args.demo and args.device == "tpu"):
            from k8s_operator_libs_tpu.tpu import IciHealthGate, SliceScopedGate

            gate = IciHealthGate(payload_mb=1.0, matmul_size=1024, run_burnin=True)
            hook = (
                SliceScopedGate(gate).validation_hook()
                if args.slice_aware
                else gate.validation_hook()
            )
            mgr.with_validation_enabled(validation_hook=hook)
        if args.slice_aware:
            from k8s_operator_libs_tpu.tpu import enable_slice_aware_planning

            enable_slice_aware_planning(mgr)
        maintenance_sim = None
        if args.requestor:
            from k8s_operator_libs_tpu.upgrade import (
                RequestorOptions,
                enable_requestor_mode,
            )

            opts = RequestorOptions.from_env()
            opts.use_maintenance_operator = True  # the flag IS the opt-in
            # The env var wins over the argparse default; from_env honors it
            # deliberately (MAINTENANCE_OPERATOR_REQUESTOR_NAMESPACE).
            if not os.environ.get("MAINTENANCE_OPERATOR_REQUESTOR_NAMESPACE"):
                opts.namespace = args.namespace
            if args.post_maintenance:
                opts.use_post_maintenance = True
                if args.ici_gate and not args.validation_pod and not args.demo:
                    # In-process warm-up ONLY where the in-process gate shape
                    # already applies (--ici-gate: the controller owns the
                    # node's chips, e.g. single-host pools). In the
                    # --validation-pod production shape the controller is off
                    # the node — an in-process battery would warm the WRONG
                    # host's cache and stall the reconcile loop; there the
                    # probe pod's hostPath cache mount is the warm-up story.
                    from k8s_operator_libs_tpu.tpu import cache_warmup_hook

                    opts.post_maintenance_hook = cache_warmup_hook()
            enable_requestor_mode(mgr, opts)
            if args.demo:
                from k8s_operator_libs_tpu.kube.sim import (
                    MaintenanceOperatorSimulator,
                )

                maintenance_sim = MaintenanceOperatorSimulator(
                    client, namespace=args.namespace
                )

        # Fleet mode (docs/fleet-control-plane.md): wrap the configured
        # manager in a ShardWorker — per-shard Lease campaigns, a
        # shard-scoped snapshot source in place of the plain one, and
        # (with --fleet-rollout) grant-gated planning under the global
        # disruption budget. Constructed BEFORE the watch wiring so the
        # workqueue handlers ride the worker's own informers (the PR 5
        # one-informer-set-serves-both-roles shape).
        if args.shards:
            import socket

            from k8s_operator_libs_tpu.fleet import (
                FleetWorkerConfig,
                ShardWorker,
                shard_id,
            )

            identity = (
                args.leader_elect_id or f"{socket.gethostname()}_{os.getpid()}"
            )
            sep = args.pool_prefix_sep

            def pool_of(name: str, _sep: str = sep) -> str:
                return name.rsplit(_sep, 1)[0] if _sep else name

            worker = ShardWorker(
                client,
                FleetWorkerConfig(
                    identity=identity,
                    pool_of=pool_of,
                    shards=args.shards,
                    namespace=args.namespace,
                    driver_labels=selector,
                    rollout_name=args.fleet_rollout,
                    preferred_shards=[shard_id(args.shard_index % args.shards)],
                    lease_namespace=args.namespace,
                    verify_every_n=args.verify_every_n,
                    watch_hub=relay_source,
                ),
                manager=mgr,
            )

        # Watch-driven triggering: informer deltas enqueue per-node keys
        # on a client-go-style rate-limited workqueue; the loop drains a
        # batch per pass and falls back to the interval as a resync — the
        # reference's controller-runtime shape (watches + workqueue +
        # periodic requeue), with per-key exponential backoff replacing
        # the old hand-rolled whole-loop delay.
        if args.watch and not args.demo:
            from k8s_operator_libs_tpu.kube import Informer
            from k8s_operator_libs_tpu.kube.workqueue import (
                RateLimitingQueue,
                default_controller_rate_limiter,
            )
            from k8s_operator_libs_tpu.upgrade import (
                IncrementalSnapshotSource,
                condition_changed_predicate,
            )

            queue = RateLimitingQueue(default_controller_rate_limiter())
            # The queue consumes informer deltas: it drains FIRST, so
            # nothing enqueues into a half-stopped trigger path.
            queue_deps = ["shard-worker" if worker is not None
                          else "snapshot-source"]
            if args.requestor:
                queue_deps.append("nm-informer")
            sup.adopt(
                FuncComponent("workqueue", stop=queue.shutdown),
                depends_on=queue_deps,
            )

            def enqueue_node(event_type, obj, old):
                queue.add(obj.name)

            def enqueue_pod_node(event_type, obj, old):
                # Key a pod event by the node(s) it concerns — new AND
                # old placement; a pod with no node yet wakes the world
                # (RESYNC_KEY) so the pass still notices the incomplete
                # snapshot.
                names = {obj.node_name or ""}
                if old is not None:
                    names.add(old.node_name or "")
                names.discard("")
                for name in names or {RESYNC_KEY}:
                    queue.add(name)

            def enqueue_world(event_type, obj, old):
                # DaemonSet/ControllerRevision deltas re-hash every
                # node's sync check — whole-world key.
                queue.add(RESYNC_KEY)

            def nm_node_names(obj):
                # NodeMaintenance CRs carry the target node in spec.
                name = (obj.raw.get("spec") or {}).get("nodeName", "")
                return [name] if name else []

            def maintenance_enqueue(event_type, obj, old):
                # React to condition flips/deletions only, as the reference's
                # predicate-filtered watch does (upgrade_requestor.go:115-159).
                if (
                    event_type != "MODIFIED"
                    or old is None
                    or condition_changed_predicate(old.raw, obj.raw)
                ):
                    for name in nm_node_names(obj) or [RESYNC_KEY]:
                        queue.add(name)

            # One informer set serves BOTH roles (ISSUE 4/5): reconcile
            # triggering (workqueue handlers) and build_state snapshots —
            # now INCREMENTAL (ISSUE 5): the source maintains the cluster
            # state from the same deltas, so a settled pool reconciles
            # with zero reads and zero per-node CPU and a single node
            # event reclassifies exactly one node
            # (docs/reconcile-data-path.md).
            if worker is not None:
                # Fleet mode: the worker already built (and wired into
                # the manager) a shard-scoped incremental source — the
                # same informers serve the workqueue triggers.
                snapshot_source = worker.source
            else:
                snapshot_source = IncrementalSnapshotSource(
                    client,
                    args.namespace,
                    selector,
                    verify_every_n=args.verify_every_n,
                    watch_hub=relay_source,
                )
            # ControllerRevision is the rollout trigger itself: a driver
            # image bump lands as a new revision — with only Node/Pod
            # watches, nothing would wake the controller to START the
            # roll (revision-hash sync, pod_manager.go:84-118). The
            # source watches it for the revision-sync read; the same
            # informer triggers reconciles.
            snapshot_source.informer("Node").add_event_handler(enqueue_node)
            snapshot_source.informer("Pod").add_event_handler(enqueue_pod_node)
            for kind in ("DaemonSet", "ControllerRevision"):
                snapshot_source.informer(kind).add_event_handler(enqueue_world)
            nm_informer = None
            if args.requestor:
                nm_informer = Informer(client, "NodeMaintenance")
                nm_informer.add_event_handler(maintenance_enqueue)
                # The incremental state must also see the unwatched-kind
                # deltas: map each CR to its node's dirty mark (a CR the
                # mapping cannot place degrades to a full invalidation).
                snapshot_source.mark_dirty_on(nm_informer, nm_node_names)
                nm_informer.start()
                # The source consumes its dirty marks, so the source
                # stops before it; the elector (if any) outlives both.
                sup.adopt(
                    FuncComponent("nm-informer", stop=nm_informer.stop),
                    depends_on=(
                        ["leader-elector"] if args.leader_elect else []
                    ),
                )
            # start() blocks until the snapshot stores are seeded — a
            # snapshot taken before sync would be empty, not stale.
            if worker is not None:
                worker.start(sync_timeout=30)  # owns its source's stop
            else:
                snapshot_source.start(sync_timeout=30)
                mgr.snapshot_source = snapshot_source
                mgr.provider.set_write_through(snapshot_source.record_write)
                mgr.common.pod_manager.revision_source = snapshot_source
                source_deps = ["nm-informer"] if args.requestor else []
                if args.leader_elect:
                    source_deps.append("leader-elector")
                if relay_source is not None:
                    source_deps.append("relay-source")
                sup.adopt(
                    FuncComponent(
                        "snapshot-source", stop=snapshot_source.stop
                    ),
                    depends_on=source_deps,
                )
            if nm_informer is not None and not nm_informer.wait_for_sync(
                timeout=30
            ):
                logging.warning(
                    "%s informer did not sync within 30s; reconciles may "
                    "miss its triggers until it catches up", nm_informer.kind,
                )

        if worker is not None:
            if not worker.source.started:
                # Fleet mode without --watch: the scoped source still needs
                # its informers up before the first tick snapshots.
                worker.start(sync_timeout=30)
            # worker.stop() releases the per-shard Leases eagerly
            # (standbys take over with zero TTL wait) and stops the
            # scoped source + health informer.
            worker_deps = ["nm-informer"] if (
                args.watch and not args.demo and args.requestor
            ) else []
            if args.leader_elect:
                worker_deps.append("leader-elector")
            if relay_source is not None:
                # The worker's informers pull streams from the relay
                # source; close the source only after they stop.
                worker_deps.append("relay-source")
            sup.adopt(
                FuncComponent("shard-worker", stop=worker.stop),
                depends_on=worker_deps,
            )

        if args.orchestrate:
            import socket

            from k8s_operator_libs_tpu.runtime import OrchestratorDaemon

            identity = (
                args.leader_elect_id or f"{socket.gethostname()}_{os.getpid()}"
            )
            # OrchestratorDaemon is a Component outright: its own
            # 'fleet-orchestrator' leader election, watch-driven tick
            # wakeups, one non-daemon tick-loop thread — stop() drains
            # them in reverse dependency order and releases the lease
            # eagerly.
            orchestrator = OrchestratorDaemon(
                client,
                args.fleet_rollout,
                namespace=args.namespace,
                identity=identity,
                # The demo's reconcile loop runs at full tilt; grant
                # rounds must keep pace or passes burn waiting.
                interval_s=0.1 if args.demo else min(args.interval, 2.0),
                use_wakeups=not args.demo,
            )
            orchestrator.start()
            sup.adopt(orchestrator)
            print(
                f"fleet orchestrator: campaigning as {identity!r}", flush=True
            )

        metrics = None
        if args.metrics_port:
            from k8s_operator_libs_tpu.upgrade import MetricsServer, UpgradeMetrics

            metrics = UpgradeMetrics(mgr)
            metrics_server = MetricsServer(
                metrics, port=args.metrics_port, host=args.metrics_host
            ).start()
            print(f"metrics: {metrics_server.url}")
            sup.adopt(FuncComponent("metrics", stop=metrics_server.stop))

        if args.leader_elect:
            import socket

            from k8s_operator_libs_tpu.kube import (
                LeaderElectionConfig,
                LeaderElector,
            )

            identity = (
                args.leader_elect_id or f"{socket.gethostname()}_{os.getpid()}"
            )
            elector = LeaderElector(
                client,
                LeaderElectionConfig(
                    name=args.leader_elect_lease
                    or f"upgrade-controller-{args.device}",
                    namespace=args.namespace,
                    identity=identity,
                ),
            ).start()
            # No depends_on: everything that consumes leadership names
            # this component, so the elector drains LAST — the lease is
            # released eagerly only after the work it gated has stopped.
            sup.adopt(FuncComponent("leader-elector", stop=elector.stop))
            print(f"leader election: campaigning as {identity!r}", flush=True)
            while not elector.wait_for_leadership(timeout=0.5):
                if sup.stop_requested:
                    return 0
            print("leader election: leading; starting reconciles", flush=True)

        return _reconcile_loop(
            args, mgr, policy, selector, elector, queue,
            metrics, sim, maintenance_sim, validation_pod_sim,
            worker=worker, sup=sup, relay_source=relay_source,
        )
    finally:
        # Every exit path — convergence, --once, lease lost, SIGTERM
        # (even mid-setup), unhandled error — drains whatever the
        # supervisor adopted: consumers before producers (the LIF804
        # stop order), each release under a bounded budget, every
        # non-daemon thread joined with a deadline, Leases released
        # eagerly (release is a no-op when this replica never held or
        # no longer holds one). The tracer flushes after every
        # span-producing component has stopped.
        sup.stop()
        sup.restore_signal_handlers()
        if tracer is not None:
            from k8s_operator_libs_tpu.utils import tracing

            tracing.clear_tracer()
            count = tracer.export_jsonl(args.trace_export)
            print(
                f"trace: {count} spans exported to {args.trace_export}",
                file=sys.stderr,
            )


def _reconcile_loop(
    args, mgr, policy, selector, elector, queue,
    metrics, sim, maintenance_sim, validation_pod_sim,
    worker=None, sup=None, relay_source=None,
):
    # The stats file is written on EVERY exit path (convergence, --once,
    # SIGTERM, lease lost, error): the bench harness reads it to sum
    # passes across worker processes — an aggregate-throughput scaling
    # probe that works on single-core machines where wall-clock cannot
    # show process scaling.
    stats: dict = {"passes": 0}
    started = time.monotonic()
    try:
        return _reconcile_passes(
            args, mgr, policy, selector, elector, queue,
            metrics, sim, maintenance_sim, validation_pod_sim,
            worker, sup, stats,
        )
    finally:
        if args.stats_json:
            payload = {
                "passes": stats["passes"],
                "wall_s": time.monotonic() - started,
            }
            transport_stats = getattr(mgr.client, "transport_stats", None)
            if callable(transport_stats):
                payload["transport"] = transport_stats()
            if relay_source is not None:
                payload["relay"] = relay_source.stats()
            with open(args.stats_json, "w") as f:
                json.dump(payload, f, indent=2, sort_keys=True)


def _reconcile_passes(
    args, mgr, policy, selector, elector, queue,
    metrics, sim, maintenance_sim, validation_pod_sim,
    worker, sup, stats,
):
    passes = 0
    # A 4-node roll converges in <40 passes; the fleet demo spends extra
    # passes between grant waves (the orchestrator ticks on its own
    # clock), so its stuck-roll ceiling is looser.
    max_demo_passes = 300 if args.fleet_rollout else 100
    # The fleet demo paces its passes slightly so grant rounds (issued
    # by the orchestrator daemon's thread) land between them; the plain
    # demo spins at full speed as before.
    demo_pause = 0.02 if args.fleet_rollout else 0.0
    consecutive_failures = 0
    #: Workqueue keys the CURRENT pass is reconciling (watch mode). A
    #: whole-world pass covers every key, so one batch drain per pass;
    #: each key gets done() after the pass, forget() on success, and
    #: add_rate_limited() on failure — per-key exponential backoff plus
    #: the shared 10 qps bucket, replacing the old hand-rolled
    #: whole-loop delay.
    keys: list = []
    while True:
        if sup is not None and sup.stop_requested:
            # SIGTERM/SIGINT landed (or request_stop() was called): the
            # handler only set the event — THIS is where the daemon
            # acts on it, from ordinary code. The caller's finally runs
            # the supervised drain.
            print("shutdown requested; draining", file=sys.stderr)
            return 0
        if elector is not None and not elector.is_leader():
            # controller-runtime semantics: a deposed leader must never
            # keep reconciling — exit and let the restart policy
            # re-campaign from scratch.
            print("leader election: lease lost; exiting", file=sys.stderr)
            return 3
        passes += 1
        stats["passes"] = passes
        if sim is not None and passes > max_demo_passes:
            print(
                f"demo: did not converge within {max_demo_passes} passes",
                file=sys.stderr,
            )
            return 1
        if sim is not None:
            sim.step()
        if maintenance_sim is not None:
            maintenance_sim.step()
        if validation_pod_sim is not None:
            validation_pod_sim.step()
        try:
            if worker is not None:
                # Fleet mode: one tick = lease campaigns + a reconcile
                # over the owned shards + grant/completion I/O. state is
                # None while this worker owns no shards (standby).
                ticked = worker.tick(policy)
                state = ticked.state
                if state is None:
                    print(
                        f"pass {passes}: no shards owned "
                        f"(campaigning for {sorted(worker.shards)})"
                    )
                    sup.wait(args.interval if sim is None else demo_pause)
                    continue
            else:
                state = mgr.build_state(args.namespace, selector)
                mgr.apply_state(state, policy)
        except Exception as e:  # noqa: BLE001 - the daemon outlives passes
            if args.once:
                raise
            # Reference contract: an error aborts the PASS, never the
            # controller — the next idempotent pass resumes from labels
            # (upgrade_state.go:49-52). Transient snapshot incompleteness
            # (a driver pod mid-recreate fails the unscheduled-pods guard)
            # heals by itself in a requeue or two; a PERSISTENT error (bad
            # RBAC, wrong namespace) must not spin a tight log loop.
            consecutive_failures += 1
            if queue is not None:
                # Watch mode: re-queue this pass's keys through the rate
                # limiter — the failing key backs off exponentially (5 ms
                # doubling to 1000 s) while fresh events still trigger
                # promptly; an event-less failure (first pass, or the
                # interval fallback) re-queues the whole-world key the
                # same way. done() ONLY on keys get_batch handed out —
                # done() on a never-obtained key would double-queue it
                # if an event enqueued it concurrently.
                for key in keys:
                    queue.add_rate_limited(key)
                    queue.done(key)
                if not keys:
                    queue.add_rate_limited(RESYNC_KEY)
                requeues = queue.num_requeues(keys[0] if keys else RESYNC_KEY)
                print(
                    f"pass {passes}: reconcile failed "
                    f"(rate-limited requeue #{requeues}): {e}",
                    file=sys.stderr,
                )
                keys = queue.get_batch(timeout=args.interval)
                continue
            # Interval mode keeps the whole-loop exponential delay —
            # 0.5 s doubling to 30 s, reset on the next successful pass.
            # Cap the exponent BEFORE raising 2 to it: a persistent error
            # left overnight would otherwise overflow float conversion.
            delay = min(0.5 * 2 ** min(consecutive_failures - 1, 10), 30.0)
            print(
                f"pass {passes}: reconcile failed "
                f"(retry #{consecutive_failures} in {delay:.1f}s): {e}",
                file=sys.stderr,
            )
            # The backoff sleep doubles as the shutdown wait: a signal
            # mid-backoff wakes it immediately instead of riding out
            # the delay.
            sup.wait(0.0 if sim is not None else delay)
            continue
        consecutive_failures = 0
        if queue is not None:
            # Success retires this pass's keys: backoff state reset, and
            # a key re-added mid-pass is re-delivered by done().
            for key in keys:
                queue.forget(key)
                queue.done(key)
        if metrics is not None:
            metrics.observe(state)
        if sim is not None:
            sim.step()
        shard_note = (
            f" | shards={','.join(sorted(worker.owned_shards()))}"
            if worker is not None
            else ""
        )
        print(
            f"pass {passes}: {state_counts(state)} | "
            f"in-progress={mgr.get_upgrades_in_progress(state)} "
            f"done={mgr.get_upgrades_done(state)} "
            f"failed={mgr.get_upgrades_failed(state)}"
            f"{shard_note}"
        )
        if sim is not None:
            # Convergence check via plain label reads — NEVER an
            # out-of-band mgr.build_state: an incremental snapshot
            # source is single-consumer, and a side-channel build would
            # consume the dirty set without applying it, wedging the
            # dirty-filtered buckets (spec-less wait-for-jobs advance)
            # forever.
            objs = mgr.client.list("Node")
            all_done = bool(objs) and all(
                (o.raw.get("metadata", {}).get("labels") or {}).get(
                    mgr.keys.state_label
                ) == "upgrade-done"
                for o in objs
            )
            if all_done and sim.all_pods_ready_and_current():
                print(f"demo: rolling upgrade complete in {passes} passes")
                return 0
        if args.once:
            return 0
        if queue is not None:
            # Event-triggered: block for the first key, then drain
            # whatever accumulated while this pass ran — one whole-world
            # pass covers them all. An empty batch (timeout) is the
            # periodic resync fallback: reconcile anyway.
            keys = queue.get_batch(timeout=args.interval)
        else:
            sup.wait(args.interval if sim is None else demo_pause)


if __name__ == "__main__":
    raise SystemExit(main())
