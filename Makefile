# Build/test harness (SURVEY.md §2 component 19; reference: Makefile:62-93).
PYTHON ?= python

.PHONY: all lint test bench dryrun demo install

all: lint test

install:
	$(PYTHON) -m pip install -e . -q --no-deps --no-build-isolation

lint:
	$(PYTHON) -m compileall -q k8s_operator_libs_tpu tests examples bench.py __graft_entry__.py
	$(PYTHON) -c "import k8s_operator_libs_tpu"

test:
	$(PYTHON) -m pytest tests/ -x -q

bench:
	$(PYTHON) bench.py

dryrun:
	$(PYTHON) __graft_entry__.py

# End-to-end demo: local apiserver + apply-crds CLI over a real kubeconfig.
demo:
	@set -e; \
	$(PYTHON) -m k8s_operator_libs_tpu.kube.apiserver --port 18001 \
	    --kubeconfig /tmp/tpu-operator-demo-kubeconfig & \
	SERVER_PID=$$!; \
	sleep 1; \
	KUBECONFIG=/tmp/tpu-operator-demo-kubeconfig $(PYTHON) examples/apply_crds.py \
	    --crds-path tests/crd_fixtures/crds --operation apply; \
	KUBECONFIG=/tmp/tpu-operator-demo-kubeconfig $(PYTHON) examples/apply_crds.py \
	    --crds-path tests/crd_fixtures/crds --operation delete; \
	kill $$SERVER_PID
