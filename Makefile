# Build/test harness (SURVEY.md §2 component 19; reference: Makefile:62-93).
PYTHON ?= python
COV_MIN ?= 88

# Container image for the framework's pod payloads (validation probe pod +
# monitor DaemonSet). IMAGE must match ValidationPodSpec.image and the
# image in manifests/monitor-daemonset.yaml — tests/test_manifests.py
# enforces the consistency. (Reference analog: Makefile:114-125.)
DOCKER ?= docker
IMAGE ?= tpu-operator.dev/tpu-health-probe
TAG ?= latest

.PHONY: all lint analyze test coverage bench dryrun demo install image

# Extra flags for the domain analyzer, e.g.
#   make analyze ANALYZE_FLAGS="--json --output analyze-report.json"
ANALYZE_FLAGS ?=

all: lint test

install:
	$(PYTHON) -m pip install -e . -q --no-deps --no-build-isolation

# Local lint tiers (reference gates on ~60 golangci linters locally,
# .golangci.yaml): compile check + the stdlib linter (tools/lint.py —
# unused/undefined names, redefinitions, bare except, mutable defaults, …)
# + the domain analyzer (tools/analyze/ — lock discipline, state-machine
# exhaustiveness, literal keys, swallowed exceptions), plus ruff when the
# environment has it (CI always does). docs/static-analysis.md maps the
# tiers.
lint: analyze
	$(PYTHON) -m compileall -q k8s_operator_libs_tpu tests examples tools bench.py __graft_entry__.py
	$(PYTHON) tools/lint.py k8s_operator_libs_tpu tests examples tools bench.py __graft_entry__.py
	$(PYTHON) -c "import k8s_operator_libs_tpu"
	@if $(PYTHON) -c "import ruff" 2>/dev/null; then \
	    $(PYTHON) -m ruff check k8s_operator_libs_tpu tests examples tools; \
	else \
	    echo "lint: ruff not installed here; stdlib linter ran (CI runs ruff+mypy)"; \
	fi

# Domain-aware static analysis over the package (exit 1 on any finding
# not covered by tools/analyze_baseline.json). --stats prints the
# call-graph coverage line (files, functions, call edges, lock sites,
# coroutines/await edges) so CI logs show analysis-coverage drift over
# time. Scope includes the chaos driver and the flight-recorder CLI —
# correctness infrastructure is analyzed like shipped code (ISSUE 15).
# The second line gates bench.py on the lifecycle pass alone (LIF8xx,
# baseline disabled): every informer/worker/hub/server the bench
# sections acquire must release on all paths (docs/daemon-lifecycle.md),
# while bench's non-lifecycle debt stays out of the full-pass scope.
analyze:
	$(PYTHON) tools/analyze.py k8s_operator_libs_tpu tools/chaos_run.py tools/trace_view.py --stats $(ANALYZE_FLAGS)
	$(PYTHON) tools/analyze.py k8s_operator_libs_tpu bench.py --select lifecycle-discipline --baseline -

test:
	$(PYTHON) -m pytest tests/ -x -q

# Line coverage with a threshold (stdlib sys.monitoring — pytest-cov is
# not in the image; CI uses pytest-cov with the same threshold).
coverage:
	$(PYTHON) tools/cover.py --min $(COV_MIN) -m pytest tests/ -q

bench:
	$(PYTHON) bench.py

image:
	$(DOCKER) build -f docker/Dockerfile -t $(IMAGE):$(TAG) .

dryrun:
	$(PYTHON) __graft_entry__.py

# End-to-end demo: local apiserver + apply-crds CLI over a real kubeconfig.
demo:
	@set -e; \
	$(PYTHON) -m k8s_operator_libs_tpu.kube.apiserver --port 18001 \
	    --kubeconfig /tmp/tpu-operator-demo-kubeconfig & \
	SERVER_PID=$$!; \
	sleep 1; \
	KUBECONFIG=/tmp/tpu-operator-demo-kubeconfig $(PYTHON) examples/apply_crds.py \
	    --crds-path tests/crd_fixtures/crds --operation apply; \
	KUBECONFIG=/tmp/tpu-operator-demo-kubeconfig $(PYTHON) examples/apply_crds.py \
	    --crds-path tests/crd_fixtures/crds --operation delete; \
	kill $$SERVER_PID
