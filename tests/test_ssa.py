"""Server-side apply: field ownership, managedFields, conflicts.

The upstream contract under test (k8s.io docs "Server-Side Apply" +
structured-merge-diff semantics, re-implemented schema-less in
kube/ssa.py):

* apply creates the object when absent and records an Apply entry in
  metadata.managedFields (FieldsV1 wire shape);
* re-apply by the same manager is declarative — omitted fields are
  removed;
* two managers co-own disjoint fields; same-value fields are shared;
* a different value on another manager's field is a 409 listing the
  owner, and force=True takes the field over;
* a plain update/patch moves ownership of the fields it changed to the
  writer, so the displaced applier conflicts on its next apply (the
  kubectl-scale-then-apply story);
* objects never written with a fieldManager stay untracked (activation
  rule — legacy behavior is byte-identical).

Battery runs the object path (FakeCluster) and the HTTP wire path
(LocalApiServer + RestClient), like the other conformance families.
"""

import pytest

from builders import make_node
from k8s_operator_libs_tpu.kube import (
    ApplyConflictError,
    FakeCluster,
    LocalApiServer,
    RestClient,
    RestConfig,
)
from k8s_operator_libs_tpu.kube.client import (
    BadRequestError,
    ConflictError,
    InvalidError,
)
from k8s_operator_libs_tpu.kube.ssa import (
    extract_leaves,
    fields_v1_to_leaves,
    leaves_to_fields_v1,
)


def cm(name="cfg", **data):
    """A ConfigMap-shaped custom object (generic map payload)."""
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name, "namespace": "ns"},
        "data": dict(data),
    }


def pod_manifest(name="p", containers=()):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "ns"},
        "spec": {"containers": [dict(c) for c in containers]},
    }


def entries(obj):
    return obj.metadata.get("managedFields") or []


class TestFieldSets:
    def test_round_trip_through_fields_v1(self):
        obj = pod_manifest(
            containers=[{"name": "a", "image": "a:1"}, {"name": "b"}]
        )
        obj["metadata"]["labels"] = {"app": "x"}
        leaves = set(extract_leaves(obj))
        wire = leaves_to_fields_v1(leaves)
        assert fields_v1_to_leaves(wire) == leaves
        # Wire shape uses upstream's f:/k: key prefixes.
        assert "f:spec" in wire
        assert any(k.startswith("k:") for k in wire["f:spec"]["f:containers"])

    def test_identity_metadata_is_never_owned(self):
        leaves = extract_leaves(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": "p",
                    "namespace": "ns",
                    "uid": "u",
                    "resourceVersion": "3",
                    "labels": {"a": "1"},
                },
            }
        )
        rendered = {str(p) for p in leaves}
        assert any("labels" in p for p in rendered)
        assert not any("'name'" in p or "uid" in p for p in rendered)


class TestApplyLifecycle:
    def test_apply_creates_and_records_ownership(self):
        cluster = FakeCluster()
        out = cluster.apply(cm(data1="x"), field_manager="alpha")
        assert out.raw["data"] == {"data1": "x"}
        ents = entries(out)
        assert len(ents) == 1
        assert ents[0]["manager"] == "alpha"
        assert ents[0]["operation"] == "Apply"
        assert ents[0]["fieldsType"] == "FieldsV1"
        assert "f:data" in ents[0]["fieldsV1"]

    def test_reapply_removes_omitted_fields(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1", b="2"), field_manager="alpha")
        out = cluster.apply(cm(a="1"), field_manager="alpha")
        assert out.raw["data"] == {"a": "1"}

    def test_co_management_of_disjoint_fields(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        out = cluster.apply(cm(b="2"), field_manager="beta")
        assert out.raw["data"] == {"a": "1", "b": "2"}
        # Beta dropping b removes it; alpha's field survives.
        out = cluster.apply(cm(), field_manager="beta")
        assert out.raw["data"] == {"a": "1"}

    def test_conflict_names_the_owner_and_force_takes_over(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        with pytest.raises(ConflictError) as exc:
            cluster.apply(cm(a="CHANGED"), field_manager="beta")
        assert 'conflict with "alpha"' in str(exc.value)
        assert ".data.a" in str(exc.value)
        out = cluster.apply(cm(a="CHANGED"), field_manager="beta", force=True)
        assert out.raw["data"]["a"] == "CHANGED"
        # Alpha lost the field: its next apply of a different value
        # now conflicts with beta.
        with pytest.raises(ConflictError) as exc:
            cluster.apply(cm(a="1"), field_manager="alpha")
        assert 'conflict with "beta"' in str(exc.value)

    def test_same_value_is_shared_ownership_not_conflict(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        out = cluster.apply(cm(a="1"), field_manager="beta")  # no raise
        assert out.raw["data"]["a"] == "1"
        # Either manager dropping the field keeps it while the other
        # still declares it.
        out = cluster.apply(cm(), field_manager="alpha")
        assert out.raw["data"] == {"a": "1"}

    def test_atomicity_on_conflict(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        rv = cluster.get("ConfigMap", "cfg", "ns").resource_version
        with pytest.raises(ConflictError):
            cluster.apply(cm(a="2", b="new"), field_manager="beta")
        after = cluster.get("ConfigMap", "cfg", "ns")
        assert after.raw["data"] == {"a": "1"}
        assert after.resource_version == rv

    def test_managed_fields_in_request_rejected(self):
        cluster = FakeCluster()
        manifest = cm(a="1")
        manifest["metadata"]["managedFields"] = [{"manager": "evil"}]
        with pytest.raises(InvalidError):
            cluster.apply(manifest, field_manager="alpha")

    def test_field_manager_required(self):
        cluster = FakeCluster()
        with pytest.raises(BadRequestError):
            cluster.apply(cm(a="1"), field_manager="")

    def test_apply_conflict_error_carries_structured_list(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        with pytest.raises(ApplyConflictError) as exc:
            cluster.apply(cm(a="2"), field_manager="beta")
        assert exc.value.conflicts == [("alpha", ".data.a")]


class TestKeyedLists:
    def test_managers_own_distinct_list_elements(self):
        cluster = FakeCluster()
        cluster.apply(
            pod_manifest(containers=[{"name": "a", "image": "a:1"}]),
            field_manager="alpha",
        )
        out = cluster.apply(
            pod_manifest(containers=[{"name": "b", "image": "b:1"}]),
            field_manager="beta",
        )
        names = [c["name"] for c in out.raw["spec"]["containers"]]
        assert names == ["a", "b"]
        # Beta dropping its element removes only b.
        out = cluster.apply(pod_manifest(containers=[]), field_manager="beta")
        names = [c["name"] for c in out.raw["spec"]["containers"]]
        assert names == ["a"]

    def test_key_only_element_declaration_is_shared_not_conflicting(self):
        # {"name": "a"} declares the element exists, not its contents:
        # later appliers naming the same element never conflict on it.
        cluster = FakeCluster()
        cluster.apply(
            pod_manifest(containers=[{"name": "a"}]), field_manager="alpha"
        )
        cluster.apply(
            pod_manifest(containers=[{"name": "a", "image": "x"}]),
            field_manager="beta",
        )
        out = cluster.apply(
            pod_manifest(containers=[{"name": "a"}]), field_manager="gamma"
        )
        assert out.raw["spec"]["containers"] == [{"name": "a", "image": "x"}]

    def test_merge_key_never_removed_alone(self):
        # Regression: ownership removal must never strip an element's
        # merge key while the element stands (removal order used to be
        # hash-seed-dependent; key-first left a keyless ghost that
        # declassified the list to atomic and let an empty re-apply wipe
        # other managers' elements).
        from k8s_operator_libs_tpu.kube.ssa import remove_leaf

        obj = {"spec": {"containers": [{"name": "b", "image": "b:1"}]}}
        key_leaf = (
            ("f", "spec"),
            ("f", "containers"),
            ("k", '{"name":"b"}'),
            ("f", "name"),
        )
        image_leaf = key_leaf[:-1] + (("f", "image"),)
        remove_leaf(obj, key_leaf)  # structural: must be a no-op
        assert obj["spec"]["containers"] == [{"name": "b", "image": "b:1"}]
        # Last real field: the element collapses, and the now-empty
        # containers list (and spec) prune away with it.
        remove_leaf(obj, image_leaf)
        assert obj == {}

    def test_element_field_conflict(self):
        cluster = FakeCluster()
        cluster.apply(
            pod_manifest(containers=[{"name": "a", "image": "a:1"}]),
            field_manager="alpha",
        )
        with pytest.raises(ConflictError) as exc:
            cluster.apply(
                pod_manifest(containers=[{"name": "a", "image": "EVIL"}]),
                field_manager="beta",
            )
        assert 'name="a"' in str(exc.value)


class TestUpdateInterplay:
    def test_update_displaces_applier_ownership(self):
        # The kubectl-scale-then-apply story: a plain write that changes
        # an applied field moves ownership to the writer; the applier's
        # next apply conflicts and force resolves it.
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        obj = cluster.get("ConfigMap", "cfg", "ns")
        obj.raw["data"]["a"] = "scaled"
        cluster.update(obj, field_manager="scaler")
        with pytest.raises(ConflictError) as exc:
            cluster.apply(cm(a="1"), field_manager="alpha")
        assert 'conflict with "scaler"' in str(exc.value)
        out = cluster.apply(cm(a="1"), field_manager="alpha", force=True)
        assert out.raw["data"]["a"] == "1"

    def test_anonymous_update_on_managed_object_uses_unknown(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        obj = cluster.get("ConfigMap", "cfg", "ns")
        obj.raw["data"]["a"] = "drifted"
        cluster.update(obj)  # no fieldManager declared
        with pytest.raises(ConflictError) as exc:
            cluster.apply(cm(a="1"), field_manager="alpha")
        assert 'conflict with "unknown"' in str(exc.value)

    def test_patch_displaces_applier_ownership(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        cluster.patch(
            "ConfigMap",
            "cfg",
            "ns",
            patch={"data": {"a": "patched"}},
            field_manager="patcher",
        )
        with pytest.raises(ConflictError) as exc:
            cluster.apply(cm(a="1"), field_manager="alpha")
        assert 'conflict with "patcher"' in str(exc.value)

    def test_explicit_create_then_apply_conflicts(self):
        cluster = FakeCluster()
        from k8s_operator_libs_tpu.kube import wrap

        cluster.create(wrap(cm(a="1")), field_manager="creator")
        with pytest.raises(ConflictError) as exc:
            cluster.apply(cm(a="2"), field_manager="alpha")
        assert 'conflict with "creator"' in str(exc.value)
        # Applying the SAME value shares ownership instead.
        out = cluster.apply(cm(a="1"), field_manager="alpha")
        assert out.raw["data"]["a"] == "1"


class TestActivationRule:
    def test_unmanaged_objects_stay_untracked(self):
        # Legacy writes (no fieldManager anywhere) must stay byte-identical
        # to pre-SSA behavior: no managedFields ever appears.
        cluster = FakeCluster()
        node = cluster.create(make_node(name="n1"))
        assert "managedFields" not in node.metadata
        node = cluster.get("Node", "n1")
        node.labels["x"] = "1"
        node = cluster.update(node)
        assert "managedFields" not in node.metadata
        out = cluster.patch(
            "Node", "n1", patch={"metadata": {"labels": {"y": "2"}}}
        )
        assert "managedFields" not in out.metadata

    def test_client_sent_managed_fields_is_ignored_on_update(self):
        cluster = FakeCluster()
        cluster.apply(cm(a="1"), field_manager="alpha")
        obj = cluster.get("ConfigMap", "cfg", "ns")
        obj.metadata["managedFields"] = [{"manager": "forged"}]
        out = cluster.update(obj, field_manager="writer")
        assert all(e["manager"] != "forged" for e in entries(out))


class TestWirePath:
    @pytest.fixture()
    def server(self):
        with LocalApiServer() as server:
            yield server

    def test_apply_round_trip_and_conflict_over_http(self, server):
        client = RestClient(RestConfig(server=server.url, namespace="ns"))
        try:
            out = client.apply(cm(a="1"), field_manager="alpha")
            assert out.raw["data"] == {"a": "1"}
            assert entries(out)[0]["manager"] == "alpha"
            with pytest.raises(ConflictError) as exc:
                client.apply(cm(a="2"), field_manager="beta")
            assert 'conflict with "alpha"' in str(exc.value)
            out = client.apply(cm(a="2"), field_manager="beta", force=True)
            assert out.raw["data"]["a"] == "2"
            # fieldManager on a plain wire update displaces ownership.
            obj = client.get("ConfigMap", "cfg", "ns")
            obj.raw["data"]["a"] = "manual"
            client.update(obj, field_manager="oncall")
            with pytest.raises(ConflictError) as exc:
                client.apply(cm(a="2"), field_manager="beta")
            assert 'conflict with "oncall"' in str(exc.value)
        finally:
            client.close()

    def test_field_manager_required_over_http(self, server):
        client = RestClient(RestConfig(server=server.url, namespace="ns"))
        try:
            with pytest.raises(BadRequestError):
                client.apply(cm(a="1"), field_manager="")
        finally:
            client.close()

    def test_apply_status_codes_and_url_body_mismatch(self, server):
        import http.client
        import json as jsonlib
        from urllib.parse import urlparse

        host = urlparse(server.url)

        def raw_apply(path, body, query="fieldManager=m"):
            conn = http.client.HTTPConnection(host.hostname, host.port)
            try:
                conn.request(
                    "PATCH",
                    f"{path}?{query}",
                    body=jsonlib.dumps(body),
                    headers={"Content-Type": "application/apply-patch+yaml"},
                )
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()

        base = "/api/v1/namespaces/ns/configmaps"
        # Create-through-apply answers 201, a later apply 200 (the real
        # apiserver contract; the POST path already does this).
        status, _ = raw_apply(f"{base}/cfg", cm(a="1"))
        assert status == 201
        status, _ = raw_apply(f"{base}/cfg", cm(a="1"))
        assert status == 200
        # The body may not address a different object than the URL.
        status, _ = raw_apply(f"{base}/cfg", cm(a="1", name="other"))
        assert status == 400
        # Apply to subresources is rejected, not silently misrouted.
        status, _ = raw_apply(f"{base}/cfg/status", cm(a="1"))
        assert status == 400

    def test_cached_client_forwards_field_manager(self):
        from k8s_operator_libs_tpu.kube import CachedClient

        cluster = FakeCluster()
        cached = CachedClient(cluster, sync_mode="passthrough")
        cached.apply(cm(a="1"), field_manager="alpha")
        obj = cached.get("ConfigMap", "cfg", "ns")
        obj.raw["data"]["a"] = "changed"
        cached.update(obj, field_manager="writer")
        with pytest.raises(ConflictError) as exc:
            cached.apply(cm(a="1"), field_manager="alpha")
        assert 'conflict with "writer"' in str(exc.value)
