"""Slice-wide multi-host validation gate (tpu/slice_gate.py).

VERDICT r4 missing #1: the production gate must exercise cross-host ICI
links. These tests cover the gang's shape and lifecycle on the fake
cluster, the end-to-end roll where every member node's uncordon is gated
by ONE shared slice-wide run, and — the flagship — a REAL multi-process
battery: gang pods' payloads run as separate OS processes that rendezvous
through ``jax.distributed`` over a CPU mesh, run collectives spanning both
processes, and agree on one verdict.
"""

import time

from k8s_operator_libs_tpu.api import DriverUpgradePolicySpec
from k8s_operator_libs_tpu.kube import FakeCluster, Node, Pod
from k8s_operator_libs_tpu.kube.sim import (
    DaemonSetSimulator,
    KubeletPayloadExecutor,
    ValidationPodSimulator,
)
from k8s_operator_libs_tpu.parallel.topology import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
)
from k8s_operator_libs_tpu.tpu import SliceProbeGangManager, SliceProbeSpec
from k8s_operator_libs_tpu.tpu.planner import enable_slice_aware_planning
from k8s_operator_libs_tpu.tpu.slice_gate import (
    GANG_GENERATION_LABEL,
    GANG_RANK_LABEL,
    GANG_SLICE_LABEL,
    slice_slug,
)
from k8s_operator_libs_tpu.tpu.validation_pod import VALIDATION_APP
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    TaskRunner,
    UpgradeKeys,
)
from k8s_operator_libs_tpu.utils import IntOrString
from k8s_operator_libs_tpu.utils.jaxenv import hermetic_cpu_env

DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
NS = "kube-system"
DS_LABELS = {"app": "libtpu-installer"}

POLICY = DriverUpgradePolicySpec(
    auto_upgrade=True,
    max_parallel_upgrades=0,
    max_unavailable=IntOrString("100%"),
)


def make_tpu_node(cluster, name, pool="pool-a", topology="4x4"):
    node = Node.new(name)
    node.labels[GKE_TPU_ACCELERATOR_LABEL] = "tpu-v5-lite-podslice"
    node.labels[GKE_TPU_TOPOLOGY_LABEL] = topology
    node.labels[GKE_NODEPOOL_LABEL] = pool
    node.set_ready(True)
    cluster.create(node)
    return node


def make_plain_node(cluster, name):
    node = Node.new(name)
    node.set_ready(True)
    cluster.create(node)
    return node


class TestSlug:
    def test_dns_safe_and_collision_resistant(self):
        a = slice_slug("Pool/With.Weird Chars!")
        assert a == a.lower()
        assert all(c.isalnum() or c == "-" for c in a)
        assert slice_slug("pool-a") != slice_slug("pool-b")

    def test_empty_input_still_yields_a_slug(self):
        assert slice_slug("!!!")  # non-empty: hash survives


class TestGangShape:
    def build(self, n=2):
        cluster = FakeCluster()
        nodes = [make_tpu_node(cluster, f"host-{i}") for i in range(n)]
        mgr = SliceProbeGangManager(cluster, SliceProbeSpec())
        return cluster, nodes, mgr

    def test_membership_observed_from_labels(self):
        cluster, nodes, mgr = self.build(3)
        make_tpu_node(cluster, "other", pool="pool-b")
        slice_id, members = mgr.slice_members(nodes[0])
        assert slice_id == "pool-a"
        assert members == ["host-0", "host-1", "host-2"]

    def test_gang_pod_carries_rendezvous_argv(self):
        cluster, nodes, mgr = self.build(2)
        pod = mgr.ensure(nodes[0])
        cmd = pod.spec["containers"][0]["command"]
        assert "--num-processes" in cmd and "2" in cmd
        assert "--process-id" in cmd
        coord = cmd[cmd.index("--coordinator") + 1]
        # rank 0's stable DNS name at the coordinator port
        assert coord.startswith(f"{pod.spec['hostname'].rsplit('-', 1)[0]}-0.")
        assert coord.endswith(":8476")
        # stable DNS: hostname + headless-service subdomain
        assert pod.spec["subdomain"] == mgr.service_name("pool-a")
        from k8s_operator_libs_tpu.kube.objects import Service

        svc = Service(cluster.get("Service", mgr.service_name("pool-a"), NS).raw)
        assert svc.is_headless()

    def test_one_pod_per_host_with_ranks(self):
        cluster, nodes, mgr = self.build(3)
        mgr.ensure(nodes[1])
        pods = [
            Pod(o.raw)
            for o in cluster.list(
                "Pod", namespace=NS,
                label_selector=f"{GANG_SLICE_LABEL}={slice_slug('pool-a')}",
            )
        ]
        assert len(pods) == 3
        assert {p.node_name for p in pods} == {"host-0", "host-1", "host-2"}
        ranks = sorted(int(p.labels[GANG_RANK_LABEL]) for p in pods)
        assert ranks == [0, 1, 2]
        # ranks follow sorted node order, so every pod names the same rank 0
        by_rank = {int(p.labels[GANG_RANK_LABEL]): p for p in pods}
        assert by_rank[0].node_name == "host-0"

    def test_single_host_slice_falls_back_to_per_node_pod(self):
        cluster = FakeCluster()
        node = make_tpu_node(cluster, "solo", pool="pool-solo")
        mgr = SliceProbeGangManager(cluster, SliceProbeSpec())
        pod = mgr.ensure(node)
        cmd = pod.spec["containers"][0]["command"]
        assert "--num-processes" not in cmd
        assert pod.name == f"{VALIDATION_APP}-solo"

    def test_non_tpu_node_falls_back(self):
        cluster = FakeCluster()
        node = make_plain_node(cluster, "cpu-node")
        mgr = SliceProbeGangManager(cluster, SliceProbeSpec())
        pod = mgr.ensure(node)
        assert "--num-processes" not in pod.spec["containers"][0]["command"]


class TestGangLifecycle:
    def build(self, n=2):
        cluster = FakeCluster()
        nodes = [make_tpu_node(cluster, f"host-{i}") for i in range(n)]
        mgr = SliceProbeGangManager(cluster, SliceProbeSpec())
        return cluster, nodes, mgr

    def gang_pods(self, cluster):
        return [
            Pod(o.raw)
            for o in cluster.list(
                "Pod", namespace=NS,
                label_selector=f"{GANG_SLICE_LABEL}={slice_slug('pool-a')}",
            )
        ]

    def test_ensure_is_idempotent_for_a_live_gang(self):
        cluster, nodes, mgr = self.build(2)
        first = mgr.ensure(nodes[0])
        again = mgr.ensure(nodes[1])
        pods = self.gang_pods(cluster)
        assert len(pods) == 2
        assert {first.name, again.name} == {p.name for p in pods}

    def test_finished_member_replaces_whole_gang(self):
        cluster, nodes, mgr = self.build(2)
        mgr.ensure(nodes[0])
        victim = next(
            p for p in self.gang_pods(cluster) if p.node_name == "host-1"
        )
        cluster.patch(
            "Pod", victim.name, NS, patch={"status": {"phase": "Failed"}}
        )
        mgr.ensure(nodes[0])
        pods = self.gang_pods(cluster)
        assert len(pods) == 2
        # every pod is generation 2, fresh names — no partial gang survives
        assert {p.labels[GANG_GENERATION_LABEL] for p in pods} == {"2"}
        assert victim.name not in {p.name for p in pods}

    def test_ready_pod_is_never_disturbed(self):
        cluster, nodes, mgr = self.build(2)
        mine = mgr.ensure(nodes[0])
        cluster.patch(
            "Pod", mine.name, NS,
            patch={
                "status": {
                    "phase": "Running",
                    "conditions": [{"type": "Ready", "status": "True"}],
                }
            },
        )
        # peer's pod vanished (its node already passed + cleaned up):
        peer = next(
            p for p in self.gang_pods(cluster) if p.node_name == "host-1"
        )
        cluster.delete("Pod", peer.name, NS)
        again = mgr.ensure(nodes[0])
        assert again.name == mine.name
        assert len(self.gang_pods(cluster)) == 1

    def test_cleanup_defers_while_a_peer_still_needs_the_gang(self):
        """Deleting ANY rank collapses the shared JAX world (rank 0 is
        the coordinator; every rank holds heartbeats), so cleanup must
        not touch the gang while a peer is still in the pipeline."""
        cluster, nodes, mgr = self.build(2)
        mgr.ensure(nodes[0])
        svc_name = mgr.service_name("pool-a")
        cluster.patch(
            "Node", "host-1", "",
            patch={
                "metadata": {
                    "labels": {KEYS.state_label: "validation-required"}
                }
            },
        )
        mgr.cleanup(Node(cluster.get("Node", "host-0").raw))
        assert len(self.gang_pods(cluster)) == 2  # untouched
        assert cluster.get_or_none("Service", svc_name, NS) is not None
        # host-1 consumed its verdict (moved past validation): the LAST
        # cleanup sweeps every pod and the rendezvous Service.
        cluster.patch(
            "Node", "host-1", "",
            patch={
                "metadata": {"labels": {KEYS.state_label: "upgrade-done"}}
            },
        )
        mgr.cleanup(Node(cluster.get("Node", "host-1").raw))
        assert self.gang_pods(cluster) == []
        assert cluster.get_or_none("Service", svc_name, NS) is None

    def test_late_joiner_defers_while_peer_verdicts_unconsumed(self):
        """A repaired host joining a slice whose gang just passed must NOT
        trigger whole-gang replacement — that would destroy peers' Ready
        pods before their gates consume the verdict. Its provisioning
        fails (validation clock runs) until the peers consume; once they
        leave the pipeline, the stale gang is swept and a fresh full
        generation forms (no leaked Ready pods, no joiner deadlock)."""
        import pytest

        cluster, nodes, mgr = self.build(2)
        mgr.ensure(nodes[0])
        for pod in self.gang_pods(cluster):
            cluster.patch(
                "Pod", pod.name, NS,
                patch={
                    "status": {
                        "phase": "Running",
                        "conditions": [{"type": "Ready", "status": "True"}],
                    }
                },
            )
        for name in ("host-0", "host-1"):
            cluster.patch(
                "Node", name, "",
                patch={
                    "metadata": {
                        "labels": {KEYS.state_label: "validation-required"}
                    }
                },
            )
        joiner = make_tpu_node(cluster, "host-2")
        with pytest.raises(RuntimeError, match="mid-consumption"):
            mgr.ensure(joiner)
        # peers' Ready pods untouched while their nodes still consume
        assert all(p.is_ready() for p in self.gang_pods(cluster))
        # Peers consumed their verdicts and left the pipeline: the joiner
        # now sweeps the stale gang and provisions a fresh 3-host one.
        for name in ("host-0", "host-1"):
            cluster.patch(
                "Node", name, "",
                patch={
                    "metadata": {"labels": {KEYS.state_label: "upgrade-done"}}
                },
            )
        mine = mgr.ensure(joiner)
        pods = self.gang_pods(cluster)
        assert len(pods) == 3
        assert {p.labels[GANG_GENERATION_LABEL] for p in pods} == {"2"}
        assert mine.node_name == "host-2"

    def test_terminating_pods_do_not_trigger_generation_churn(self):
        """Real-apiserver shape: a deleted pod lingers Terminating (here:
        held by a finalizer). It must be invisible to gang accounting, or
        every reconcile would replace a fresh healthy generation."""
        cluster, nodes, mgr = self.build(2)
        mgr.ensure(nodes[0])
        victim = next(
            p for p in self.gang_pods(cluster) if p.node_name == "host-1"
        )
        cluster.patch(
            "Pod", victim.name, NS,
            patch={
                "metadata": {"finalizers": ["test/hold"]},
                "status": {"phase": "Failed"},
            },
        )
        mgr.ensure(nodes[0])  # failed member -> generation 2
        live = [
            p for p in self.gang_pods(cluster) if p.deletion_timestamp is None
        ]
        assert {p.labels[GANG_GENERATION_LABEL] for p in live} == {"2"}
        # The victim is still listed (Terminating); ensure() must settle
        # on generation 2, not churn to 3.
        assert any(
            p.deletion_timestamp is not None for p in self.gang_pods(cluster)
        )
        mgr.ensure(nodes[0])
        live = [
            p for p in self.gang_pods(cluster) if p.deletion_timestamp is None
        ]
        assert {p.labels[GANG_GENERATION_LABEL] for p in live} == {"2"}

    def test_membership_change_starts_new_generation(self):
        cluster, nodes, mgr = self.build(2)
        mgr.ensure(nodes[0])
        extra = make_tpu_node(cluster, "host-2")  # repaired host joined
        mgr.ensure(nodes[0])
        pods = self.gang_pods(cluster)
        assert len(pods) == 3
        assert {p.node_name for p in pods} == {"host-0", "host-1", "host-2"}
        cmd = pods[0].spec["containers"][0]["command"]
        assert cmd[cmd.index("--num-processes") + 1] == "3"
        assert extra.name in {p.node_name for p in pods}


def build_pool(n, pool="pool-a"):
    cluster = FakeCluster()
    for i in range(n):
        make_tpu_node(cluster, f"host-{i}", pool=pool)
    sim = DaemonSetSimulator(
        cluster,
        name="libtpu-installer",
        namespace=NS,
        match_labels=DS_LABELS,
        initial_hash="v1",
    )
    sim.settle()
    return cluster, sim


def make_manager(cluster, provisioner, timeout_seconds=600):
    mgr = ClusterUpgradeStateManager(
        cluster, DEVICE, runner=TaskRunner(inline=True)
    )
    mgr.with_validation_enabled(
        pod_provisioner=provisioner, timeout_seconds=timeout_seconds
    )
    enable_slice_aware_planning(mgr)
    return mgr


class TestEndToEndSimulated:
    def test_whole_slice_gated_by_one_gang(self):
        """A 3-host slice rolls; every node's uncordon is gated by the ONE
        gang (3 pods, one generation), not three per-node batteries."""
        cluster, sim = build_pool(3)
        spec = SliceProbeSpec()
        provisioner = SliceProbeGangManager(cluster, spec)
        vps = ValidationPodSimulator(cluster, namespace=NS)
        mgr = make_manager(cluster, provisioner)

        sim.set_template_hash("v2")
        seen_gang_pods: set[str] = set()
        seen_generations: set[str] = set()
        for _ in range(60):
            sim.step()
            vps.step()
            state = mgr.build_state(NS, DS_LABELS)
            mgr.apply_state(state, POLICY)
            sim.step()
            for obj in cluster.list("Pod", namespace=NS):
                pod = Pod(obj.raw)
                if GANG_SLICE_LABEL in pod.labels:
                    seen_gang_pods.add(pod.name)
                    seen_generations.add(pod.labels[GANG_GENERATION_LABEL])
            if all(
                n.labels.get(KEYS.state_label) == "upgrade-done"
                for n in cluster.list("Node")
            ) and sim.all_pods_ready_and_current():
                break
        else:
            raise AssertionError("slice roll did not converge")
        # ONE shared run: exactly one gang generation, one pod per host.
        assert len(seen_gang_pods) == 3, seen_gang_pods
        assert seen_generations == {"1"}
        # all probe pods cleaned up, chips released
        assert (
            cluster.list(
                "Pod", namespace=NS, label_selector=f"app={VALIDATION_APP}"
            )
            == []
        )
        for node in cluster.list("Node"):
            assert not Node(node.raw).unschedulable

    def test_one_bad_host_blocks_every_member(self):
        """The agreement contract at the pod level: when one host's pod
        fails, peers never go Ready (their battery cannot pass without
        unanimity), so EVERY member of the slice stays cordoned."""
        cluster, sim = build_pool(2)
        provisioner = SliceProbeGangManager(cluster, SliceProbeSpec())

        def decide(pod: Pod) -> bool:
            # the kubelet-sim analog of the agreement collective: a gang
            # with a broken member fails on every host
            gang = [
                Pod(o.raw)
                for o in cluster.list(
                    "Pod", namespace=NS,
                    label_selector=(
                        f"{GANG_SLICE_LABEL}="
                        f"{pod.labels.get(GANG_SLICE_LABEL, '')}"
                    ),
                )
            ]
            return not any(p.node_name == "host-0" for p in gang)

        vps = ValidationPodSimulator(cluster, namespace=NS, decide=decide)
        mgr = make_manager(cluster, provisioner, timeout_seconds=0)
        sim.set_template_hash("v2")

        deadline = time.time() + 30
        saw_failed = set()
        while time.time() < deadline:
            sim.step()
            vps.step()
            state = mgr.build_state(NS, DS_LABELS)
            mgr.apply_state(state, POLICY)
            sim.step()
            labels = {
                n.name: n.labels.get(KEYS.state_label)
                for n in cluster.list("Node")
            }
            for name, value in labels.items():
                if value == "upgrade-failed":
                    saw_failed.add(name)
            if saw_failed == {"host-0", "host-1"}:
                break
            time.sleep(0.3)
        assert saw_failed == {"host-0", "host-1"}
        # nobody uncordoned: the slice-wide verdict gated every member
        for node in cluster.list("Node"):
            assert Node(node.raw).unschedulable


def _gang_argv_transform(port_base=0):
    """Map the gang's in-cluster DNS coordinator address to loopback (the
    kube-dns role) and pin each rank to the hermetic CPU mesh."""

    def transform(pod: Pod, argv: list[str]) -> list[str]:
        argv = list(argv)
        if "--coordinator" in argv:
            i = argv.index("--coordinator") + 1
            port = argv[i].rsplit(":", 1)[1]
            argv[i] = f"127.0.0.1:{port}"
        return argv

    return transform


class TestEndToEndRealProcesses:
    """The flagship: gang payloads are REAL processes forming one JAX world
    over the CPU mesh — collectives span both processes (the CPU analog of
    cross-host ICI), and the agreement psum produces the shared verdict."""

    def _spec(self, **overrides):
        kwargs = dict(
            payload_mb=0.05,
            matmul_size=64,
            min_ring_gbytes_per_s=0.0,
            min_mxu_tflops=0.0,
            use_pallas_matmul=False,
            run_flash_attention=False,
            run_seq_parallel_probes=False,
            run_burnin=False,
            compile_cache_dir="",
        )
        kwargs.update(overrides)
        return SliceProbeSpec(**kwargs)

    def _drive(self, spec, n=2, budget_s=300.0, argv_transform=None):
        cluster, sim = build_pool(n)
        provisioner = SliceProbeGangManager(cluster, spec)
        executor = KubeletPayloadExecutor(
            env=hermetic_cpu_env(2),
            extra_args=["--no-compile-cache"],
            timeout_seconds=budget_s,
            argv_transform=argv_transform or _gang_argv_transform(),
        )
        vps = ValidationPodSimulator(cluster, namespace=NS, executor=executor)
        mgr = make_manager(cluster, provisioner)
        sim.set_template_hash("v2")
        deadline = time.monotonic() + budget_s
        ready_contents: dict[str, str] = {}
        labels: dict[str, str] = {}
        with executor:
            # Deadline-driven (never a pass cap): the real battery's
            # wall-clock is load-dependent (VERDICT r4 weak #1).
            while time.monotonic() < deadline:
                sim.step()
                vps.step()
                for pod_name in executor.tracked_pods():
                    content = executor.ready_file_content(pod_name)
                    if content is not None:
                        ready_contents[pod_name] = content
                state = mgr.build_state(NS, DS_LABELS)
                mgr.apply_state(state, POLICY)
                sim.step()
                labels = {
                    n_.name: n_.labels.get(KEYS.state_label)
                    for n_ in cluster.list("Node")
                }
                if all(v == "upgrade-done" for v in labels.values()) and (
                    sim.all_pods_ready_and_current()
                ):
                    break
                time.sleep(0.5)
        return cluster, executor, labels, ready_contents

    def test_slice_rolls_behind_one_real_multiprocess_battery(self):
        cluster, executor, labels, ready_contents = self._drive(self._spec())
        assert labels == {"host-0": "upgrade-done", "host-1": "upgrade-done"}
        # Both ranks' payloads really ran and really passed...
        assert len(executor.history) == 2, executor.history
        assert all(executor.history.values())
        # ...as ONE world: each ready-file records the slice-wide verdict
        # (4 devices over 2 hosts — the cross-process fabric was probed).
        for content in ready_contents.values():
            assert "slice=4/4 over 2 hosts" in content
        assert len(ready_contents) == 2
        for node in cluster.list("Node"):
            assert not Node(node.raw).unschedulable

    def test_one_broken_rank_blocks_both_nodes(self):
        """Rank asymmetry injected at the kubelet (one host's 'hardware'
        fails its floor): the broken rank fails locally, the healthy rank
        fails on AGREEMENT — no ready-file anywhere, both nodes stay
        cordoned and eventually fail validation."""
        base = _gang_argv_transform()

        def transform(pod: Pod, argv: list[str]) -> list[str]:
            argv = base(pod, argv)
            if pod.node_name == "host-1":
                argv += ["--min-mxu-tflops", "1e9"]
            return argv

        spec = self._spec()
        cluster, sim = build_pool(2)
        provisioner = SliceProbeGangManager(cluster, spec)
        executor = KubeletPayloadExecutor(
            env=hermetic_cpu_env(2),
            extra_args=["--no-compile-cache"],
            timeout_seconds=240.0,
            argv_transform=transform,
        )
        vps = ValidationPodSimulator(cluster, namespace=NS, executor=executor)
        mgr = make_manager(cluster, provisioner)
        sim.set_template_hash("v2")
        deadline = time.monotonic() + 240.0
        with executor:
            # Phase 1: both payloads deliver verdicts; neither may pass.
            while time.monotonic() < deadline:
                sim.step()
                vps.step()
                state = mgr.build_state(NS, DS_LABELS)
                mgr.apply_state(state, POLICY)
                sim.step()
                if len(executor.history) >= 2:
                    break
                time.sleep(0.5)
            assert len(executor.history) == 2, "gang batteries never finished"
            assert not any(executor.history.values()), executor.history
            # Phase 2: shrink the validation clock; both nodes must land in
            # upgrade-failed, still cordoned — the one shared verdict.
            mgr.common.validation_manager._timeout = 0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sim.step()
                vps.step()
                state = mgr.build_state(NS, DS_LABELS)
                mgr.apply_state(state, POLICY)
                sim.step()
                labels = {
                    n.name: n.labels.get(KEYS.state_label)
                    for n in cluster.list("Node")
                }
                if all(v == "upgrade-failed" for v in labels.values()):
                    break
                time.sleep(0.5)
            else:
                raise AssertionError(
                    f"both nodes should reach upgrade-failed, got {labels}"
                )
            for node in cluster.list("Node"):
                assert Node(node.raw).unschedulable
