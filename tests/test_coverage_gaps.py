"""Behavioral coverage for the utility tiers the big suites only graze:
sync primitives under real threads, int-or-percent edge cases, event
recording, the hermetic-env helpers, and the threaded TaskRunner."""

import os
import threading
import time

import pytest

from k8s_operator_libs_tpu.kube import FakeCluster, Node
from k8s_operator_libs_tpu.kube.events import EventRecorder, FakeRecorder
from k8s_operator_libs_tpu.upgrade import TaskRunner
from k8s_operator_libs_tpu.utils import IntOrString, KeyedMutex, StringSet
from k8s_operator_libs_tpu.utils.jaxenv import (
    hermetic_cpu_env,
    plugin_shim_on_path,
    probe_default_backend,
    strip_plugin_paths,
)


class TestStringSet:
    def test_basic_ops(self):
        s = StringSet()
        s.add("a")
        s.add("b")
        assert s.has("a") and "b" in s and len(s) == 2
        assert s.snapshot() == frozenset({"a", "b"})
        s.remove("a")
        assert not s.has("a") and len(s) == 1
        s.clear()
        assert len(s) == 0

    def test_remove_absent_is_noop(self):
        s = StringSet()
        s.remove("never-added")
        assert len(s) == 0

    def test_concurrent_adds(self):
        s = StringSet()
        def worker(i):
            for j in range(100):
                s.add(f"{i}-{j}")
        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(s) == 800


class TestKeyedMutex:
    def test_same_key_serializes(self):
        m = KeyedMutex()
        order = []
        inside = threading.Event()
        release = threading.Event()

        def first():
            with m.locked("node-1"):
                inside.set()
                release.wait(timeout=5)
                order.append("first")

        def second():
            inside.wait(timeout=5)
            with m.locked("node-1"):
                order.append("second")

        t1 = threading.Thread(target=first)
        t2 = threading.Thread(target=second)
        t1.start(); t2.start()
        inside.wait(timeout=5)
        time.sleep(0.05)  # give second a chance to (wrongly) enter
        assert order == []  # second is blocked while first holds the key
        release.set()
        t1.join(timeout=5); t2.join(timeout=5)
        assert order == ["first", "second"]

    def test_distinct_keys_do_not_block(self):
        m = KeyedMutex()
        with m.locked("a"):
            acquired = []

            def other():
                with m.locked("b"):
                    acquired.append(True)

            t = threading.Thread(target=other)
            t.start()
            t.join(timeout=5)
            assert acquired == [True]


class TestIntOrString:
    def test_numeric_string_tolerated(self):
        assert IntOrString("5").value == 5
        assert not IntOrString("5").is_percent

    def test_percent_scaling_rounds(self):
        assert IntOrString("25%").scaled_value(10) == 3          # ceil
        assert IntOrString("25%").scaled_value(10, round_up=False) == 2
        assert IntOrString("100%").scaled_value(7) == 7
        assert IntOrString("0%").scaled_value(7) == 0

    def test_absolute_value_ignores_total(self):
        assert IntOrString(4).scaled_value(100) == 4

    @pytest.mark.parametrize("bad", ["abc", "-5", "-5%", "%", "5%%"])
    def test_invalid_strings_rejected(self, bad):
        with pytest.raises(ValueError):
            IntOrString(bad)

    def test_negative_and_bool_rejected(self):
        with pytest.raises(ValueError):
            IntOrString(-1)
        with pytest.raises(ValueError):
            IntOrString(True)
        with pytest.raises(ValueError):
            IntOrString(1.5)  # type: ignore[arg-type]

    def test_parse_and_json_round_trip(self):
        assert IntOrString.parse(None) is None
        v = IntOrString("30%")
        assert IntOrString.parse(v) is v
        assert IntOrString.parse(3).to_json() == 3
        assert v.to_json() == "30%"


class TestEventRecorder:
    def test_records_real_event_objects(self):
        cluster = FakeCluster()
        node = Node.new("n1")
        cluster.create(node)
        rec = EventRecorder(cluster, namespace="event-ns")
        rec.eventf(node, "Warning", "UpgradeFailed", "drain failed on %s", "n1")
        events = cluster.list("Event", namespace="event-ns")
        assert len(events) == 1
        ev = events[0].raw
        assert ev["type"] == "Warning"
        assert ev["reason"] == "UpgradeFailed"
        assert ev["message"] == "drain failed on n1"
        assert ev["involvedObject"]["name"] == "n1"
        assert ev["involvedObject"]["kind"] == "Node"

    def test_fake_recorder_bounded_and_drains(self):
        rec = FakeRecorder(capacity=3)
        node = Node.new("n1")
        for i in range(5):
            rec.eventf(node, "Normal", "R", "msg %d", i)
        drained = rec.drain()
        assert drained == ["Normal R msg 2", "Normal R msg 3", "Normal R msg 4"]
        assert rec.drain() == []


class TestJaxEnvHelpers:
    def test_strip_plugin_paths(self):
        joined = os.pathsep.join(
            ["/a/lib", "/root/.axon_site", "/b/lib"]
        )
        assert strip_plugin_paths(joined) == os.pathsep.join(
            ["/a/lib", "/b/lib"]
        )
        assert strip_plugin_paths("") == ""

    def test_plugin_shim_detection_uses_given_env(self):
        assert plugin_shim_on_path({"PYTHONPATH": "/root/.axon_site"})
        assert not plugin_shim_on_path({"PYTHONPATH": "/usr/lib"})
        assert not plugin_shim_on_path({})

    def test_hermetic_env_pins_cpu_and_device_count(self):
        base = {
            "PYTHONPATH": "/x" + os.pathsep + "/root/.axon_site",
            "JAX_PLATFORMS": "axon",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2 --other",
        }
        env = hermetic_cpu_env(8, base=base)
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env["PYTHONPATH"] == "/x"
        flags = env["XLA_FLAGS"].split()
        assert "--xla_force_host_platform_device_count=8" in flags
        assert "--other" in flags
        assert "--xla_force_host_platform_device_count=2" not in flags

    def test_hermetic_env_drops_empty_pythonpath(self):
        env = hermetic_cpu_env(4, base={"PYTHONPATH": "/root/.axon_site"})
        assert "PYTHONPATH" not in env

    def test_probe_timeout_reports_deadline(self):
        ok, detail = probe_default_backend(timeout_s=0.001)
        assert not ok
        assert "deadline" in detail

    def test_probe_failure_reports_stderr_tail(self, monkeypatch):
        import k8s_operator_libs_tpu.utils.jaxenv as jaxenv

        # A python that immediately fails stands in for a broken backend.
        monkeypatch.setattr(jaxenv.sys, "executable", "/bin/false")
        ok, detail = probe_default_backend(timeout_s=10)
        assert not ok
        assert "backend init failed" in detail


class TestThreadedTaskRunner:
    def test_runs_and_dedups_in_flight(self):
        runner = TaskRunner(max_workers=2)
        try:
            started = threading.Event()
            release = threading.Event()
            runs = []

            def slow():
                runs.append("slow")
                started.set()
                release.wait(timeout=5)

            assert runner.submit("node-1", slow)
            started.wait(timeout=5)
            assert runner.in_progress("node-1")
            # Same key while in flight: refused, not queued.
            assert not runner.submit("node-1", slow)
            # Different key proceeds.
            other_done = threading.Event()
            assert runner.submit("node-2", lambda: other_done.set())
            assert other_done.wait(timeout=5)
            release.set()
            assert runner.wait_idle(timeout=5)
            assert not runner.in_progress("node-1")
            assert runs == ["slow"]  # the refused submit never ran
        finally:
            runner.shutdown()

    def test_task_exception_never_bubbles_and_key_released(self):
        runner = TaskRunner(max_workers=1)
        try:
            def boom():
                raise RuntimeError("task error")

            assert runner.submit("node-1", boom)
            assert runner.wait_idle(timeout=5)
            assert not runner.in_progress("node-1")
            # Key is reusable after a crash.
            done = threading.Event()
            assert runner.submit("node-1", lambda: done.set())
            assert done.wait(timeout=5)
        finally:
            runner.shutdown()

    def test_wait_idle_empty_is_true(self):
        runner = TaskRunner(max_workers=1)
        try:
            assert runner.wait_idle(timeout=1)
        finally:
            runner.shutdown()
