"""Ring attention: numerics vs host oracle, gradients, probe report.

Runs on the virtual 8-device CPU mesh (conftest.py) — the same SPMD
partitioner and collectives XLA emits on a TPU slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_operator_libs_tpu.ops import (
    reference_attention,
    ring_attention,
    ring_attention_probe,
)
from k8s_operator_libs_tpu.parallel import build_mesh


def _oracle_grads(q, k, v):
    """d/d{q,k,v} of sum(attention(q,k,v)^2), causal, in numpy float64.

    Standard softmax-attention backward: with P = softmax(S), O = P V and
    L = sum(O^2): dO = 2O; dV = Pᵀ dO; dP = dO Vᵀ;
    dS = P ∘ (dP − rowsum(dP ∘ P)); dQ = scale · dS K; dK = scale · dSᵀ Q.
    """
    qn, kn, vn = (np.asarray(t, np.float64) for t in (q, k, v))
    scale = qn.shape[-1] ** -0.5
    s = qn.shape[2]
    scores = np.einsum("bhqd,bhkd->bhqk", qn * scale, kn)
    scores = np.where(np.tril(np.ones((s, s), dtype=bool)), scores, -np.inf)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, vn)
    d_out = 2.0 * out
    d_v = np.einsum("bhqk,bhqd->bhkd", p, d_out)
    d_p = np.einsum("bhqd,bhkd->bhqk", d_out, vn)
    d_s = p * (d_p - np.sum(d_p * p, axis=-1, keepdims=True))
    d_q = scale * np.einsum("bhqk,bhkd->bhqd", d_s, kn)
    d_k = scale * np.einsum("bhqk,bhqd->bhkd", d_s, qn)
    return d_q, d_k, d_v


def _qkv(shape, dtype=jnp.float32, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(kq, shape, dtype=jnp.float32).astype(dtype),
        jax.random.normal(kk, shape, dtype=jnp.float32).astype(dtype),
        jax.random.normal(kv, shape, dtype=jnp.float32).astype(dtype),
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_reference(self, sp, causal):
        mesh = build_mesh({"sp": sp})
        q, k, v = _qkv((2, 4, 16 * sp, 8))
        out = ring_attention(q, k, v, mesh, "sp", causal=causal)
        expected = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), expected, atol=1e-5, rtol=1e-4
        )

    def test_bf16_within_tolerance(self):
        mesh = build_mesh({"sp": 4})
        q, k, v = _qkv((1, 2, 64, 32), dtype=jnp.bfloat16)
        out = ring_attention(q, k, v, mesh, "sp", causal=True)
        expected = reference_attention(q, k, v, causal=True)
        err = np.max(np.abs(np.asarray(out, np.float32) - expected))
        assert err < 2e-2

    def test_composes_with_dp_and_tp(self):
        """Full 3D layout: batch over dp, heads over tp, sequence over sp."""
        mesh = build_mesh({"dp": 2, "tp": 2, "sp": 2})
        spec = P("dp", "tp", "sp", None)
        q, k, v = _qkv((2, 2, 32, 16))
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(t, sharding) for t in (q, k, v))
        out = ring_attention(q, k, v, mesh, "sp", causal=True, spec=spec)
        expected = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), expected, atol=1e-5, rtol=1e-4
        )

    def test_gradients_flow_and_match(self):
        """Grad through the ring (reverse rotation over the same links)
        matches a hand-derived float64 host oracle.

        The oracle is numpy, not jnp: an f32 jnp softmax-attention grad is
        itself noisy to ~1e-2 here, while the ring grad lands within 1e-6 of
        the f64 truth — so the test compares against the truth directly.
        """
        mesh = build_mesh({"sp": 4})
        q, k, v = _qkv((1, 2, 32, 8))

        def ring_loss(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, mesh, "sp", causal=True) ** 2
            )

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_truth = _oracle_grads(q, k, v)
        for gr, gd in zip(g_ring, g_truth):
            assert np.all(np.isfinite(np.asarray(gr)))
            np.testing.assert_allclose(
                np.asarray(gr, np.float64), gd, atol=1e-4, rtol=1e-3
            )

    def test_jits_into_single_program(self):
        mesh = build_mesh({"sp": 8})
        q, k, v = _qkv((1, 1, 64, 8))
        jitted = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, "sp")
        )
        out = jitted(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out),
            reference_attention(q, k, v),
            atol=1e-5,
            rtol=1e-4,
        )


class TestRingAttentionProbe:
    def test_probe_passes_on_healthy_mesh(self):
        mesh = build_mesh({"sp": 4})
        report = ring_attention_probe(
            mesh, "sp", seq_per_device=32, head_dim=16
        )
        assert report.ok, report.error
        assert report.max_abs_err < 2e-2
        assert report.tokens_per_s > 0

    def test_probe_defaults_to_all_devices(self):
        report = ring_attention_probe(seq_per_device=16, head_dim=8)
        assert report.ok, report.error
