"""The shipped example controller CLI, run as a real process.

`examples/upgrade_controller.py` is the L5/L6 surface an operator author
copies from — its FLAG WIRING is product behavior (the slice-aware +
requestor enable order once silently disabled slice alignment and only a
review caught it). These tests run the CLI as a subprocess in demo mode
so the wiring of every mode combination is pinned end to end.
"""

import os
import subprocess
import sys

import pytest

from k8s_operator_libs_tpu.utils.jaxenv import hermetic_cpu_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "examples", "upgrade_controller.py")


def run_demo(*flags, timeout=240):
    return subprocess.run(
        [sys.executable, CLI, "--demo", *flags],
        env=hermetic_cpu_env(4),
        capture_output=True,
        text=True,
        timeout=timeout,
    )


@pytest.mark.parametrize(
    "flags",
    [
        (),
        ("--slice-aware",),
        ("--requestor",),
        # The order-bug combination: slice-aware wired BEFORE requestor
        # in the example's source; must still compose via the
        # requestor_factory hook (tpu/planner.py).
        ("--requestor", "--slice-aware"),
        ("--requestor", "--post-maintenance"),
        ("--requestor", "--slice-aware", "--post-maintenance"),
    ],
    ids=[
        "plain", "slice-aware", "requestor", "requestor+slice-aware",
        "requestor+post-maintenance", "requestor+slice-aware+post-maint",
    ],
)
def test_demo_roll_completes(flags):
    proc = run_demo(*flags)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert "rolling upgrade complete" in proc.stdout


def test_once_mode_exits_after_one_pass():
    proc = run_demo("--once", timeout=120)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert proc.stdout.count("pass 1:") == 1
    assert "pass 2:" not in proc.stdout


def test_demo_with_leader_election():
    """--leader-elect campaigns over the same (in-memory) cluster: the
    single replica acquires the Lease, reconciles to completion, and
    releases on exit."""
    proc = run_demo("--leader-elect", "--leader-elect-id", "demo-replica")
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert "campaigning as 'demo-replica'" in proc.stdout
    assert "leading; starting reconciles" in proc.stdout
    assert "rolling upgrade complete" in proc.stdout


def test_demo_fleet_mode_single_shard():
    """--shards wires the fleet tier (docs/fleet-control-plane.md) into
    the example: the worker claims its per-shard Lease, reconciles
    through the shard-scoped incremental source, and the demo roll
    still completes. One shard = the single-worker fleet shape."""
    proc = run_demo("--shards", "1", "--shard-index", "0")
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert "rolling upgrade complete" in proc.stdout
    assert "shards=shard-00" in proc.stdout


def test_demo_fleet_orchestrate():
    """--orchestrate runs the FleetOrchestrator as a supervised daemon
    inside the same process: it campaigns for the 'fleet-orchestrator'
    Lease and issues grants from the FleetRollout ledger, without which
    the budget-gated roll cannot converge (the demo wedges if the
    orchestrator never grants — pinned by the flag-wiring review)."""
    proc = run_demo(
        "--shards", "1", "--shard-index", "0",
        "--fleet-rollout", "demo-roll", "--orchestrate",
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert "fleet orchestrator: campaigning" in proc.stdout
    assert "rolling upgrade complete" in proc.stdout


def test_orchestrate_requires_fleet_rollout():
    proc = run_demo("--orchestrate", timeout=60)
    assert proc.returncode == 2
    assert "--orchestrate requires --fleet-rollout" in proc.stderr
