"""Test harness configuration.

JAX-dependent tests run on a virtual 8-device CPU mesh: multi-chip TPU
hardware is not available in CI, so shardings/collectives are validated on
host devices (the same XLA partitioner runs either way). Environment must be
set before jax initializes its backends, hence module scope here.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
