"""Test harness configuration.

JAX-dependent tests run on a virtual 8-device CPU mesh: multi-chip TPU
hardware is not available in CI, so shardings/collectives are validated on
host devices (the same XLA partitioner runs either way). Environment must be
set before jax initializes its backends, hence module scope here.
"""

import os

# Hard-set, not setdefault: the ambient environment pins JAX_PLATFORMS to
# the single-chip TPU backend, but this suite is defined to run on the
# virtual CPU mesh (multi-device shardings need 8 devices, and test runs
# must not contend with bench/demo processes for the one real chip).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
