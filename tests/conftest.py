"""Test harness configuration.

JAX-dependent tests run on a virtual 8-device CPU mesh: multi-chip TPU
hardware is not available in CI, so shardings/collectives are validated on
host devices (the same XLA partitioner runs either way). Environment must be
set before jax initializes its backends, hence module scope here.

The deployment environment additionally injects a TPU device-plugin shim
into every Python process via ``PYTHONPATH`` (a ``sitecustomize.py`` that
registers an experimental PJRT plugin at interpreter startup). The shim
hooks backend lookup, so merely setting ``JAX_PLATFORMS=cpu`` here is not
enough: a wedged plugin tunnel hangs the whole suite, and its fd-level
side effects break pytest's default ``--capture=fd``. When the shim is
detected, the suite re-execs itself once with a hermetic CPU environment
(``utils/jaxenv.py``) so ``python -m pytest tests/`` works where the
driver runs, with no manual env tweaks. The re-exec happens in
``pytest_configure`` — not at module scope — because pytest's global
FD capture is already active while conftest loads; the capture must be
torn down first or the re-exec'd process inherits a temp file as stdout
and every byte of test output is lost.
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from k8s_operator_libs_tpu.utils.jaxenv import (  # noqa: E402
    hermetic_cpu_env,
    plugin_shim_on_path,
)

_REEXEC_MARK = "K8S_OPERATOR_LIBS_TPU_TEST_REEXEC"


def _needs_reexec() -> bool:
    return plugin_shim_on_path() and not os.environ.get(_REEXEC_MARK)


def pytest_configure(config):
    if not _needs_reexec():
        return
    # Restore the real stdout/stderr fds before replacing the process:
    # global FD capture is live from initial-conftest loading onwards.
    capman = config.pluginmanager.get_plugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    env = hermetic_cpu_env(8)
    env[_REEXEC_MARK] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


if not _needs_reexec():
    # Hard-set, not setdefault: the ambient environment pins JAX_PLATFORMS
    # to the single-chip TPU backend, but this suite is defined to run on
    # the virtual CPU mesh (multi-device shardings need 8 devices, and test
    # runs must not contend with bench/demo processes for the one real
    # chip).
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
