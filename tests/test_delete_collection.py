"""deleteCollection — selector-scoped bulk delete through the
per-object pipeline (finalizers, owner GC, dry-run), on all three
client layers.
"""

from __future__ import annotations

import pytest

from builders import make_node, make_pod
from k8s_operator_libs_tpu.kube import (
    CachedClient,
    FakeCluster,
    LocalApiServer,
    NotFoundError,
    RestClient,
    RestConfig,
)


def seed(cluster):
    cluster.create(make_node("keep", labels={"team": "gpu"}))
    cluster.create(make_node("drop-1", labels={"team": "tpu"}))
    cluster.create(make_node("drop-2", labels={"team": "tpu"}))


class TestFakeCluster:
    def test_selector_scoped(self):
        cluster = FakeCluster()
        seed(cluster)
        deleted = cluster.delete_collection(
            "Node", label_selector="team=tpu"
        )
        assert sorted(o.name for o in deleted) == ["drop-1", "drop-2"]
        assert cluster.get("Node", "keep")
        with pytest.raises(NotFoundError):
            cluster.get("Node", "drop-1")

    def test_namespace_scoped(self):
        cluster = FakeCluster()
        cluster.create(make_pod("a", namespace="one"))
        cluster.create(make_pod("b", namespace="two"))
        deleted = cluster.delete_collection("Pod", namespace="one")
        assert [o.name for o in deleted] == ["a"]
        assert cluster.get("Pod", "b", "two")

    def test_finalizers_hold_objects_in_terminating(self):
        cluster = FakeCluster()
        pod = make_pod("held", namespace="ns")
        pod.metadata["finalizers"] = ["example.com/hold"]
        cluster.create(pod)
        cluster.delete_collection("Pod", namespace="ns")
        live = cluster.get("Pod", "held", "ns")
        assert live.deletion_timestamp is not None  # Terminating, not gone

    def test_namespaced_kind_requires_namespace(self):
        """Real-apiserver parity: deletecollection is not served on the
        all-namespaces path — an empty namespace on a namespaced kind
        is refused instead of silently deleting cluster-wide."""
        from k8s_operator_libs_tpu.kube import BadRequestError

        cluster = FakeCluster()
        cluster.create(make_pod("a", namespace="one"))
        with pytest.raises(BadRequestError):
            cluster.delete_collection("Pod")
        assert cluster.get("Pod", "a", "one")

    def test_rest_client_defaults_namespace_like_other_verbs(self):
        """RestClient falls back to config.namespace for namespaced
        kinds, mirroring every other write verb."""
        server = LocalApiServer().start()
        try:
            client = RestClient(
                RestConfig(server=server.url, namespace="one")
            )
            server.cluster.create(make_pod("a", namespace="one"))
            server.cluster.create(make_pod("b", namespace="two"))
            deleted = client.delete_collection("Pod")
            assert [o.name for o in deleted] == ["a"]
            assert client.get("Pod", "b", "two")
        finally:
            server.stop()

    def test_dry_run_deletes_nothing(self):
        cluster = FakeCluster()
        seed(cluster)
        deleted = cluster.delete_collection(
            "Node", label_selector="team=tpu", dry_run=True
        )
        assert len(deleted) == 2
        assert cluster.get("Node", "drop-1")
        assert cluster.get("Node", "drop-2")

    def test_gc_cascades_per_object(self):
        cluster = FakeCluster()
        owner = cluster.create(make_node("owner", labels={"bulk": "yes"}))
        dependent = make_pod("dep", namespace="ns")
        dependent.add_owner_reference(owner)
        cluster.create(dependent)
        cluster.delete_collection("Node", label_selector="bulk=yes")
        with pytest.raises(NotFoundError):
            cluster.get("Pod", "dep", "ns")


class TestOverHttp:
    def test_wire_collection_delete(self):
        server = LocalApiServer().start()
        try:
            client = RestClient(RestConfig(server=server.url))
            seed(server.cluster)
            deleted = client.delete_collection(
                "Node", label_selector="team=tpu"
            )
            assert sorted(o.name for o in deleted) == ["drop-1", "drop-2"]
            assert client.get("Node", "keep")
            with pytest.raises(NotFoundError):
                client.get("Node", "drop-1")
            # Dry-run over the wire.
            deleted = client.delete_collection(
                "Node", label_selector="team=gpu", dry_run=True
            )
            assert [o.name for o in deleted] == ["keep"]
            assert client.get("Node", "keep")
            # CachedClient passes through.
            cached = CachedClient(client)
            assert [
                o.name
                for o in cached.delete_collection(
                    "Node", label_selector="team=gpu", dry_run=True
                )
            ] == ["keep"]
        finally:
            server.stop()
