"""deleteCollection — selector-scoped bulk delete through the
per-object pipeline (finalizers, owner GC, dry-run), on all three
client layers.
"""

from __future__ import annotations

import pytest

from builders import make_node, make_pod
from k8s_operator_libs_tpu.kube import (
    CachedClient,
    FakeCluster,
    LocalApiServer,
    NotFoundError,
    RestClient,
    RestConfig,
)


def seed(cluster):
    cluster.create(make_node("keep", labels={"team": "gpu"}))
    cluster.create(make_node("drop-1", labels={"team": "tpu"}))
    cluster.create(make_node("drop-2", labels={"team": "tpu"}))


class TestFakeCluster:
    def test_selector_scoped(self):
        cluster = FakeCluster()
        seed(cluster)
        deleted = cluster.delete_collection(
            "Node", label_selector="team=tpu"
        )
        assert sorted(o.name for o in deleted) == ["drop-1", "drop-2"]
        assert cluster.get("Node", "keep")
        with pytest.raises(NotFoundError):
            cluster.get("Node", "drop-1")

    def test_namespace_scoped(self):
        cluster = FakeCluster()
        cluster.create(make_pod("a", namespace="one"))
        cluster.create(make_pod("b", namespace="two"))
        deleted = cluster.delete_collection("Pod", namespace="one")
        assert [o.name for o in deleted] == ["a"]
        assert cluster.get("Pod", "b", "two")

    def test_finalizers_hold_objects_in_terminating(self):
        cluster = FakeCluster()
        pod = make_pod("held", namespace="ns")
        pod.metadata["finalizers"] = ["example.com/hold"]
        cluster.create(pod)
        cluster.delete_collection("Pod", namespace="ns")
        live = cluster.get("Pod", "held", "ns")
        assert live.deletion_timestamp is not None  # Terminating, not gone

    def test_namespaced_kind_requires_namespace(self):
        """Real-apiserver parity: deletecollection is not served on the
        all-namespaces path — an empty namespace on a namespaced kind
        is refused instead of silently deleting cluster-wide."""
        from k8s_operator_libs_tpu.kube import BadRequestError

        cluster = FakeCluster()
        cluster.create(make_pod("a", namespace="one"))
        with pytest.raises(BadRequestError):
            cluster.delete_collection("Pod")
        assert cluster.get("Pod", "a", "one")

    def test_rest_client_defaults_namespace_like_other_verbs(self):
        """RestClient falls back to config.namespace for namespaced
        kinds, mirroring every other write verb."""
        server = LocalApiServer().start()
        try:
            client = RestClient(
                RestConfig(server=server.url, namespace="one")
            )
            server.cluster.create(make_pod("a", namespace="one"))
            server.cluster.create(make_pod("b", namespace="two"))
            deleted = client.delete_collection("Pod")
            assert [o.name for o in deleted] == ["a"]
            assert client.get("Pod", "b", "two")
        finally:
            server.stop()

    def test_dry_run_deletes_nothing(self):
        cluster = FakeCluster()
        seed(cluster)
        deleted = cluster.delete_collection(
            "Node", label_selector="team=tpu", dry_run=True
        )
        assert len(deleted) == 2
        assert cluster.get("Node", "drop-1")
        assert cluster.get("Node", "drop-2")

    def test_gc_cascades_per_object(self):
        cluster = FakeCluster()
        owner = cluster.create(make_node("owner", labels={"bulk": "yes"}))
        dependent = make_pod("dep", namespace="ns")
        dependent.add_owner_reference(owner)
        cluster.create(dependent)
        cluster.delete_collection("Node", label_selector="bulk=yes")
        with pytest.raises(NotFoundError):
            cluster.get("Pod", "dep", "ns")


class TestRegisteredCustomKinds:
    """ADVICE.md fidelity gap: the namespace guard resolved
    namespacedness via KINDS only, so namespaced custom resources
    registered through kube.resources.register_resource bypassed it —
    delete_collection('Widget') with no namespace silently deleted the
    kind across ALL namespaces. The guard now consults the resource
    registry first."""

    def _seed_custom(self, cluster):
        from k8s_operator_libs_tpu.api import make_workload_checkpoint
        from k8s_operator_libs_tpu.kube.objects import KubeObject

        # WorkloadCheckpoint is a registered custom kind (namespaced)
        # that is NOT in objects.KINDS — exactly the bypass case.
        for ns in ("one", "two"):
            cluster.create(KubeObject(make_workload_checkpoint(
                f"pod-{ns}", ns, "node-0", step=1
            )))

    def test_registry_entry_matches_api_contract(self):
        """The CR contract lives in api/upgrade_v1alpha1.py but its
        REST-registry entry lives in kube/resources._bootstrap (so kube
        surfaces know the kind without importing api, and api stays
        kube-free). Pin the two in sync."""
        from k8s_operator_libs_tpu.api.upgrade_v1alpha1 import (
            WORKLOAD_CHECKPOINT_API_VERSION,
            WORKLOAD_CHECKPOINT_KIND,
            WORKLOAD_CHECKPOINT_PLURAL,
        )
        from k8s_operator_libs_tpu.kube.resources import resource_for_kind

        info = resource_for_kind(WORKLOAD_CHECKPOINT_KIND)
        assert info.api_version == WORKLOAD_CHECKPOINT_API_VERSION
        assert info.plural == WORKLOAD_CHECKPOINT_PLURAL
        assert info.namespaced is True

    def test_api_module_does_not_import_kube(self):
        """Importing the api dataclasses alone must not pull the kube
        package (the cost the registry placement exists to avoid)."""
        import subprocess
        import sys

        code = (
            "import sys\n"
            "import k8s_operator_libs_tpu.api\n"
            "mods = [m for m in sys.modules if m.startswith("
            "'k8s_operator_libs_tpu.kube')]\n"
            "assert not mods, mods\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True
        )
        assert proc.returncode == 0, proc.stderr

    def test_all_namespaces_delete_refused(self):
        from k8s_operator_libs_tpu.kube import BadRequestError

        cluster = FakeCluster()
        self._seed_custom(cluster)
        with pytest.raises(BadRequestError):
            cluster.delete_collection("WorkloadCheckpoint")
        # Nothing was deleted anywhere.
        assert cluster.get("WorkloadCheckpoint", "pod-one-checkpoint", "one")
        assert cluster.get("WorkloadCheckpoint", "pod-two-checkpoint", "two")

    def test_namespace_scoped_delete_works(self):
        cluster = FakeCluster()
        self._seed_custom(cluster)
        deleted = cluster.delete_collection(
            "WorkloadCheckpoint", namespace="one"
        )
        assert [o.name for o in deleted] == ["pod-one-checkpoint"]
        assert cluster.get("WorkloadCheckpoint", "pod-two-checkpoint", "two")

    def test_guard_mirrored_in_apiserver(self):
        """Over the wire the same refusal must come from the apiserver's
        deletecollection route (a raw HTTP client could otherwise hit the
        all-namespaces path the typed client never emits)."""
        import urllib.request

        server = LocalApiServer().start()
        try:
            self._seed_custom(server.cluster)
            url = (
                f"{server.url}/apis/upgrade.tpu-operator.dev/v1alpha1/"
                "workloadcheckpoints"
            )
            req = urllib.request.Request(url, method="DELETE")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req)
            assert err.value.code == 400
            assert server.cluster.get(
                "WorkloadCheckpoint", "pod-one-checkpoint", "one"
            )
            # The namespaced route still serves the bulk delete.
            ns_url = (
                f"{server.url}/apis/upgrade.tpu-operator.dev/v1alpha1/"
                "namespaces/one/workloadcheckpoints"
            )
            req = urllib.request.Request(ns_url, method="DELETE")
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
            assert server.cluster.get_or_none(
                "WorkloadCheckpoint", "pod-one-checkpoint", "one"
            ) is None
        finally:
            server.stop()


class TestOverHttp:
    def test_wire_collection_delete(self):
        server = LocalApiServer().start()
        try:
            client = RestClient(RestConfig(server=server.url))
            seed(server.cluster)
            deleted = client.delete_collection(
                "Node", label_selector="team=tpu"
            )
            assert sorted(o.name for o in deleted) == ["drop-1", "drop-2"]
            assert client.get("Node", "keep")
            with pytest.raises(NotFoundError):
                client.get("Node", "drop-1")
            # Dry-run over the wire.
            deleted = client.delete_collection(
                "Node", label_selector="team=gpu", dry_run=True
            )
            assert [o.name for o in deleted] == ["keep"]
            assert client.get("Node", "keep")
            # CachedClient passes through.
            cached = CachedClient(client)
            assert [
                o.name
                for o in cached.delete_collection(
                    "Node", label_selector="team=gpu", dry_run=True
                )
            ] == ["keep"]
        finally:
            server.stop()
