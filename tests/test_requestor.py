"""Requestor-mode tests: NodeMaintenance CR protocol, shared-requestor
coordination, dual-mode coexistence.

Coverage model: reference upgrade_state_test.go requestor specs (incl.
shared-requestor AdditionalRequestors) and upgrade_requestor.go behavior.
The external maintenance operator is simulated by setting Status.Conditions
on CRs directly, exactly as the reference suite does
(upgrade_suit_test.go:282-293).
"""

import pytest

from k8s_operator_libs_tpu.api import DrainSpec, DriverUpgradePolicySpec
from k8s_operator_libs_tpu.kube import FakeCluster, NodeMaintenance
from k8s_operator_libs_tpu.kube.sim import DaemonSetSimulator
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    RequestorNodeStateManager,
    RequestorOptions,
    TaskRunner,
    UpgradeKeys,
    condition_changed_predicate,
    enable_requestor_mode,
    requestor_id_predicate,
)
from k8s_operator_libs_tpu.utils import IntOrString
from builders import make_node

DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
NS = "driver-ns"
LABELS = {"app": "driver"}
MAINT_NS = "maintenance-ns"

POLICY = DriverUpgradePolicySpec(
    auto_upgrade=True,
    max_parallel_upgrades=0,
    max_unavailable=IntOrString("100%"),
    drain=DrainSpec(enable=True, force=True, timeout_seconds=120),
)


def make_harness(node_count=1, node_states=None, requestor_id="tpu.operator.dev"):
    cluster = FakeCluster()
    for i in range(node_count):
        labels = {}
        if node_states and node_states[i]:
            labels[KEYS.state_label] = node_states[i]
        cluster.create(make_node(f"node-{i}", labels=labels))
    sim = DaemonSetSimulator(
        cluster, name="driver", namespace=NS, match_labels=LABELS
    )
    sim.settle()
    opts = RequestorOptions(
        use_maintenance_operator=True,
        requestor_id=requestor_id,
        namespace=MAINT_NS,
    )
    mgr = ClusterUpgradeStateManager(
        cluster, DEVICE, runner=TaskRunner(inline=True)
    )
    enable_requestor_mode(mgr, opts)
    return cluster, sim, mgr, opts


def state_of(cluster, name):
    return cluster.get("Node", name).labels.get(KEYS.state_label, "")


def simulate_maintenance_ready(cluster, nm_name, namespace=MAINT_NS):
    """Play the external maintenance operator: cordon done, Ready."""
    cluster.patch(
        "NodeMaintenance",
        nm_name,
        namespace,
        patch={
            "status": {
                "conditions": [
                    {"type": "Ready", "status": "True", "reason": "Ready"}
                ]
            }
        },
    )


class TestUpgradeRequiredFlow:
    def test_creates_cr_and_moves_to_maintenance_required(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["upgrade-required"]
        )
        sim.set_template_hash("rev-2")
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        node = cluster.get("Node", "node-0")
        assert node.labels[KEYS.state_label] == "node-maintenance-required"
        assert KEYS.requestor_mode_annotation in node.annotations
        nm = cluster.get(
            "NodeMaintenance", "tpu-operator-node-0", MAINT_NS
        )
        nm = NodeMaintenance(nm.raw)
        assert nm.requestor_id == "tpu.operator.dev"
        assert nm.node_name == "node-0"
        # Policy conversion carried the drain spec.
        assert nm.spec["drainSpec"]["timeoutSeconds"] == 120
        assert nm.spec["drainSpec"]["force"] is True

    def test_skip_label_respected(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["upgrade-required"]
        )
        cluster.patch(
            "Node", "node-0",
            patch={"metadata": {"labels": {KEYS.skip_label: "true"}}},
        )
        sim.set_template_hash("rev-2")
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        assert state_of(cluster, "node-0") == "upgrade-required"
        assert (
            cluster.get_or_none("NodeMaintenance", "tpu-operator-node-0", MAINT_NS)
            is None
        )


class TestMaintenanceRequiredFlow:
    def test_ready_condition_advances_to_pod_restart(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["upgrade-required"]
        )
        sim.set_template_hash("rev-2")
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)  # creates CR
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)  # not ready yet
        assert state_of(cluster, "node-0") == "node-maintenance-required"
        simulate_maintenance_ready(cluster, "tpu-operator-node-0")
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        assert state_of(cluster, "node-0") == "pod-restart-required"

    def test_missing_cr_requeues_to_upgrade_required(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["node-maintenance-required"]
        )
        # No CR exists; node must fall back to upgrade-required.
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        assert state_of(cluster, "node-0") == "upgrade-required"


class TestUncordonFlow:
    def test_owner_deletes_cr_on_completion(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["uncordon-required"]
        )
        # Node finished via requestor mode.
        cluster.patch(
            "Node", "node-0",
            patch={"metadata": {"annotations": {
                KEYS.requestor_mode_annotation: "true"}}},
        )
        req: RequestorNodeStateManager = mgr.requestor
        nm = req.new_node_maintenance("node-0", POLICY)
        cluster.create(nm)
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        node = cluster.get("Node", "node-0")
        assert node.labels[KEYS.state_label] == "upgrade-done"
        assert KEYS.requestor_mode_annotation not in node.annotations
        assert (
            cluster.get_or_none("NodeMaintenance", nm.name, MAINT_NS) is None
        )

    def test_inplace_node_unaffected_by_requestor_uncordon(self):
        # Dual-mode coexistence: a node NOT in requestor mode at
        # uncordon-required is finished by the in-place flow even though
        # requestor mode is enabled (reference: upgrade_state.go:311-325).
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["uncordon-required"]
        )
        cluster.patch("Node", "node-0", patch={"spec": {"unschedulable": True}})
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        node = cluster.get("Node", "node-0")
        assert node.labels[KEYS.state_label] == "upgrade-done"
        assert not node.unschedulable  # in-place flow uncordoned it


class TestSharedRequestorProtocol:
    def test_second_requestor_appends_to_additional(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["upgrade-required"]
        )
        sim.set_template_hash("rev-2")
        # Another operator (e.g. NIC firmware) already owns the CR.
        other = NodeMaintenance.new("tpu-operator-node-0", namespace=MAINT_NS)
        other.requestor_id = "nic.operator.dev"
        other.node_name = "node-0"
        cluster.create(other)
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        nm = NodeMaintenance(
            cluster.get("NodeMaintenance", "tpu-operator-node-0", MAINT_NS).raw
        )
        assert nm.requestor_id == "nic.operator.dev"
        assert "tpu.operator.dev" in nm.additional_requestors

    def test_append_is_idempotent(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["upgrade-required"]
        )
        sim.set_template_hash("rev-2")
        other = NodeMaintenance.new("tpu-operator-node-0", namespace=MAINT_NS)
        other.requestor_id = "nic.operator.dev"
        other.additional_requestors = ["tpu.operator.dev"]
        cluster.create(other)
        rv_before = cluster.get(
            "NodeMaintenance", "tpu-operator-node-0", MAINT_NS
        ).resource_version
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        nm = NodeMaintenance(
            cluster.get("NodeMaintenance", "tpu-operator-node-0", MAINT_NS).raw
        )
        assert nm.additional_requestors.count("tpu.operator.dev") == 1
        assert nm.resource_version == rv_before  # no write happened

    def test_non_owner_removes_itself_on_completion(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["uncordon-required"]
        )
        cluster.patch(
            "Node", "node-0",
            patch={"metadata": {"annotations": {
                KEYS.requestor_mode_annotation: "true"}}},
        )
        shared = NodeMaintenance.new("tpu-operator-node-0", namespace=MAINT_NS)
        shared.requestor_id = "nic.operator.dev"
        shared.additional_requestors = ["tpu.operator.dev"]
        cluster.create(shared)
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        nm_obj = cluster.get_or_none(
            "NodeMaintenance", "tpu-operator-node-0", MAINT_NS
        )
        assert nm_obj is not None  # owner keeps the CR
        nm = NodeMaintenance(nm_obj.raw)
        assert "tpu.operator.dev" not in nm.additional_requestors

    def test_custom_prefix_creates_own_cr(self):
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["upgrade-required"]
        )
        opts.node_maintenance_name_prefix = "my-prefix"
        enable_requestor_mode(mgr, opts)
        sim.set_template_hash("rev-2")
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        assert (
            cluster.get_or_none("NodeMaintenance", "my-prefix-node-0", MAINT_NS)
            is not None
        )


class TestEndToEndRequestorUpgrade:
    def test_full_roll_with_simulated_maintenance_operator(self):
        cluster, sim, mgr, opts = make_harness(node_count=3)
        sim.set_template_hash("rev-2")
        for _ in range(30):
            sim.step()
            # The external maintenance operator: mark any pending CR Ready.
            for obj in cluster.list("NodeMaintenance", namespace=MAINT_NS):
                nm = NodeMaintenance(obj.raw)
                if not nm.is_ready() and nm.deletion_timestamp is None:
                    simulate_maintenance_ready(cluster, nm.name)
            mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
            sim.step()
            states = {
                n.name: n.labels.get(KEYS.state_label, "")
                for n in cluster.list("Node")
            }
            if all(s == "upgrade-done" for s in states.values()):
                break
        else:
            raise AssertionError(f"requestor roll did not converge: {states}")
        # All CRs cleaned up, no annotations left.
        assert cluster.list("NodeMaintenance", namespace=MAINT_NS) == []
        for n in cluster.list("Node"):
            assert KEYS.requestor_mode_annotation not in (
                n.metadata.get("annotations") or {}
            )
        assert sim.all_pods_ready_and_current()


class TestPredicates:
    def test_requestor_id_predicate(self):
        obj = {"spec": {"requestorID": "a", "additionalRequestors": ["b"]}}
        assert requestor_id_predicate(obj, "a")
        assert requestor_id_predicate(obj, "b")
        assert not requestor_id_predicate(obj, "c")

    def test_condition_changed(self):
        old = {"status": {"conditions": [{"type": "Ready", "status": "False"}]},
               "metadata": {}}
        new = {"status": {"conditions": [{"type": "Ready", "status": "True"}]},
               "metadata": {}}
        assert condition_changed_predicate(old, new)

    def test_condition_order_insensitive(self):
        old = {"status": {"conditions": [
            {"type": "A", "status": "True"}, {"type": "B", "status": "False"}]},
            "metadata": {}}
        new = {"status": {"conditions": [
            {"type": "B", "status": "False"}, {"type": "A", "status": "True"}]},
            "metadata": {}}
        assert not condition_changed_predicate(old, new)

    def test_deletion_detected(self):
        old = {"status": {}, "metadata": {"finalizers": ["x"]}}
        new = {"status": {}, "metadata": {"deletionTimestamp": 123.0}}
        assert condition_changed_predicate(old, new)

    def test_nil_objects_ignored(self):
        assert not condition_changed_predicate(None, {"metadata": {}})
        assert not condition_changed_predicate({"metadata": {}}, None)

    def test_from_env_defaults_requestor_id(self, monkeypatch):
        monkeypatch.setenv("MAINTENANCE_OPERATOR_ENABLED", "true")
        monkeypatch.delenv("MAINTENANCE_OPERATOR_REQUESTOR_ID", raising=False)
        opts = RequestorOptions.from_env()
        # An empty ID would make every operator look like every CR's owner.
        assert opts.requestor_id == "tpu.operator.dev"

    def test_enable_requestor_mode_rejects_without_mutating(self):
        cluster = FakeCluster()
        mgr = ClusterUpgradeStateManager(cluster, DEVICE)
        original_options = mgr.options
        with pytest.raises(ValueError):
            enable_requestor_mode(
                mgr, RequestorOptions(use_maintenance_operator=False)
            )
        assert mgr.options is original_options
        assert mgr.requestor is None

    def test_cr_cleanup_failure_leaves_node_resumable(self):
        # CR release precedes the DONE transition: if release fails the node
        # stays in uncordon-required and the next pass self-heals.
        cluster, sim, mgr, opts = make_harness(
            node_count=1, node_states=["uncordon-required"]
        )
        cluster.patch(
            "Node", "node-0",
            patch={"metadata": {"annotations": {
                KEYS.requestor_mode_annotation: "true"}}},
        )
        req: RequestorNodeStateManager = mgr.requestor
        cluster.create(req.new_node_maintenance("node-0", POLICY))
        from k8s_operator_libs_tpu.kube import ApiError

        boom = {"armed": True}

        def fail_once(verb, kind, payload):
            if boom["armed"]:
                boom["armed"] = False
                raise ApiError("transient")

        cluster.add_reactor("delete", "NodeMaintenance", fail_once)
        with pytest.raises(ApiError):
            mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        # Node unchanged -> retried next pass, which now succeeds.
        assert state_of(cluster, "node-0") == "uncordon-required"
        mgr.apply_state(mgr.build_state(NS, LABELS), POLICY)
        assert state_of(cluster, "node-0") == "upgrade-done"
        assert cluster.get_or_none(
            "NodeMaintenance", "tpu-operator-node-0", MAINT_NS
        ) is None

    def test_options_from_env(self, monkeypatch):
        monkeypatch.setenv("MAINTENANCE_OPERATOR_ENABLED", "true")
        monkeypatch.setenv("MAINTENANCE_OPERATOR_REQUESTOR_ID", "me")
        monkeypatch.setenv("MAINTENANCE_OPERATOR_REQUESTOR_NAMESPACE", "ns1")
        opts = RequestorOptions.from_env()
        assert opts.use_maintenance_operator
        assert opts.requestor_id == "me"
        assert opts.namespace == "ns1"
        assert opts.node_maintenance_name_prefix == "tpu-operator"

    def test_disabled_mode_rejected(self):
        cluster = FakeCluster()
        mgr = ClusterUpgradeStateManager(cluster, DEVICE)
        with pytest.raises(ValueError):
            RequestorNodeStateManager(
                cluster, mgr.common, RequestorOptions(use_maintenance_operator=False)
            )
