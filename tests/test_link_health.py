"""Per-link health plane (ISSUE 12, docs/fleet-telemetry.md "Per-link
schema" + docs/ici-health-gate.md "Link localization").

The contract under test:

* **grading** (api/telemetry_v1alpha1.grade_link): failed transport =
  failed; collapsed bandwidth / ballooned latency = degraded; missing
  numbers never read sick;
* **CR round trip**: link maps serialize with graded verdicts and
  bounded per-link rolling windows, parse defensively, and peers drop
  out when no longer observed;
* **symmetric topology fold** (fold_link_topology / node_link_scores /
  effective_scores): one ASYMMETRIC observation degrades BOTH
  endpoints — including an endpoint that never published a report;
  disagreeing endpoints take the worst observation;
* **publisher debounce** extends to the graded non-ok link SET: a link
  transition (sick or recovered) always writes; healthy link jitter
  stays debounced;
* **probes**: ppermute_per_link times each hop alone and the quick
  battery surfaces the map; the full gate carries it into
  HealthReport.observation()/links_observation();
* **planner**: a sick link orders its slice first while every per-node
  aggregate reads identically healthy (the localization the scalar
  score provably cannot do); a cross-slice link degrades both slices;
* **quarantine**: both endpoints of a sick link are admission
  candidates and recovery requires the LINK healthy, not just the
  node's own aggregate;
* **fleet**: the aggregator's pool fold pairs cross-shard endpoint
  reports and propagates link-degraded pools degraded-first; the
  tpu_operator_fleet_* family renders worker/orchestrator counters.
"""

import threading

from k8s_operator_libs_tpu.api import (
    DriverUpgradePolicySpec,
    LINK_DEGRADED,
    LINK_FAILED,
    LINK_OK,
    NodeHealth,
    QuarantineSpec,
    effective_node_score,
    effective_scores,
    fold_link_topology,
    grade_link,
    link_key,
    make_node_health_report,
    node_link_scores,
    parse_node_health,
)
from k8s_operator_libs_tpu.api import telemetry_v1alpha1 as telemetry
from k8s_operator_libs_tpu.kube import FakeCluster
from k8s_operator_libs_tpu.tpu.monitor import ReportPublisher
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    TaskRunner,
    UpgradeKeys,
)
from k8s_operator_libs_tpu.utils import IntOrString
from builders import make_node
from test_telemetry import LABELS, NS, make_harness

KEYS = UpgradeKeys(DeviceClass.tpu())

SICK = {"ok": True, "latency_s": 5.0, "gbytes_per_s": 1.0}
HEALTHY = {"ok": True, "latency_s": 0.001, "gbytes_per_s": 42.0}


def publish(cluster, node, links=None, score_bad=False, **kwargs):
    metrics = (
        {"ring_gbytes_per_s": 1.0, "probe_latency_s": 120.0}
        if score_bad
        else {"ring_gbytes_per_s": 45.0, "probe_latency_s": 2.0}
    )
    return ReportPublisher(
        cluster, node, heartbeat_seconds=0.0, **kwargs
    ).publish({"ring_allreduce": not score_bad}, metrics, links=links)


class TestGrading:
    def test_verdict_thresholds(self):
        assert grade_link(False, 0.001, 42.0) == LINK_FAILED
        assert grade_link(True, 0.001, 42.0) == LINK_OK
        # Below half the healthy bandwidth reference: degraded.
        assert grade_link(True, 0.001, 10.0) == LINK_DEGRADED
        # Past twice the per-hop latency budget: degraded.
        assert grade_link(True, 3.0, 42.0) == LINK_DEGRADED
        # Missing numbers are missing measurements, never sickness.
        assert grade_link(True, 0.0, 0.0) == LINK_OK

    def test_verdict_scores_cover_quarantine_thresholds(self):
        """A degraded link must be able to quarantine its endpoints:
        its score sits below the default admission threshold, and a
        failed link below everything."""
        assert telemetry.LINK_VERDICT_SCORES[LINK_FAILED] == 0.0
        assert telemetry.LINK_VERDICT_SCORES[LINK_DEGRADED] < 50.0
        assert telemetry.LINK_VERDICT_SCORES[LINK_OK] == 100.0


class TestContractRoundTrip:
    def test_links_serialize_graded_and_parse(self):
        raw = make_node_health_report(
            "a", {"ring_allreduce": True}, {},
            links={"b": dict(SICK), "device-2": dict(HEALTHY)},
        )
        parsed = parse_node_health(raw)
        assert parsed.links["b"].verdict == LINK_DEGRADED
        assert parsed.links["b"].gbytes_per_s == 1.0
        assert parsed.links["device-2"].verdict == LINK_OK
        # The aggregate score stays link-BLIND by design: localization
        # lives in the map, not the scalar.
        assert parsed.score == 100.0
        worst = parsed.worst_link()
        assert worst is not None and worst.peer == "b"

    def test_link_window_is_bounded_and_peers_drop_out(self):
        prior = None
        for i in range(telemetry.DEFAULT_LINK_WINDOW + 4):
            raw = make_node_health_report(
                "a", {}, {},
                links={"b": {"ok": True, "latency_s": 0.001,
                             "gbytes_per_s": 40.0 + i}},
                prior_links=prior,
            )
            prior = parse_node_health(raw).links
        window = prior["b"].window
        assert len(window) == telemetry.DEFAULT_LINK_WINDOW
        assert window[-1] == 40.0 + telemetry.DEFAULT_LINK_WINDOW + 3
        # A peer absent from the new observation leaves the map —
        # membership is observed, not accumulated.
        raw = make_node_health_report(
            "a", {}, {}, links={"c": dict(HEALTHY)}, prior_links=prior
        )
        assert set(parse_node_health(raw).links) == {"c"}

    def test_parse_tolerates_malformed_links(self):
        raw = make_node_health_report("a", {}, {})
        raw["status"]["links"] = {
            "b": {"latencyS": "nope", "gbytesPerS": []},
            "c": "not-a-mapping",
            "d": {"latencyS": 0.1, "gbytesPerS": 5.0,
                  "verdict": "gibberish", "window": ["x", 1.5]},
        }
        parsed = parse_node_health(raw)
        assert "b" not in parsed.links and "c" not in parsed.links
        # Unknown verdict degrades to ok (absence of a grade is not
        # sickness); unparseable window samples are dropped.
        assert parsed.links["d"].verdict == LINK_OK
        assert parsed.links["d"].window == (1.5,)


class TestTopologyFold:
    def test_asymmetric_observation_degrades_both_endpoints(self):
        health = {
            "a": parse_node_health(make_node_health_report(
                "a", {}, {}, links={"b": dict(SICK)}
            )),
            "b": parse_node_health(make_node_health_report("b", {}, {})),
        }
        topology = fold_link_topology(health)
        obs = topology[link_key("a", "b")]
        assert obs.verdict == LINK_DEGRADED
        assert obs.reporters == ("a",)
        scores = node_link_scores(topology)
        assert scores["a"] == scores["b"] == 40.0
        eff = effective_scores(health)
        assert eff["a"] == eff["b"] == 40.0

    def test_disagreeing_endpoints_take_the_worst(self):
        health = {
            "a": parse_node_health(make_node_health_report(
                "a", {}, {}, links={"b": dict(HEALTHY)}
            )),
            "b": parse_node_health(make_node_health_report(
                "b", {}, {},
                links={"a": {"ok": False, "latency_s": 0.0,
                             "gbytes_per_s": 0.0}},
            )),
        }
        obs = fold_link_topology(health)[link_key("a", "b")]
        assert obs.verdict == LINK_FAILED
        assert obs.reporters == ("a", "b")
        # Worst on every axis: the healthy direction's bandwidth does
        # not launder the failed one.
        assert node_link_scores({obs.key: obs})["a"] == 0.0

    def test_peer_only_node_gets_an_effective_score(self):
        """An endpoint that never published a report still degrades —
        only the peer's report names it."""
        health = {
            "a": parse_node_health(make_node_health_report(
                "a", {}, {}, links={"ghost": dict(SICK)}
            )),
        }
        assert effective_node_score("ghost", health) == 40.0
        assert effective_node_score("unrelated", health) is None

    def test_own_aggregate_and_link_fold_by_min(self):
        health = {
            "a": NodeHealth("a", score=20.0),
            "b": parse_node_health(make_node_health_report(
                "b", {}, {}, links={"a": dict(SICK)}
            )),
        }
        eff = effective_scores(health)
        assert eff["a"] == 20.0  # own aggregate is worse than the link
        assert eff["b"] == 40.0  # link is worse than own aggregate


class TestPublisherLinkDebounce:
    def test_sick_link_transition_always_writes(self):
        cluster = FakeCluster()
        pub = ReportPublisher(cluster, "a", heartbeat_seconds=3600.0)
        assert pub.publish({"x": True}, {}, links={"b": dict(HEALTHY)})
        rv = cluster.get("NodeHealthReport", "a").resource_version
        # Healthy link jitter: same ok verdict, different timings —
        # debounced like any other steady-state observation.
        assert not pub.publish(
            {"x": True}, {},
            links={"b": {"ok": True, "latency_s": 0.002,
                         "gbytes_per_s": 41.0}},
        )
        assert cluster.get("NodeHealthReport", "a").resource_version == rv
        # The link grades degraded: writes immediately.
        assert pub.publish({"x": True}, {}, links={"b": dict(SICK)})
        # Unchanged sick set: debounced again.
        assert not pub.publish({"x": True}, {}, links={"b": dict(SICK)})
        # Recovery is a transition too: writes immediately.
        assert pub.publish({"x": True}, {}, links={"b": dict(HEALTHY)})

    def test_linkless_publish_carries_the_map_forward(self):
        """A publisher tier that ran NO link probes (links=None — the
        full gate under --no-link-probes, a checks-only publisher) must
        not erase the quick tier's link map: it learned nothing about
        the links. Erasure would flip effective scores healthy every
        full-gate cycle — premature quarantine release plus a
        debounce-defeating sick-set flap."""
        cluster = FakeCluster()
        pub = ReportPublisher(cluster, "a", heartbeat_seconds=3600.0)
        assert pub.publish({"x": True}, {}, links={"b": dict(SICK)})
        # Checks-only steady state: the carried-forward map makes the
        # sick set UNCHANGED, so this debounces entirely.
        assert not pub.publish({"x": True}, {}, links=None)
        parsed = parse_node_health(cluster.get("NodeHealthReport", "a").raw)
        assert parsed.links["b"].verdict == LINK_DEGRADED
        # A forced write (check flip) still preserves the map verbatim.
        assert pub.publish({"x": False}, {}, links=None)
        parsed = parse_node_health(cluster.get("NodeHealthReport", "a").raw)
        assert parsed.links["b"].verdict == LINK_DEGRADED
        assert parsed.links["b"].window == (1.0,)
        # An EMPTY mapping is a measurement ("no neighbors"): replaces.
        assert pub.publish({"x": False}, {}, links={})
        parsed = parse_node_health(cluster.get("NodeHealthReport", "a").raw)
        assert parsed.links == {}

    def test_link_windows_survive_publisher_restarts(self):
        cluster = FakeCluster()
        assert publish(cluster, "a", links={"b": dict(SICK)})
        # A NEW publisher (restart) appends to the CR's window.
        assert publish(
            cluster, "a",
            links={"b": {"ok": True, "latency_s": 4.0,
                         "gbytes_per_s": 1.5}},
        )
        parsed = parse_node_health(
            cluster.get("NodeHealthReport", "a").raw
        )
        assert parsed.links["b"].window == (1.0, 1.5)


class TestProbes:
    def test_ppermute_per_link_times_each_hop(self):
        import jax

        from k8s_operator_libs_tpu.ops.collectives import ppermute_per_link
        from k8s_operator_libs_tpu.parallel.mesh import single_axis_mesh

        mesh = single_axis_mesh("x")
        n = len(jax.devices())
        hops = ppermute_per_link(mesh, "x", payload_mb=0.05)
        assert len(hops) == n
        assert all(h.ok for h in hops), [h.error for h in hops]
        assert all(h.latency_s > 0 and h.gbytes_per_s > 0 for h in hops)
        # One report per ring hop, each attributing to a distinct link.
        assert len({(h.src, h.dst) for h in hops}) == n

    def test_quick_battery_surfaces_link_map(self):
        from k8s_operator_libs_tpu.ops.probe_harness import quick_battery

        report = quick_battery(payload_mb=0.05, matmul_size=64)
        assert report.checks.get("links") is True
        assert report.links and all(
            set(obs) == {"ok", "latency_s", "gbytes_per_s"}
            for obs in report.links.values()
        )
        assert report.metrics["worst_link_gbytes_per_s"] > 0

    def test_slice_gang_quick_battery_maps_peer_names(self):
        """Single-process shape: every device is local, so peers keep
        device tags (member_names only applies to OTHER processes) and
        every hop is reported (all srcs local)."""
        import jax

        from k8s_operator_libs_tpu.ops.probe_harness import (
            slice_gang_quick_battery,
        )

        report = slice_gang_quick_battery(
            member_names=["this-host"], payload_mb=0.05, matmul_size=64
        )
        assert report.checks.get("links") is True
        assert len(report.links) == len(jax.devices())
        assert all(peer.startswith("device-") for peer in report.links)

    def test_full_gate_report_carries_links(self):
        from k8s_operator_libs_tpu.tpu.health import HealthReport, IciHealthGate

        gate = IciHealthGate(
            payload_mb=0.05, matmul_size=64, run_burnin=False,
        )
        report = gate.run()
        assert report.links and all(h.ok for h in report.links)
        checks, metrics = report.observation()
        assert checks["links"] is True
        assert metrics["worst_link_gbytes_per_s"] > 0
        links = report.links_observation()
        assert set(links) == {h.peer for h in report.links}
        # The JSON round trip the subprocess gate rides.
        import dataclasses

        rebuilt = HealthReport.from_dict(dataclasses.asdict(report))
        assert rebuilt.links_observation() == links

    def test_gate_cli_args_round_trip_link_knobs(self):
        from k8s_operator_libs_tpu.tpu.health import IciHealthGate

        gate = IciHealthGate(
            run_link_probes=False, link_peer_names=["h0", "h1"]
        )
        args = gate.to_cli_args()
        assert "--no-link-probes" in args
        assert args[args.index("--link-peers") + 1] == "h0,h1"

    def test_quick_probe_loop_once_publishes(self):
        from k8s_operator_libs_tpu.ops.probe_harness import QuickBatteryReport
        from k8s_operator_libs_tpu.tpu.monitor import run_quick_probe_loop

        cluster = FakeCluster()
        pub = ReportPublisher(
            cluster, "node-1", source="quick-probe", heartbeat_seconds=0.0
        )
        battery = lambda: QuickBatteryReport(  # noqa: E731 - tiny stub
            ok=True,
            checks={"ring_allreduce": True, "links": True},
            metrics={"probe_latency_s": 0.1},
            links={"peer-1": dict(SICK)},
        )
        rc = run_quick_probe_loop(pub, once=True, battery=battery)
        assert rc == 0
        parsed = parse_node_health(cluster.get("NodeHealthReport",
                                               "node-1").raw)
        assert parsed.links["peer-1"].verdict == LINK_DEGRADED

    def test_failed_link_tier_does_not_erase_the_published_map(self):
        """A quick cycle whose link tier produced NO measurement
        (disabled, raised, single-device mesh — QuickBatteryReport.links
        is None) must not erase the CR's existing link map: only a
        MEASURED map (empty included) replaces it."""
        from k8s_operator_libs_tpu.ops.probe_harness import (
            QuickBatteryReport,
            quick_battery,
            run_quick_probe_cycle,
        )

        cluster = FakeCluster()
        pub = ReportPublisher(cluster, "node-1", heartbeat_seconds=0.0)
        run_quick_probe_cycle(pub, battery=lambda: QuickBatteryReport(
            ok=True, checks={"ring_allreduce": True},
            links={"peer-1": dict(SICK)},
        ))
        # Tier absent: links defaults to None — the map survives.
        run_quick_probe_cycle(pub, battery=lambda: QuickBatteryReport(
            ok=True, checks={"ring_allreduce": True},
        ))
        parsed = parse_node_health(cluster.get("NodeHealthReport",
                                               "node-1").raw)
        assert parsed.links["peer-1"].verdict == LINK_DEGRADED
        # The real battery with the tier disabled reports None too.
        report = quick_battery(
            payload_mb=0.05, matmul_size=64, probe_links=False
        )
        assert report.links is None

    def test_quick_probe_loop_outlives_blips_and_stops(self):
        from k8s_operator_libs_tpu.tpu.monitor import run_quick_probe_loop

        calls = {"n": 0}
        stop = threading.Event()

        def battery():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient probe blip")
            stop.set()
            from k8s_operator_libs_tpu.ops.probe_harness import (
                QuickBatteryReport,
            )

            return QuickBatteryReport(ok=True, checks={"x": True})

        cluster = FakeCluster()
        pub = ReportPublisher(cluster, "node-1", heartbeat_seconds=0.0)
        rc = run_quick_probe_loop(
            pub, interval_seconds=0.01, battery=battery, stop_event=stop
        )
        assert rc == 0
        assert calls["n"] == 2  # the raising cycle did not kill the loop


class TestQuickProbeGuard:
    def test_busy_or_skip_labeled_node_is_not_probed(self):
        from k8s_operator_libs_tpu.kube import Pod
        from k8s_operator_libs_tpu.tpu.libtpu import TPU_RESOURCE
        from k8s_operator_libs_tpu.tpu.monitor import make_quick_probe_guard

        cluster = FakeCluster()
        cluster.create(make_node("node-1"))
        guard = make_quick_probe_guard(cluster, "node-1")
        assert guard() is None  # idle node: probe
        # A live TPU workload on the node: device contention would make
        # the battery publish a falsely failing report.
        pod = Pod.new("workload", namespace="default")
        pod.node_name = "node-1"
        pod.spec["containers"] = [{
            "name": "w",
            "resources": {"requests": {TPU_RESOURCE: "4"}},
        }]
        cluster.create(pod)
        assert guard() == "TPU chips in use by workloads"
        cluster.delete("Pod", "workload", "default")
        node = cluster.get("Node", "node-1")
        from k8s_operator_libs_tpu.kube import Node as NodeObj

        n = NodeObj(node.raw)
        n.labels[KEYS.skip_label] = "true"
        cluster.update(n)
        assert guard() == "skip label set"

    def test_skipped_cycle_publishes_nothing_and_is_not_a_failure(self):
        from k8s_operator_libs_tpu.tpu.monitor import run_quick_probe_loop

        cluster = FakeCluster()
        pub = ReportPublisher(cluster, "node-1", heartbeat_seconds=0.0)

        def battery():
            raise AssertionError("battery must not run on a skipped cycle")

        rc = run_quick_probe_loop(
            pub, once=True, battery=battery, skip_cycle=lambda: "busy"
        )
        assert rc == 0
        assert cluster.get_or_none("NodeHealthReport", "node-1") is None


class TestGatePublishEntrypoint:
    def test_validation_pod_spec_wires_publish_report(self):
        from k8s_operator_libs_tpu.tpu.validation_pod import (
            ValidationPodManager,
            ValidationPodSpec,
        )

        spec = ValidationPodSpec(publish_reports=True)
        assert "--publish-report" in spec.probe_command()
        pod = ValidationPodManager(FakeCluster(), spec).build_pod("node-1")
        (container,) = pod.spec["containers"]
        env = {e["name"]: e for e in container["env"]}
        assert (
            env["NODE_NAME"]["valueFrom"]["fieldRef"]["fieldPath"]
            == "spec.nodeName"
        )
        # Default shape unchanged: no flag, no NODE_NAME env.
        default_pod = ValidationPodManager(
            FakeCluster(), ValidationPodSpec()
        ).build_pod("node-1")
        (default_container,) = default_pod.spec["containers"]
        assert "--publish-report" not in default_container["command"]
        assert all(
            e["name"] != "NODE_NAME" for e in default_container["env"]
        )

    def test_gang_pod_carries_publish_and_link_peers(self):
        """The production cross-host emitter: a gang pod built from a
        publish_reports spec carries BOTH --link-peers (node-name peer
        ids) and --publish-report — each rank publishes its own
        outgoing cross-host links."""
        from k8s_operator_libs_tpu.tpu.slice_gate import (
            SliceProbeGangManager,
            SliceProbeSpec,
        )

        mgr = SliceProbeGangManager(
            FakeCluster(), SliceProbeSpec(publish_reports=True)
        )
        pod = mgr.build_gang_pod("slice-1", 1, 0, ["host-a", "host-b"])
        (container,) = pod.spec["containers"]
        cmd = container["command"]
        assert "--publish-report" in cmd
        assert cmd[cmd.index("--link-peers") + 1] == "host-a,host-b"

    def test_cli_requires_node_name(self):
        import subprocess, sys, os

        env = {k: v for k, v in os.environ.items() if k != "NODE_NAME"}
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_operator_libs_tpu.tpu.health",
             "--publish-report"],
            capture_output=True, text=True, env=env, timeout=60,
        )
        assert proc.returncode == 2
        assert "NODE_NAME" in proc.stderr


class TestLinkMetricsDedup:
    def test_carried_forward_map_is_not_reobserved(self):
        """A checks-only publish carries the link map forward verbatim;
        the informer re-delivers it, but the histogram must count each
        MEASUREMENT once — not once per write."""
        from k8s_operator_libs_tpu.upgrade import HealthSource, LinkMetrics
        from test_informer import wait_until

        cluster = FakeCluster()
        pub = ReportPublisher(cluster, "a", heartbeat_seconds=0.0)
        source = HealthSource(cluster)
        metrics = LinkMetrics(source)
        with source:
            assert pub.publish({"x": True}, {}, links={"b": dict(SICK)})
            assert pub.publish({"x": False}, {}, links=None)  # carry
            assert pub.publish({"x": True}, {}, links=None)  # carry
            assert wait_until(lambda: source.updates >= 3)
            snap = metrics._latency.snapshot()
            assert snap["count"] == 1  # one measurement, three writes
            # A re-MEASURED link observes again.
            assert pub.publish(
                {"x": True}, {},
                links={"b": {"ok": True, "latency_s": 4.0,
                             "gbytes_per_s": 1.5}},
            )
            assert wait_until(
                lambda: metrics._latency.snapshot()["count"] == 2
            )


class TestPlannerLinkLocalization:
    def _link_pool(self):
        from test_telemetry import TestDegradedFirstPlanning

        return TestDegradedFirstPlanning()._mini_pool()

    def test_sick_link_slice_rolls_first_despite_equal_aggregates(self):
        from k8s_operator_libs_tpu.tpu import enable_slice_aware_planning

        cluster, sim = self._link_pool()
        # EVERY node publishes an identically healthy aggregate; only
        # pool-c-0 carries a degraded link entry against pool-c-1.
        for pool in ("pool-a", "pool-b", "pool-c"):
            for i in range(2):
                name = f"{pool}-{i}"
                links = (
                    {"pool-c-1": dict(SICK)}
                    if name == "pool-c-0"
                    else {f"{pool}-{1 - i}": dict(HEALTHY)}
                )
                publish(cluster, name, links=links)
        mgr = ClusterUpgradeStateManager(
            cluster, DeviceClass.tpu(), runner=TaskRunner(inline=True)
        )
        enable_slice_aware_planning(mgr)
        source = mgr.with_health_telemetry()
        try:
            sim.set_template_hash("rev-2")
            policy = DriverUpgradePolicySpec(
                auto_upgrade=True, max_parallel_upgrades=1,
                max_unavailable=IntOrString(1),
            )
            mgr.apply_state(mgr.build_state(NS, LABELS), policy)
            mgr.apply_state(mgr.build_state(NS, LABELS), policy)
            states = {
                n.name: n.labels.get(KEYS.state_label, "")
                for n in cluster.list("Node")
            }
            assert states["pool-c-0"] == "cordon-required"
            assert states["pool-c-1"] == "cordon-required"
            assert states["pool-a-0"] == "upgrade-required"
            assert states["pool-b-0"] == "upgrade-required"
        finally:
            source.stop()

    def test_cross_slice_link_degrades_both_slices(self):
        from k8s_operator_libs_tpu.kube import Pod
        from k8s_operator_libs_tpu.tpu import TpuNodeDetector
        from k8s_operator_libs_tpu.tpu.planner import assess_slices
        from k8s_operator_libs_tpu.upgrade import (
            ClusterUpgradeState,
            NodeUpgradeState,
            UpgradeState,
        )

        state = ClusterUpgradeState()
        for name in ("pool-a-0", "pool-b-0", "pool-c-0"):
            state.node_states[UpgradeState.DONE].append(NodeUpgradeState(
                node=make_node(name),
                driver_pod=Pod.new(f"driver-{name}", namespace=NS),
                driver_daemonset=None,
            ))
        state.node_health = {
            "pool-a-0": parse_node_health(make_node_health_report(
                "pool-a-0", {}, {}, links={"pool-b-0": dict(SICK)}
            )),
        }
        out = assess_slices(TpuNodeDetector(), state)
        # Both endpoint slices consult the worst incident link; the
        # third slice stays fully healthy.
        assert out.effective_score("pool-a-0") == 40.0
        assert out.effective_score("pool-b-0") == 40.0
        assert out.effective_score("pool-c-0") == 100.0
        assert out.worst_links["pool-a-0"] == link_key(
            "pool-a-0", "pool-b-0"
        )
        assert out.worst_links["pool-b-0"] == link_key(
            "pool-a-0", "pool-b-0"
        )

    def test_no_link_maps_is_byte_identical_old_ordering(self):
        from k8s_operator_libs_tpu.tpu.planner import SliceAssessment

        assessment = SliceAssessment(
            candidates={"a": [], "b": []},
            scores={"a": 50.0},
        )
        assert assessment.effective_score("a") == 50.0
        assert assessment.effective_score("b") == 100.0
        assert assessment.link_scores == {}


class TestQuarantineLinkAware:
    POLICY = DriverUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=0,
        max_unavailable=IntOrString("100%"),
        quarantine=QuarantineSpec(
            enable=True,
            unhealthy_score=50.0,
            recovery_score=70.0,
            reprobe_backoff_seconds=1,
        ),
    )

    def test_both_endpoints_quarantine_and_release_on_link_recovery(self):
        import time as _time

        cluster, sim, mgr = make_harness(nodes=4)
        source = mgr.with_health_telemetry()
        try:
            for _ in range(3):  # settle: classify everyone to done
                sim.step()
                mgr.apply_state(mgr.build_state(NS, LABELS), self.POLICY)
            # ONE asymmetric sick-link report; node-2's own report is
            # fully healthy, node-1 never reports at all.
            publish(cluster, "node-0", links={"node-1": dict(SICK)})
            publish(cluster, "node-2", links={"node-3": dict(HEALTHY)})
            from test_informer import wait_until

            assert wait_until(lambda: source.updates >= 2)
            mgr.apply_state(mgr.build_state(NS, LABELS), self.POLICY)
            states = {
                n.name: n.labels.get(KEYS.state_label, "")
                for n in cluster.list("Node")
            }
            # Both endpoints of the sick link — including never-reported
            # node-1 — quarantined; the healthy-link pair untouched.
            assert states["node-0"] == "quarantined"
            assert states["node-1"] == "quarantined"
            assert states["node-2"] == "upgrade-done"
            assert states["node-3"] == "upgrade-done"
            # Recovery requires the LINK healthy: the reporter's own
            # aggregate was always 100, so only the link transition can
            # release.
            publish(cluster, "node-0", links={"node-1": dict(HEALTHY)})
            assert wait_until(lambda: source.updates >= 3)
            deadline = _time.time() + 10.0
            while True:
                _time.sleep(0.3)  # let the 1 s recheck backoff expire
                mgr.apply_state(mgr.build_state(NS, LABELS), self.POLICY)
                totals = mgr.common.quarantine_manager.totals()
                if totals["in_quarantine"] == 0:
                    break
                assert _time.time() < deadline, totals
            assert all(
                not (o.raw.get("spec") or {}).get("unschedulable")
                for o in cluster.list("Node")
            )
        finally:
            source.stop()


class TestFleetLinkFold:
    def test_cross_shard_link_pairs_in_the_merged_fold(self):
        """The two endpoints of a cross-shard link live in DIFFERENT
        sources; the pool fold must merge maps before folding topology
        or the pair never meets."""
        from k8s_operator_libs_tpu.fleet import FleetHealthAggregator

        class StubSource:
            def __init__(self, snap):
                self._snap = snap

            def snapshot(self):
                return self._snap

        a_report = parse_node_health(make_node_health_report(
            "pool-1-n0", {}, {}, links={"pool-2-n0": dict(SICK)}
        ))
        b_report = parse_node_health(make_node_health_report(
            "pool-2-n0", {}, {}
        ))
        agg = FleetHealthAggregator(
            pool_of=lambda name: name.rsplit("-n", 1)[0]
        )
        agg.add_source(StubSource({"pool-1-n0": a_report}))
        agg.add_source(StubSource({"pool-2-n0": b_report}))
        health = agg.pool_health()
        # BOTH pools degrade from the one asymmetric link observation.
        assert health["pool-1"][0] == 40.0
        assert health["pool-2"][0] == 40.0
        assert agg.ordered(["pool-3", "pool-2", "pool-1"])[-1] == "pool-3"

    def test_duplicate_node_merges_sicker_links_across_copies(self):
        """Mid-failover a node appears in two sources. The merge is
        PER AXIS: the lower aggregate score from one copy AND the
        sicker link map from the other — picking one whole report
        would discard whichever signal rode the losing copy."""
        from k8s_operator_libs_tpu.fleet import FleetHealthAggregator

        class StubSource:
            def __init__(self, snap):
                self._snap = snap

            def snapshot(self):
                return self._snap

        stale = NodeHealth("p1-n0", score=95.0)  # lower score, no links
        fresh = parse_node_health(make_node_health_report(
            "p1-n0", {}, {},
            links={"p2-n0": {"ok": False, "latency_s": 0.0,
                             "gbytes_per_s": 0.0}},
        ))  # score 100, FAILED link
        agg = FleetHealthAggregator(
            pool_of=lambda name: name.rsplit("-n", 1)[0]
        )
        agg.add_source(StubSource({"p1-n0": stale}))
        agg.add_source(StubSource({"p1-n0": fresh}))
        health = agg.pool_health()
        # The failed link (score 0) survives the merge despite riding
        # the higher-aggregate copy; the peer's pool degrades too.
        assert health["p1"][0] == 0.0
        assert health["p2"][0] == 0.0

    def test_strict_pool_mapper_tolerates_device_tag_peers(self):
        from k8s_operator_libs_tpu.fleet import FleetHealthAggregator

        class StubSource:
            def snapshot(self):
                return {
                    "n0": parse_node_health(make_node_health_report(
                        "n0", {}, {}, links={"device-3": dict(SICK)}
                    )),
                }

        def strict_pool_of(name):
            if name.startswith("device-"):
                raise KeyError(name)
            return "pool-1"

        agg = FleetHealthAggregator(pool_of=strict_pool_of)
        agg.add_source(StubSource())
        # The device-tag peer is skipped; its NODE endpoint still
        # carries the degradation into the pool.
        assert agg.pool_health() == {"pool-1": (40.0, 0)}

    def test_mapper_failure_for_a_reported_node_stays_loud(self):
        """The peer-only suppression must not swallow a mapper failure
        for a node that PUBLISHED a report — silently dropping it would
        hide a degraded pool from the fleet fold."""
        import pytest

        from k8s_operator_libs_tpu.fleet import FleetHealthAggregator

        class StubSource:
            def snapshot(self):
                return {"n0": NodeHealth("n0", score=10.0)}

        def broken_pool_of(name):
            raise KeyError(name)

        agg = FleetHealthAggregator(pool_of=broken_pool_of)
        agg.add_source(StubSource())
        with pytest.raises(KeyError):
            agg.pool_health()


class TestFleetMetricsFamily:
    def test_renders_orchestrator_and_worker_counters(self):
        from k8s_operator_libs_tpu.fleet import FleetMetrics

        class StubOrchestrator:
            grants_issued = 7
            budget_denials = 3
            ticks = 11
            api_errors = 1
            last_summary = {
                "budget": 4, "granted": 3, "done": 2, "pending": 5,
            }

        class StubConfig:
            identity = "worker-a"

        class StubWorker:
            config = StubConfig()
            passes = 42
            shard_passes = {"shard-00": 40, "shard-01": 2}

            def owned_shards(self):
                return frozenset({"shard-00", "shard-01"})

            def lease_stats(self):
                return {
                    "acquisitions": 5,
                    "failover_acquisitions": 2,
                    "losses": 1,
                }

        metrics = FleetMetrics(orchestrator=StubOrchestrator())
        metrics.add_worker(StubWorker())
        text = metrics.render()
        assert "tpu_operator_fleet_grants_total 7" in text
        assert "tpu_operator_fleet_budget_denials_total 3" in text
        # headroom = budget - (granted - done) = 4 - 1 = 3
        assert "tpu_operator_fleet_budget_headroom 3" in text
        assert (
            'tpu_operator_fleet_worker_owned_shards{worker="worker-a"} 2'
            in text
        )
        assert (
            'tpu_operator_fleet_lease_failovers_total{worker="worker-a"} 2'
            in text
        )
        assert (
            'tpu_operator_fleet_shard_passes_total'
            '{worker="worker-a",shard="shard-00"} 40' in text
        )

    def test_served_by_the_shared_metrics_server(self):
        import urllib.request

        from k8s_operator_libs_tpu.fleet import FleetMetrics
        from k8s_operator_libs_tpu.upgrade import MetricsServer

        with MetricsServer(FleetMetrics()) as server:
            body = urllib.request.urlopen(server.url).read().decode()
        assert body == ""  # no halves wired: an empty, valid exposition

    def test_worker_lease_and_pass_counters_move(self):
        """Drive a real 1-worker fleet tick loop far enough to see the
        counters the exporter reads: acquisitions on claim, per-shard
        pass counts on reconcile."""
        from k8s_operator_libs_tpu.fleet import FleetWorkerConfig, ShardWorker

        cluster = FakeCluster()
        for i in range(4):
            cluster.create(make_node(f"n{i}"))
        from k8s_operator_libs_tpu.kube.sim import DaemonSetSimulator

        sim = DaemonSetSimulator(
            cluster, name="driver", namespace=NS, match_labels=LABELS
        )
        sim.settle()
        clock = {"t": 1000.0}
        worker = ShardWorker(
            cluster,
            FleetWorkerConfig(
                identity="w1",
                shards=2,
                namespace=NS,
                driver_labels=LABELS,
                pool_of=lambda name: "pool-0",
            ),
            now_fn=lambda: clock["t"],
            wall_fn=lambda: clock["t"],
        )
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=0,
            max_unavailable=IntOrString("100%"),
        )
        with worker:
            for _ in range(3):
                worker.tick(policy)
                clock["t"] += 3.0
            stats = worker.lease_stats()
            assert stats["acquisitions"] == 2  # both shards claimed once
            assert stats["failover_acquisitions"] == 0  # all preferred
            assert stats["losses"] == 0
            assert worker.passes >= 2
            owned_shard_passes = {
                s: c for s, c in worker.shard_passes.items() if c
            }
            assert owned_shard_passes  # coverage series populated
