"""Monitor DaemonSet payload end to end: the REAL process chain.

`python -m k8s_operator_libs_tpu.tpu.monitor --once` runs as an actual
subprocess against a LocalApiServer over real HTTP (kubeconfig +
NODE_NAME from the environment, exactly the DaemonSet's wiring), and its
default subprocess gate spawns the REAL probe grandchild — so one test
covers monitor CLI → RestClient-over-kubeconfig → SubprocessHealthGate →
health payload battery → Node condition written over the wire. Nothing
is monkeypatched.

Uses the new `--gate-preset portable` (no floors, no TPU-only kernels) so
the battery passes on the hermetic CPU mesh; the failure path arms an
impossible MXU floor through the monitor's own floor-override knobs.
"""

import subprocess
import sys

import pytest

from k8s_operator_libs_tpu.kube import LocalApiServer, Node
from k8s_operator_libs_tpu.kube.objects import condition_status
from k8s_operator_libs_tpu.tpu.monitor import ICI_HEALTHY_CONDITION
from k8s_operator_libs_tpu.upgrade import DeviceClass, UpgradeKeys
from k8s_operator_libs_tpu.utils.jaxenv import hermetic_cpu_env

KEYS = UpgradeKeys(DeviceClass.tpu())


@pytest.fixture()
def server():
    with LocalApiServer() as srv:
        yield srv


@pytest.fixture(scope="module")
def cache_dir(tmp_path_factory):
    """Run-private XLA cache shared across this module's e2e runs (warm
    second run) — NEVER a fixed /tmp path: a predictable world-writable
    location invites cache poisoning and cross-user collisions (see the
    HEALTH_CACHE_DIR threat model in tpu/health.py)."""
    return str(tmp_path_factory.mktemp("monitor-e2e-jax-cache"))


def run_monitor(server, tmp_path, cache_dir, node_name, *extra_args,
                timeout=300):
    kubeconfig = server.write_kubeconfig(str(tmp_path / "kubeconfig"))
    env = hermetic_cpu_env(4)
    env["KUBECONFIG"] = kubeconfig
    env["NODE_NAME"] = node_name
    # The probe grandchild inherits this too.
    env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    return subprocess.run(
        [
            sys.executable, "-m", "k8s_operator_libs_tpu.tpu.monitor",
            "--once", *extra_args,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def node_condition(server, name):
    node = Node(server.cluster.get("Node", name).raw)
    return condition_status(node.status, ICI_HEALTHY_CONDITION)


def make_ready_node(server, name, labels=None):
    node = Node.new(name, labels=labels or {})
    node.set_ready(True)
    server.cluster.create(node)


class TestMonitorPayloadEndToEnd:
    def test_passing_battery_publishes_true_condition(
        self, server, tmp_path, cache_dir
    ):
        make_ready_node(server, "mon-node")
        proc = run_monitor(
            server, tmp_path, cache_dir, "mon-node",
            "--gate-preset", "portable",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert node_condition(server, "mon-node") == "True"

    def test_floor_violation_publishes_false_condition_and_rc1(
        self, server, tmp_path, cache_dir
    ):
        make_ready_node(server, "mon-node")
        proc = run_monitor(
            server, tmp_path, cache_dir, "mon-node",
            "--gate-preset", "portable",
            "--min-mxu-tflops", "1e9",  # no device reaches this
        )
        assert proc.returncode == 1, proc.stderr[-2000:]
        assert node_condition(server, "mon-node") == "False"

    def test_skip_label_probes_nothing(self, server, tmp_path, cache_dir):
        make_ready_node(
            server, "mon-node", labels={KEYS.skip_label: "true"}
        )
        proc = run_monitor(
            server, tmp_path, cache_dir, "mon-node",
            "--gate-preset", "portable", timeout=60,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert node_condition(server, "mon-node") is None
