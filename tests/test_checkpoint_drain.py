"""Checkpoint-coordinated zero-disruption drains (ISSUE 6).

The contract under test (docs/checkpoint-drain.md):

* **arc** — wait-for-jobs routes into ``checkpoint-required`` when the
  policy enables checkpointing; the drain gates on checkpoint-complete
  acks; uncordon is restore-verified against the WorkloadCheckpoint CRs;
* **epoch idempotency** — re-entry after an aborted pass re-derives the
  same epoch id from the durable clock: no duplicate requests, no stale
  acks from an earlier arc;
* **deadline escalation** — a wedged (non-acking) workload escalates to
  a plain drain at the deadline, with the partial manifest of whatever
  DID ack recorded; the roll always completes;
* **restore degradation** — a vanished checkpoint defers uncordon up to
  its own deadline, then degrades to a cold restart — bounded, never a
  stalled pool;
* **lost steps** — the sim's accounting shows a checkpointed victim
  re-trains only post-checkpoint steps while the evict-only baseline
  re-trains everything.
"""

import json

import pytest

from k8s_operator_libs_tpu.api import (
    CheckpointSpec,
    DrainSpec,
    DriverUpgradePolicySpec,
    make_workload_checkpoint,
    workload_checkpoint_name,
)
from k8s_operator_libs_tpu.api.upgrade_v1alpha1 import WORKLOAD_CHECKPOINT_KIND
from k8s_operator_libs_tpu.kube import FakeCluster, Node, Pod
from k8s_operator_libs_tpu.kube.objects import KubeObject
from k8s_operator_libs_tpu.kube.sim import (
    CheckpointingWorkloadSimulator,
    DaemonSetSimulator,
)
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    TaskRunner,
    UpgradeKeys,
    UpgradeMetrics,
    UpgradeState,
)
from k8s_operator_libs_tpu.utils import IntOrString
from builders import make_node

DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
NS = "driver-ns"
LABELS = {"app": "driver"}
TRAIN_NS = "training"
TRAIN_SELECTOR = "app=trainer"


def checkpoint_policy(timeout_seconds=300, enable=True, **kwargs):
    return DriverUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=0,
        max_unavailable=IntOrString("100%"),
        drain=DrainSpec(enable=True, force=True, timeout_seconds=30),
        checkpoint=(
            CheckpointSpec(
                enable=True,
                pod_selector=TRAIN_SELECTOR,
                timeout_seconds=timeout_seconds,
                **kwargs,
            )
            if enable
            else None
        ),
    )


def make_harness(node_count=2, nonacking=(), ack_delay_steps=1):
    cluster = FakeCluster()
    for i in range(node_count):
        cluster.create(make_node(f"node-{i}"))
    sim = DaemonSetSimulator(
        cluster, name="driver", namespace=NS, match_labels=LABELS
    )
    sim.settle()
    workload = CheckpointingWorkloadSimulator(
        cluster, KEYS, namespace=TRAIN_NS,
        nonacking=nonacking, ack_delay_steps=ack_delay_steps,
    )
    mgr = ClusterUpgradeStateManager(
        cluster, DEVICE, runner=TaskRunner(inline=True)
    )
    return cluster, sim, workload, mgr


def state_of(cluster, name):
    return cluster.get("Node", name).labels.get(KEYS.state_label, "")


def drive(cluster, sim, workload, mgr, policy, max_passes=60,
          record=None):
    for i in range(max_passes):
        workload.step()
        sim.step()
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)
        sim.step()
        if record is not None:
            record(i)
        if all(
            state_of(cluster, n.name) == str(UpgradeState.DONE)
            for n in cluster.list("Node")
        ) and sim.all_pods_ready_and_current():
            for _ in range(3):
                workload.step()  # evicted victims reschedule + restore
            return i + 1
    raise AssertionError("roll did not converge")


@pytest.fixture
def clock():
    # The durable-clock helpers (advance_durable_clock, the
    # pod-completion wait) read wall time through the process-wide
    # faultpoints seam — the same virtual clock the chaos harness
    # installs (docs/chaos-harness.md), so these tests drive deadlines
    # the way a chaos schedule does.
    from k8s_operator_libs_tpu.utils import faultpoints

    fake = faultpoints.ChaosClock(wall_start=1_000_000.0)
    faultpoints.install_clock(fake)
    yield fake
    faultpoints.clear_clock()


class TestHappyArc:
    def test_roll_passes_through_checkpoint_required(self):
        cluster, sim, workload, mgr = make_harness(node_count=2)
        seen = set()
        sim.set_template_hash("v2")

        def record(_):
            for n in cluster.list("Node"):
                seen.add(n.labels.get(KEYS.state_label, ""))

        drive(cluster, sim, workload, mgr, checkpoint_policy(),
              record=record)
        assert str(UpgradeState.CHECKPOINT_REQUIRED) in seen
        totals = mgr.common.checkpoint_manager.totals()
        assert totals["completions"] == 2
        assert totals["escalations"] == 0
        assert totals["restores_verified"] == 2

    def test_drain_gated_until_ack(self):
        # A slow acker (3 ticks) holds its node in checkpoint-required
        # while the workload pod is still alive — eviction must not
        # precede the ack.
        cluster, sim, workload, mgr = make_harness(
            node_count=1, ack_delay_steps=3
        )
        policy = checkpoint_policy()
        sim.set_template_hash("v2")
        saw_gated = {"passes": 0}

        def record(_):
            if state_of(cluster, "node-0") == str(
                UpgradeState.CHECKPOINT_REQUIRED
            ):
                # The workload pod must still exist while gated.
                assert cluster.get_or_none(
                    "Pod", workload.workload("node-0").pod_name, TRAIN_NS
                ) is not None
                saw_gated["passes"] += 1

        drive(cluster, sim, workload, mgr, policy, record=record)
        assert saw_gated["passes"] >= 2  # actually waited for the ack
        w = workload.workload("node-0")
        assert w.restarts == 1
        # Only the steps after the checkpoint were re-trained.
        assert 0 <= w.lost_steps <= 3

    def test_lost_steps_strictly_fewer_than_full_restart(self):
        results = {}
        for mode in ("baseline", "checkpointed"):
            cluster, sim, workload, mgr = make_harness(node_count=2)
            for _ in range(10):
                workload.step()  # history worth losing
            sim.set_template_hash("v2")
            drive(cluster, sim, workload, mgr,
                  checkpoint_policy(enable=(mode == "checkpointed")))
            results[mode] = workload.lost_steps()
        assert results["checkpointed"] < results["baseline"]
        assert results["baseline"] >= 20  # both victims lost everything

    def test_arc_annotations_cleaned_up_at_done(self):
        cluster, sim, workload, mgr = make_harness(node_count=1)
        sim.set_template_hash("v2")
        drive(cluster, sim, workload, mgr, checkpoint_policy())
        annotations = Node(cluster.get("Node", "node-0").raw).annotations
        for key in (
            KEYS.checkpoint_start_annotation,
            KEYS.checkpoint_manifest_annotation,
            KEYS.checkpoint_escalated_annotation,
            KEYS.restore_verify_start_annotation,
        ):
            assert key not in annotations, key

    def test_restore_verified_before_uncordon(self):
        """The WorkloadCheckpoint CR must exist (restore-verified) while
        the node is still cordoned — the manifest gate runs in the
        validation bucket, pre-uncordon."""
        cluster, sim, workload, mgr = make_harness(node_count=1)
        sim.set_template_hash("v2")
        verified_while_cordoned = []
        real_gate = mgr.common.checkpoint_manager.restore_gate

        def spy(node):
            ok = real_gate(node)
            if ok:
                raw = cluster.get("Node", node.name).raw
                verified_while_cordoned.append(
                    bool((raw.get("spec") or {}).get("unschedulable"))
                )
            return ok

        mgr.common.validation_manager.restore_gate = spy
        drive(cluster, sim, workload, mgr, checkpoint_policy())
        assert verified_while_cordoned and all(verified_while_cordoned)


class TestEpochIdempotency:
    def setup_node_in_checkpoint(self, cluster, mgr):
        node = Node(cluster.get("Node", "node-0").raw)
        mgr.provider.change_node_upgrade_state(
            node, UpgradeState.CHECKPOINT_REQUIRED
        )

    def test_reentry_reuses_epoch_and_issues_no_duplicate_requests(self):
        cluster, sim, workload, mgr = make_harness(node_count=1)
        workload.step()  # workload pod exists
        cm = mgr.common.checkpoint_manager
        spec = CheckpointSpec(
            enable=True, pod_selector=TRAIN_SELECTOR, timeout_seconds=300
        )
        node = Node(cluster.get("Node", "node-0").raw)
        for _ in range(3):  # three aborted/retried passes
            cm.coordinate(node, spec, UpgradeState.DRAIN_REQUIRED)
            node = Node(cluster.get("Node", "node-0").raw)
        assert cm.totals()["requests"] == 1  # one request, not three
        epoch = node.annotations[KEYS.checkpoint_start_annotation]
        pod = Pod(
            cluster.get(
                "Pod", workload.workload("node-0").pod_name, TRAIN_NS
            ).raw
        )
        assert pod.annotations[KEYS.checkpoint_request_annotation] == epoch

    def test_stale_ack_from_previous_epoch_does_not_count(self, clock):
        cluster, sim, workload, mgr = make_harness(node_count=1)
        workload.step()
        cm = mgr.common.checkpoint_manager
        spec = CheckpointSpec(
            enable=True, pod_selector=TRAIN_SELECTOR, timeout_seconds=300
        )
        pod_name = workload.workload("node-0").pod_name
        # A leftover ack from an imaginary earlier arc.
        cluster.patch(
            "Pod", pod_name, TRAIN_NS,
            patch={"metadata": {"annotations": {
                KEYS.checkpoint_complete_annotation: "999",
            }}},
        )
        node = Node(cluster.get("Node", "node-0").raw)
        cm.coordinate(node, spec, UpgradeState.DRAIN_REQUIRED)
        # Not advanced: the stale ack did not satisfy the fresh epoch.
        assert state_of(cluster, "node-0") == str(
            UpgradeState.CHECKPOINT_REQUIRED
        ) or KEYS.state_label not in Node(
            cluster.get("Node", "node-0").raw
        ).labels
        assert cm.totals()["completions"] == 0


class TestDeadlineEscalation:
    def test_nonacking_workload_escalates_and_roll_completes(self, clock):
        cluster, sim, workload, mgr = make_harness(
            node_count=2, nonacking=("node-0",)
        )
        sim.set_template_hash("v2")
        policy = checkpoint_policy(timeout_seconds=5)

        def record(_):
            clock.advance(2)  # wall time passes between reconcile passes

        drive(cluster, sim, workload, mgr, policy, record=record)
        totals = mgr.common.checkpoint_manager.totals()
        assert totals["escalations"] == 1  # node-0 only, exactly once
        assert totals["completions"] == 1  # node-1 acked normally
        # The wedged victim paid the full restart; the acking one didn't.
        assert workload.workload("node-0").lost_steps > 0
        assert (
            workload.workload("node-1").lost_steps
            < workload.workload("node-0").lost_steps
        )

    def test_escalation_records_partial_manifest(self, clock):
        """Two victims on one node, one acks, one is wedged: the
        escalated manifest still carries the acker's checkpoint."""
        cluster = FakeCluster()
        cluster.create(make_node("node-0"))
        sim = DaemonSetSimulator(
            cluster, name="driver", namespace=NS, match_labels=LABELS
        )
        sim.settle()
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        )
        cm = mgr.common.checkpoint_manager
        spec = CheckpointSpec(
            enable=True, pod_selector=TRAIN_SELECTOR, timeout_seconds=5
        )
        for pod_name in ("acker", "wedged"):
            pod = Pod.new(pod_name, namespace=TRAIN_NS)
            pod.node_name = "node-0"
            pod.labels.update({"app": "trainer"})
            pod.phase = "Running"
            cluster.create(pod)
        node = Node(cluster.get("Node", "node-0").raw)
        cm.coordinate(node, spec, UpgradeState.DRAIN_REQUIRED)
        epoch = node.annotations[KEYS.checkpoint_start_annotation]
        # Only "acker" completes the contract.
        cluster.create(KubeObject(make_workload_checkpoint(
            "acker", TRAIN_NS, "node-0", step=7, request_id=epoch
        )))
        cluster.patch(
            "Pod", "acker", TRAIN_NS,
            patch={"metadata": {"annotations": {
                KEYS.checkpoint_complete_annotation: epoch,
                KEYS.checkpoint_step_annotation: "7",
            }}},
        )
        clock.advance(6)  # past the deadline
        node = Node(cluster.get("Node", "node-0").raw)
        cm.coordinate(node, spec, UpgradeState.DRAIN_REQUIRED)
        node = Node(cluster.get("Node", "node-0").raw)
        assert node.labels[KEYS.state_label] == str(
            UpgradeState.DRAIN_REQUIRED
        )
        assert (
            node.annotations[KEYS.checkpoint_escalated_annotation] == "true"
        )
        manifest = json.loads(
            node.annotations[KEYS.checkpoint_manifest_annotation]
        )
        assert manifest == {f"{TRAIN_NS}/acker": 7}
        assert cm.totals()["escalations"] == 1

    def test_restart_past_deadline_with_full_acks_does_not_escalate(
        self, clock
    ):
        """ISSUE 13 satellite pin (found by the chaos worker-restart
        schedule): a worker killed after every ack LANDED and restarted
        after the deadline must re-enter via the durable epoch id and
        COMPLETE the gate — the checkpoint is done, whatever the clock
        says. Before the fix, the expiry check ran first and a finished
        checkpoint was escalated into a cold-restart drain, stamping
        the escalated annotation that then haunted the restore gate."""
        cluster = FakeCluster()
        cluster.create(make_node("node-0"))
        sim = DaemonSetSimulator(
            cluster, name="driver", namespace=NS, match_labels=LABELS
        )
        sim.settle()
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        )
        cm = mgr.common.checkpoint_manager
        spec = CheckpointSpec(
            enable=True, pod_selector=TRAIN_SELECTOR, timeout_seconds=5
        )
        pod = Pod.new("victim", namespace=TRAIN_NS)
        pod.node_name = "node-0"
        pod.labels.update({"app": "trainer"})
        pod.phase = "Running"
        cluster.create(pod)
        node = Node(cluster.get("Node", "node-0").raw)
        cm.coordinate(node, spec, UpgradeState.DRAIN_REQUIRED)
        epoch = node.annotations[KEYS.checkpoint_start_annotation]
        cluster.create(KubeObject(make_workload_checkpoint(
            "victim", TRAIN_NS, "node-0", step=9, request_id=epoch
        )))
        cluster.patch(
            "Pod", "victim", TRAIN_NS,
            patch={"metadata": {"annotations": {
                KEYS.checkpoint_complete_annotation: epoch,
                KEYS.checkpoint_step_annotation: "9",
            }}},
        )
        # The worker dies here. The RESTARTED worker's first pass runs
        # long after the deadline — a fresh manager, the same durable
        # state.
        clock.advance(600)
        restarted = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        ).common.checkpoint_manager
        node = Node(cluster.get("Node", "node-0").raw)
        restarted.coordinate(node, spec, UpgradeState.DRAIN_REQUIRED)
        node = Node(cluster.get("Node", "node-0").raw)
        assert node.labels[KEYS.state_label] == str(
            UpgradeState.DRAIN_REQUIRED
        )
        assert restarted.totals()["escalations"] == 0
        assert restarted.totals()["completions"] == 1
        assert (
            KEYS.checkpoint_escalated_annotation not in node.annotations
        ), "a complete checkpoint must never wear the escalated mark"
        manifest = json.loads(
            node.annotations[KEYS.checkpoint_manifest_annotation]
        )
        assert manifest == {f"{TRAIN_NS}/victim": 9}
        # The durable clock retired with the gate: nothing left to
        # spuriously expire a later arc.
        assert KEYS.checkpoint_start_annotation not in node.annotations

    def test_disabled_spec_advances_parked_nodes(self):
        """Checkpointing withdrawn mid-roll: nodes already parked in
        checkpoint-required advance into the eviction path instead of
        wedging on a disabled feature — and the durable deadline clock
        is cleared with them (review finding: a surviving stamp would
        read as instantly-expired on the NEXT enabled roll and escalate
        it with zero requests ever issued)."""
        cluster, sim, workload, mgr = make_harness(node_count=1)
        workload.step()  # a victim exists, so the arc actually started
        node = Node(cluster.get("Node", "node-0").raw)
        mgr.provider.change_node_upgrade_state(
            node, UpgradeState.CHECKPOINT_REQUIRED
        )
        sim.step()
        # One enabled pass starts the clock (requests out, no acks yet).
        mgr.apply_state(mgr.build_state(NS, LABELS), checkpoint_policy())
        node = Node(cluster.get("Node", "node-0").raw)
        assert KEYS.checkpoint_start_annotation in node.annotations
        # Policy withdrawn: the park-path exit must clear the clock.
        mgr.apply_state(
            mgr.build_state(NS, LABELS), checkpoint_policy(enable=False)
        )
        node = Node(cluster.get("Node", "node-0").raw)
        assert node.labels[KEYS.state_label] == str(
            UpgradeState.DRAIN_REQUIRED
        )
        assert KEYS.checkpoint_start_annotation not in node.annotations

    def test_verify_restore_false_skips_verification(self):
        """verifyRestore=false must actually be consulted (review
        finding): the gate retires the manifest without checking CRs —
        no deferral, no restores_verified count."""
        cluster, sim, workload, mgr = make_harness(node_count=1)
        sim.set_template_hash("v2")
        # Victim acks normally, but we DELETE its checkpoint CR as soon
        # as it exists — with verification on this would defer uncordon
        # for the whole restore deadline; with it off the roll must
        # complete promptly and unverified.
        from k8s_operator_libs_tpu.api.upgrade_v1alpha1 import (
            WORKLOAD_CHECKPOINT_KIND as CKPT_KIND,
        )

        def record(_):
            for o in cluster.list(CKPT_KIND, namespace=TRAIN_NS):
                cluster.delete(CKPT_KIND, o.name, TRAIN_NS)

        drive(cluster, sim, workload, mgr,
              checkpoint_policy(verify_restore=False), record=record)
        totals = mgr.common.checkpoint_manager.totals()
        assert totals["completions"] == 1
        assert totals["restores_verified"] == 0
        assert totals["restore_escalations"] == 0
        node = Node(cluster.get("Node", "node-0").raw)
        assert KEYS.checkpoint_manifest_annotation not in node.annotations

    def test_restore_deferral_does_not_burn_validation_clock(self, clock):
        """Review finding: once every validation gate passed, the
        validation timeout clock must be retired BEFORE the restore gate
        defers — a stale stamp plus a later transient pod flap would
        FAIL a node that passed everything."""
        cluster = FakeCluster()
        cluster.create(make_node("node-0"))
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        )
        hook_runs = []
        mgr.with_validation_enabled(
            validation_hook=lambda node: hook_runs.append(node.name) or True
        )
        node = Node(cluster.get("Node", "node-0").raw)
        # Manifest pointing at a CR that never exists: the gate defers.
        mgr.provider.change_node_upgrade_annotation(
            node, KEYS.checkpoint_manifest_annotation,
            json.dumps({f"{TRAIN_NS}/ghost": 5}),
        )
        # A previously stamped validation clock (an earlier not-ready
        # probe pass), now ancient.
        mgr.provider.change_node_upgrade_annotation(
            node, KEYS.validation_start_annotation, "1"
        )
        vm = mgr.common.validation_manager
        node = Node(cluster.get("Node", "node-0").raw)
        assert vm.validate(node) is False  # restore gate defers
        node = Node(cluster.get("Node", "node-0").raw)
        # The validation clock is gone — the deferral cannot be turned
        # into a validation FAILURE by a later flap reading the stamp.
        assert KEYS.validation_start_annotation not in node.annotations
        assert KEYS.validation_failed_annotation not in node.annotations
        # And the device-bound hook never ran: the restore gate defers
        # BEFORE the expensive gates, not after them.
        assert hook_runs == []

    def test_no_eligible_pods_completes_trivially(self):
        cluster, sim, workload, mgr = make_harness(node_count=1)
        # No workload.step(): no training pod exists on the node.
        node = Node(cluster.get("Node", "node-0").raw)
        mgr.provider.change_node_upgrade_state(
            node, UpgradeState.CHECKPOINT_REQUIRED
        )
        sim.step()
        mgr.apply_state(mgr.build_state(NS, LABELS), checkpoint_policy())
        assert state_of(cluster, "node-0") == str(
            UpgradeState.DRAIN_REQUIRED
        )
        assert mgr.common.checkpoint_manager.totals()["completions"] == 1


class TestRestoreVerifiedUncordon:
    def _node_with_manifest(self, cluster, mgr, manifest):
        node = Node(cluster.get("Node", "node-0").raw)
        mgr.provider.change_node_upgrade_annotation(
            node, KEYS.checkpoint_manifest_annotation, json.dumps(manifest)
        )
        return Node(cluster.get("Node", "node-0").raw)

    def test_missing_checkpoint_defers_then_degrades(self, clock):
        cluster = FakeCluster()
        cluster.create(make_node("node-0"))
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        )
        cm = mgr.common.checkpoint_manager
        node = self._node_with_manifest(
            cluster, mgr, {f"{TRAIN_NS}/ghost": 5}
        )
        assert cm.restore_gate(node) is False  # defers: CR missing
        assert cm.restore_gate(node) is False
        clock.advance(601)  # past RESTORE_VERIFY_TIMEOUT_SECONDS
        assert cm.restore_gate(node) is True  # degrades, never stalls
        totals = cm.totals()
        assert totals["restore_escalations"] == 1
        assert totals["restores_verified"] == 0
        node = Node(cluster.get("Node", "node-0").raw)
        assert KEYS.checkpoint_manifest_annotation not in node.annotations

    def test_checkpoint_older_than_manifest_defers(self, clock):
        """A CR that exists but holds an OLDER step than the manifest
        recorded is not restorable to the promised point."""
        cluster = FakeCluster()
        cluster.create(make_node("node-0"))
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        )
        cm = mgr.common.checkpoint_manager
        cluster.create(KubeObject(make_workload_checkpoint(
            "victim", TRAIN_NS, "node-0", step=3
        )))
        node = self._node_with_manifest(
            cluster, mgr, {f"{TRAIN_NS}/victim": 9}
        )
        assert cm.restore_gate(node) is False
        # Workload re-checkpoints at the promised step: gate opens.
        cluster.patch(
            WORKLOAD_CHECKPOINT_KIND,
            workload_checkpoint_name("victim"),
            TRAIN_NS,
            patch={"spec": {"step": 9}},
        )
        node = Node(cluster.get("Node", "node-0").raw)
        assert cm.restore_gate(node) is True
        assert cm.totals()["restores_verified"] == 1

    def test_corrupt_manifest_clears_and_proceeds(self):
        cluster = FakeCluster()
        cluster.create(make_node("node-0"))
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        )
        cm = mgr.common.checkpoint_manager
        node = Node(cluster.get("Node", "node-0").raw)
        mgr.provider.change_node_upgrade_annotation(
            node, KEYS.checkpoint_manifest_annotation, "not-json"
        )
        node = Node(cluster.get("Node", "node-0").raw)
        assert cm.restore_gate(node) is True
        node = Node(cluster.get("Node", "node-0").raw)
        assert KEYS.checkpoint_manifest_annotation not in node.annotations

    def test_failed_recovery_routes_through_restore_gate(self):
        """Review finding: a FAILED node carrying a checkpoint manifest
        must recover THROUGH the validation bucket (where the restore
        gate runs and retires the manifest), never straight to uncordon
        — otherwise the uncordon is unverified and the stale manifest
        haunts the next roll."""
        cluster, sim, workload, mgr = make_harness(node_count=1)
        workload.step()
        sim.step()
        node = Node(cluster.get("Node", "node-0").raw)
        # A node that checkpointed, then failed mid-upgrade: cordoned,
        # manifest recorded, driver pod in sync again (recovery signal).
        mgr.common.cordon_manager.cordon(node)
        pod_name = workload.workload("node-0").pod_name
        cluster.create(KubeObject(make_workload_checkpoint(
            pod_name, TRAIN_NS, "node-0", step=4
        )))
        mgr.provider.change_node_upgrade_annotation(
            node, KEYS.checkpoint_manifest_annotation,
            json.dumps({f"{TRAIN_NS}/{pod_name}": 4}),
        )
        mgr.provider.change_node_upgrade_state(node, UpgradeState.FAILED)
        sim.step()
        mgr.apply_state(mgr.build_state(NS, LABELS), checkpoint_policy())
        assert state_of(cluster, "node-0") == str(
            UpgradeState.VALIDATION_REQUIRED
        )
        # Next pass: restore gate verifies, manifest retired, released.
        sim.step()
        mgr.apply_state(mgr.build_state(NS, LABELS), checkpoint_policy())
        node = Node(cluster.get("Node", "node-0").raw)
        assert KEYS.checkpoint_manifest_annotation not in node.annotations
        assert mgr.common.checkpoint_manager.totals()["restores_verified"] == 1

    def test_manifest_routes_pod_restart_through_validation_bucket(self):
        """Even with validation unconfigured, a manifest-carrying node
        goes pod-restart → validation-required (where the restore gate
        polls) — never straight to uncordon."""
        cluster, sim, workload, mgr = make_harness(node_count=1)
        assert not mgr.is_validation_enabled()
        seen = set()
        sim.set_template_hash("v2")

        def record(_):
            seen.add(state_of(cluster, "node-0"))

        drive(cluster, sim, workload, mgr, checkpoint_policy(),
              record=record)
        assert str(UpgradeState.VALIDATION_REQUIRED) in seen
        assert (
            mgr.common.checkpoint_manager.totals()["restores_verified"] == 1
        )


class TestObservability:
    def test_checkpoint_gauge_family_exported(self):
        cluster, sim, workload, mgr = make_harness(node_count=1)
        metrics = UpgradeMetrics(mgr)
        sim.set_template_hash("v2")
        policy = checkpoint_policy()
        for _ in range(40):
            workload.step()
            sim.step()
            state = mgr.build_state(NS, LABELS)
            mgr.apply_state(state, policy)
            metrics.observe(state)
            sim.step()
            if state_of(cluster, "node-0") == str(UpgradeState.DONE):
                break
        text = metrics.render()
        for line in (
            "tpu_operator_upgrade_checkpoint_escalations_total",
            "tpu_operator_upgrade_checkpoint_completed_total",
            "tpu_operator_upgrade_checkpoint_restores_verified_total",
            "tpu_operator_upgrade_checkpoint_nodes_waiting",
        ):
            assert line in text, line
        assert (
            'tpu_operator_upgrade_checkpoint_escalations_total{device="tpu"} 0'
            in text
        )

    def test_pass_stats_count_the_arc(self):
        cluster, sim, workload, mgr = make_harness(node_count=1)
        workload.step()
        node = Node(cluster.get("Node", "node-0").raw)
        mgr.provider.change_node_upgrade_state(
            node, UpgradeState.CHECKPOINT_REQUIRED
        )
        sim.step()
        mgr.apply_state(mgr.build_state(NS, LABELS), checkpoint_policy())
        stats = mgr.last_pass_stats
        assert stats.checkpoint_requests_issued == 1
        assert stats.checkpoint_nodes_waiting == 1  # gated on the ack
        # The workload acks; the next pass completes the gate.
        workload.step()
        sim.step()
        mgr.apply_state(mgr.build_state(NS, LABELS), checkpoint_policy())
        stats = mgr.last_pass_stats
        assert stats.checkpoint_completions == 1
        assert stats.checkpoint_nodes_waiting == 0
        assert stats.checkpoints_completed_total == 1

    def test_drain_event_distinguishes_flavors(self):
        events = []

        class Recorder:
            def eventf(self, obj, event_type, reason, fmt, *args):
                events.append(fmt % args if args else fmt)

        cluster = FakeCluster()
        cluster.create(make_node("node-0"))
        sim = DaemonSetSimulator(
            cluster, name="driver", namespace=NS, match_labels=LABELS
        )
        sim.settle()
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True),
            recorder=Recorder(),
        )
        node = Node(cluster.get("Node", "node-0").raw)
        mgr.provider.change_node_upgrade_annotation(
            node, KEYS.checkpoint_manifest_annotation,
            json.dumps({f"{TRAIN_NS}/victim": 3}),
        )
        mgr.provider.change_node_upgrade_state(
            node, UpgradeState.DRAIN_REQUIRED
        )
        sim.step()
        mgr.apply_state(mgr.build_state(NS, LABELS), checkpoint_policy())
        assert any(
            "checkpoint-coordinated drain" in e for e in events
        ), events


class TestCrossFileStateEnumerations:
    def test_checkpoint_required_is_a_gang_consumer_state(self):
        """Review finding: tpu/slice_gate.py enumerates the mid-pipeline
        states POSITIVELY — a slice peer parked in checkpoint-required
        must keep protecting its probe gang from teardown/replacement,
        like every other state between cordon and validation."""
        from k8s_operator_libs_tpu.tpu.slice_gate import (
            _GANG_CONSUMER_STATES,
        )

        assert str(UpgradeState.CHECKPOINT_REQUIRED) in _GANG_CONSUMER_STATES


class TestSpecValidation:
    def test_round_trip(self):
        policy = checkpoint_policy(timeout_seconds=120)
        restored = DriverUpgradePolicySpec.from_dict(policy.to_dict())
        assert restored.checkpoint == policy.checkpoint
        assert restored.checkpoint.timeout_seconds == 120
        assert restored.checkpoint.verify_restore is True

    def test_zero_deadline_rejected(self):
        with pytest.raises(ValueError):
            CheckpointSpec(
                enable=True, pod_selector="app=x", timeout_seconds=0
            )
        with pytest.raises(ValueError):
            CheckpointSpec.from_dict(
                {"enable": True, "podSelector": "app=x", "timeoutSeconds": -5}
            )

    def test_enabled_without_selector_rejected(self):
        """Review finding: an empty selector would ask EVERY pod on the
        node (driver pods included) to checkpoint; none would ack and
        every node would stall to the deadline and spuriously escalate."""
        with pytest.raises(ValueError):
            CheckpointSpec(enable=True)
        with pytest.raises(ValueError):
            CheckpointSpec.from_dict({"enable": True})
        # Disabled specs stay constructible with the defaults.
        assert CheckpointSpec().pod_selector == ""

    def test_absent_checkpoint_key_keeps_legacy_shape(self):
        d = DriverUpgradePolicySpec(auto_upgrade=True).to_dict()
        assert "checkpoint" not in d
        assert DriverUpgradePolicySpec.from_dict(d).checkpoint is None
