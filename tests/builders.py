"""Fixture builders shared across suites.

Model: the reference's fluent test builders — NewNode/NewDaemonSet/NewPod
(auto Running+Ready)/NewNodeMaintenance (reference:
upgrade_suit_test.go:216-428).
"""

from __future__ import annotations

import itertools
import uuid
from typing import Mapping, Optional

from k8s_operator_libs_tpu.kube import (
    ControllerRevision,
    DaemonSet,
    Node,
    NodeMaintenance,
    Pod,
)

_seq = itertools.count(1)


def unique(prefix: str) -> str:
    return f"{prefix}-{next(_seq)}-{uuid.uuid4().hex[:6]}"


def make_node(
    name: Optional[str] = None,
    labels: Optional[Mapping[str, str]] = None,
    annotations: Optional[Mapping[str, str]] = None,
    unschedulable: bool = False,
    ready: bool = True,
) -> Node:
    node = Node.new(name or unique("node"), labels=labels, annotations=annotations)
    node.unschedulable = unschedulable
    node.set_ready(ready)
    return node


def make_daemonset(
    name: Optional[str] = None,
    namespace: str = "driver-ns",
    match_labels: Optional[Mapping[str, str]] = None,
    desired: int = 0,
) -> DaemonSet:
    ds = DaemonSet.new(name or unique("ds"), namespace=namespace)
    ds.match_labels = dict(match_labels or {"app": "driver"})
    ds.labels.update(ds.match_labels)
    ds.desired_number_scheduled = desired
    return ds


def make_pod(
    name: Optional[str] = None,
    namespace: str = "driver-ns",
    node_name: str = "",
    labels: Optional[Mapping[str, str]] = None,
    phase: str = "Running",
    ready: bool = True,
    owner: Optional[DaemonSet] = None,
    revision_hash: str = "",
    empty_dir: bool = False,
    controlled: bool = False,
) -> Pod:
    pod = Pod.new(name or unique("pod"), namespace=namespace, labels=labels)
    pod.node_name = node_name
    pod.phase = phase
    if ready and phase == "Running":
        pod.status["conditions"] = [{"type": "Ready", "status": "True"}]
    if owner is not None:
        pod.add_owner_reference(owner)
        pod.labels.update(owner.match_labels)
    elif controlled:
        # owner_references is a non-inserting read accessor; mutate via
        # metadata (or add_owner_reference) so the ref actually lands.
        pod.metadata.setdefault("ownerReferences", []).append(
            {"apiVersion": "apps/v1", "kind": "ReplicaSet",
             "name": unique("rs"), "uid": str(uuid.uuid4()), "controller": True}
        )
    if revision_hash:
        pod.labels["controller-revision-hash"] = revision_hash
    if empty_dir:
        pod.spec["volumes"] = [{"name": "scratch", "emptyDir": {}}]
    return pod


def make_controller_revision(
    owner: DaemonSet, revision: int, hash_value: str
) -> ControllerRevision:
    cr = ControllerRevision.new(
        f"{owner.name}-{hash_value}", namespace=owner.namespace
    )
    cr.revision = revision
    cr.labels.update(owner.match_labels)
    cr.labels["controller-revision-hash"] = hash_value
    cr.add_owner_reference(owner)
    return cr


def make_node_maintenance(
    name: Optional[str] = None,
    namespace: str = "maintenance-ns",
    node_name: str = "",
    requestor_id: str = "tpu.operator.dev",
    ready: bool = False,
) -> NodeMaintenance:
    nm = NodeMaintenance.new(name or unique("nm"), namespace=namespace)
    nm.requestor_id = requestor_id
    nm.node_name = node_name
    if ready:
        nm.status["conditions"] = [
            {"type": "Ready", "status": "True", "reason": "Ready"}
        ]
    return nm
