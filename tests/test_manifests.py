"""The shipped manifests/ CRDs are valid and drive end-to-end through
crdutil — the TPU analog of the reference booting envtest from its checked-in
fixture (reference: upgrade_suit_test.go:87-89,
hack/crd/bases/maintenance.nvidia.com_nodemaintenances.yaml) and of
examples/apply-crds as crdutil's e2e driver (reference:
examples/apply-crds/main.go:34-61).
"""

import glob
import os
import re

import yaml

from k8s_operator_libs_tpu.crdutil import parse_crds_from_file, process_crds
from k8s_operator_libs_tpu.kube import FakeCluster, NodeMaintenance

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFESTS_ROOT = os.path.join(REPO, "manifests")
MANIFESTS = os.path.join(MANIFESTS_ROOT, "crds")
DOCKERFILE = os.path.join(REPO, "docker", "Dockerfile")


def test_manifests_apply_and_establish():
    cluster = FakeCluster()
    count = process_crds(cluster, [MANIFESTS], "apply")
    assert count == 2
    for name in (
        "tpuupgradepolicies.tpu-operator.dev",
        "nodemaintenances.maintenance.nvidia.com",
    ):
        assert cluster.get("CustomResourceDefinition", name).is_established()


def test_nodemaintenance_fixture_matches_protocol_surface():
    """Every field the requestor protocol reads/writes exists in the CRD
    schema — the fixture can't drift from the code silently."""
    path = os.path.join(MANIFESTS, "nodemaintenances.yaml")
    (crd,) = parse_crds_from_file(path)
    assert crd.raw["spec"]["group"] == "maintenance.nvidia.com"
    version = crd.raw["spec"]["versions"][0]
    assert (
        f"{crd.raw['spec']['group']}/{version['name']}"
        == NodeMaintenance.API_VERSION
    )
    props = version["schema"]["openAPIV3Schema"]["properties"]
    spec_props = props["spec"]["properties"]
    for field in (
        "nodeName",
        "requestorID",
        "additionalRequestors",
        "waitForPodCompletion",
        "drainSpec",
    ):
        assert field in spec_props, field
    drain_props = spec_props["drainSpec"]["properties"]
    for field in (
        "force",
        "podSelector",
        "timeoutSeconds",
        "deleteEmptyDir",
        "podEvictionFilters",
    ):
        assert field in drain_props, field
    assert "conditions" in props["status"]["properties"]


def test_nodemaintenance_fixture_delete_tolerates_absence():
    cluster = FakeCluster()
    # Delete-before-apply must not fail (reference: crdutil.go:252-272).
    process_crds(cluster, [MANIFESTS], "delete")
    process_crds(cluster, [MANIFESTS], "apply")
    process_crds(cluster, [MANIFESTS], "delete")
    assert cluster.list("CustomResourceDefinition") == []


# -- every shipped manifest parses and is internally consistent -----------


def all_manifest_docs():
    docs = []
    for path in sorted(
        glob.glob(os.path.join(MANIFESTS_ROOT, "**", "*.yaml"), recursive=True)
    ):
        with open(path) as fh:
            for doc in yaml.safe_load_all(fh):
                if doc is not None:
                    docs.append((path, doc))
    return docs


def monitor_docs():
    path = os.path.join(MANIFESTS_ROOT, "monitor-daemonset.yaml")
    with open(path) as fh:
        return {d["kind"]: d for d in yaml.safe_load_all(fh) if d}


def test_every_manifest_yaml_parses_with_kind_and_name():
    docs = all_manifest_docs()
    assert len(docs) >= 6  # 2 CRDs + DaemonSet/SA/ClusterRole/Binding
    for path, doc in docs:
        assert doc.get("kind"), path
        assert doc.get("apiVersion"), path
        assert (doc.get("metadata") or {}).get("name"), path


class TestMonitorDaemonSet:
    """The round-3 manifest finally under test: schema shape, image
    consistency with the code that schedules pods from this image, RBAC
    coverage for every API call the monitor makes."""

    def test_selector_matches_template_labels(self):
        ds = monitor_docs()["DaemonSet"]
        match = ds["spec"]["selector"]["matchLabels"]
        labels = ds["spec"]["template"]["metadata"]["labels"]
        assert match.items() <= labels.items()

    def test_image_matches_validation_pod_spec_and_makefile(self):
        from k8s_operator_libs_tpu.tpu.validation_pod import ValidationPodSpec

        ds = monitor_docs()["DaemonSet"]
        (container,) = ds["spec"]["template"]["spec"]["containers"]
        spec = ValidationPodSpec()
        assert container["image"] == spec.full_image
        makefile = open(os.path.join(REPO, "Makefile")).read()
        image_default = re.search(
            r"^IMAGE \?= (\S+)$", makefile, re.MULTILINE
        ).group(1)
        assert image_default == spec.image

    def test_command_is_the_monitor_module(self):
        import importlib.util

        ds = monitor_docs()["DaemonSet"]
        (container,) = ds["spec"]["template"]["spec"]["containers"]
        cmd = container["command"]
        assert cmd[:3] == ["python", "-m", "k8s_operator_libs_tpu.tpu.monitor"]
        assert importlib.util.find_spec(cmd[2]) is not None

    def test_metrics_port_consistent_with_command(self):
        ds = monitor_docs()["DaemonSet"]
        (container,) = ds["spec"]["template"]["spec"]["containers"]
        cmd = container["command"]
        declared = {p["containerPort"]: p["name"] for p in container["ports"]}
        port = int(cmd[cmd.index("--metrics-port") + 1])
        assert declared.get(port) == "metrics"

    def test_node_name_from_downward_api(self):
        ds = monitor_docs()["DaemonSet"]
        (container,) = ds["spec"]["template"]["spec"]["containers"]
        env = {e["name"]: e for e in container["env"]}
        assert (
            env["NODE_NAME"]["valueFrom"]["fieldRef"]["fieldPath"]
            == "spec.nodeName"
        )

    def test_compile_cache_env_matches_mount_and_constant(self):
        from k8s_operator_libs_tpu.tpu.health import HEALTH_CACHE_DIR

        ds = monitor_docs()["DaemonSet"]
        pod = ds["spec"]["template"]["spec"]
        (container,) = pod["containers"]
        env = {e["name"]: e.get("value") for e in container["env"]}
        assert env["JAX_COMPILATION_CACHE_DIR"] == HEALTH_CACHE_DIR
        mounts = {m["name"]: m["mountPath"] for m in container["volumeMounts"]}
        volumes = {v["name"]: v for v in pod["volumes"]}
        assert mounts["jax-cache"] == HEALTH_CACHE_DIR
        assert volumes["jax-cache"]["hostPath"]["path"] == HEALTH_CACHE_DIR

    def test_targets_tpu_nodes_and_tolerates_taints(self):
        from k8s_operator_libs_tpu.parallel.topology import (
            GKE_TPU_ACCELERATOR_LABEL,
        )
        from k8s_operator_libs_tpu.tpu.libtpu import TPU_RESOURCE

        ds = monitor_docs()["DaemonSet"]
        pod = ds["spec"]["template"]["spec"]
        terms = pod["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"]
        keys = {
            expr["key"] for t in terms for expr in t["matchExpressions"]
        }
        assert GKE_TPU_ACCELERATOR_LABEL in keys
        toleration_keys = {t.get("key") for t in pod["tolerations"]}
        assert TPU_RESOURCE in toleration_keys

    def test_rbac_covers_every_monitor_api_call(self):
        """TpuHealthMonitor calls: get node, list pods (busy-chip check),
        update node status, create events (tpu/monitor.py). The shipped
        ClusterRole must grant each; the binding must wire the
        DaemonSet's ServiceAccount to it."""
        docs = monitor_docs()
        rules = docs["ClusterRole"]["rules"]

        def allows(resource, verb):
            return any(
                resource in r.get("resources", ())
                and verb in r.get("verbs", ())
                for r in rules
            )

        assert allows("nodes", "get")
        assert allows("nodes/status", "update")
        assert allows("pods", "list")
        assert allows("events", "create")
        binding = docs["ClusterRoleBinding"]
        assert binding["roleRef"]["name"] == docs["ClusterRole"]["metadata"]["name"]
        (subject,) = binding["subjects"]
        sa = docs["ServiceAccount"]
        assert subject["kind"] == "ServiceAccount"
        assert subject["name"] == sa["metadata"]["name"]
        assert subject["namespace"] == sa["metadata"]["namespace"]
        ds = docs["DaemonSet"]
        assert (
            ds["spec"]["template"]["spec"]["serviceAccountName"]
            == sa["metadata"]["name"]
        )


def quickprobe_docs():
    path = os.path.join(MANIFESTS_ROOT, "monitor-quickprobe-daemonset.yaml")
    with open(path) as fh:
        return {d["kind"]: d for d in yaml.safe_load_all(fh) if d}


class TestQuickProbeDaemonSet:
    """The quick-probe tier as a real deployment shape (ISSUE 12,
    ROADMAP 5b): the low-rate monitor DaemonSet running --quick-only,
    publishing NodeHealthReports and nothing else — so its RBAC is
    exactly the report surface, with no nodes/status write at all."""

    def test_selector_matches_template_labels(self):
        ds = quickprobe_docs()["DaemonSet"]
        match = ds["spec"]["selector"]["matchLabels"]
        labels = ds["spec"]["template"]["metadata"]["labels"]
        assert match.items() <= labels.items()

    def test_command_is_quick_only_monitor(self):
        import importlib.util

        ds = quickprobe_docs()["DaemonSet"]
        (container,) = ds["spec"]["template"]["spec"]["containers"]
        cmd = container["command"]
        assert cmd[:3] == [
            "python", "-m", "k8s_operator_libs_tpu.tpu.monitor"
        ]
        assert importlib.util.find_spec(cmd[2]) is not None
        assert "--quick-only" in cmd
        interval = float(cmd[cmd.index("--quick-interval-seconds") + 1])
        # The tier's whole point is a cadence well below the full
        # gate's 300 s interval.
        assert 0 < interval < 300

    def test_image_and_cache_match_full_monitor(self):
        full = monitor_docs()["DaemonSet"]
        quick = quickprobe_docs()["DaemonSet"]
        (fc,) = full["spec"]["template"]["spec"]["containers"]
        (qc,) = quick["spec"]["template"]["spec"]["containers"]
        assert qc["image"] == fc["image"]  # one probe payload image
        fenv = {e["name"]: e.get("value") for e in fc["env"]}
        qenv = {e["name"]: e.get("value") for e in qc["env"]}
        assert (
            qenv["JAX_COMPILATION_CACHE_DIR"]
            == fenv["JAX_COMPILATION_CACHE_DIR"]
        )
        assert (
            qenv.get("NODE_NAME") is None  # downward API, not a literal
        )

    def test_rbac_is_exactly_the_report_surface(self):
        """--quick-only publishes NodeHealthReports (get + create +
        update/patch incl. status) plus the READ-ONLY probe-discipline
        guard (get nodes for the skip label, list pods for the
        busy-chip check) and touches nothing else — in particular no
        nodes/status: the quick tier writes no conditions, and its
        ClusterRole must not be able to."""
        docs = quickprobe_docs()
        rules = docs["ClusterRole"]["rules"]

        def allows(resource, verb):
            return any(
                resource in r.get("resources", ())
                and verb in r.get("verbs", ())
                for r in rules
            )

        for verb in ("get", "create", "update", "patch"):
            assert allows("nodehealthreports", verb)
        assert allows("nodehealthreports/status", "update")
        assert allows("nodes", "get")  # skip-label guard
        assert allows("pods", "list")  # busy-chip guard
        assert not allows("nodes/status", "update")
        assert not allows("nodes", "update")
        assert not allows("nodes", "patch")
        binding = docs["ClusterRoleBinding"]
        assert (
            binding["roleRef"]["name"]
            == docs["ClusterRole"]["metadata"]["name"]
        )
        (subject,) = binding["subjects"]
        sa = docs["ServiceAccount"]
        assert subject["name"] == sa["metadata"]["name"]
        assert (
            docs["DaemonSet"]["spec"]["template"]["spec"][
                "serviceAccountName"
            ]
            == sa["metadata"]["name"]
        )

    def test_targets_tpu_nodes(self):
        from k8s_operator_libs_tpu.parallel.topology import (
            GKE_TPU_ACCELERATOR_LABEL,
        )

        ds = quickprobe_docs()["DaemonSet"]
        pod = ds["spec"]["template"]["spec"]
        terms = pod["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"]
        keys = {
            expr["key"] for t in terms for expr in t["matchExpressions"]
        }
        assert GKE_TPU_ACCELERATOR_LABEL in keys


class TestDockerfile:
    """`make image` produces the image the framework's pod shapes name;
    no container runtime exists in CI, so the build file is validated
    structurally: every COPY source exists, the payload modules resolve,
    and the cache path matches the code constant."""

    def test_copy_sources_exist(self):
        content = open(DOCKERFILE).read()
        copies = re.findall(r"^COPY\s+(.+)$", content, re.MULTILINE)
        assert copies
        for line in copies:
            sources = line.split()[:-1]  # last token is the destination
            for src in sources:
                assert os.path.exists(os.path.join(REPO, src)), src

    def test_cmd_module_resolves(self):
        import importlib.util
        import json

        content = open(DOCKERFILE).read()
        cmd = json.loads(
            re.search(r"^CMD\s+(\[.*\])$", content, re.MULTILINE).group(1)
        )
        assert cmd[:2] == ["python", "-m"]
        assert importlib.util.find_spec(cmd[2]) is not None

    def test_cache_dir_matches_health_constant(self):
        from k8s_operator_libs_tpu.tpu.health import HEALTH_CACHE_DIR

        content = open(DOCKERFILE).read()
        assert f"JAX_COMPILATION_CACHE_DIR={HEALTH_CACHE_DIR}" in content
        assert f"mkdir -p {HEALTH_CACHE_DIR}" in content

    def test_make_image_target_builds_this_dockerfile(self):
        makefile = open(os.path.join(REPO, "Makefile")).read()
        assert re.search(r"^image:", makefile, re.MULTILINE)
        assert "docker/Dockerfile" in makefile

    def test_pinned_jax_matches_environment(self):
        """The image pins the jax the floors were calibrated against —
        which is the jax this repo runs everywhere else."""
        import jax

        content = open(DOCKERFILE).read()
        pinned = re.search(
            r"^ARG JAX_VERSION=(\S+)$", content, re.MULTILINE
        ).group(1)
        assert pinned == jax.__version__
