"""The shipped manifests/ CRDs are valid and drive end-to-end through
crdutil — the TPU analog of the reference booting envtest from its checked-in
fixture (reference: upgrade_suit_test.go:87-89,
hack/crd/bases/maintenance.nvidia.com_nodemaintenances.yaml) and of
examples/apply-crds as crdutil's e2e driver (reference:
examples/apply-crds/main.go:34-61).
"""

import os

from k8s_operator_libs_tpu.crdutil import parse_crds_from_file, process_crds
from k8s_operator_libs_tpu.kube import FakeCluster, NodeMaintenance

MANIFESTS = os.path.join(os.path.dirname(__file__), "..", "manifests", "crds")


def test_manifests_apply_and_establish():
    cluster = FakeCluster()
    count = process_crds(cluster, [MANIFESTS], "apply")
    assert count == 2
    for name in (
        "tpuupgradepolicies.tpu-operator.dev",
        "nodemaintenances.maintenance.nvidia.com",
    ):
        assert cluster.get("CustomResourceDefinition", name).is_established()


def test_nodemaintenance_fixture_matches_protocol_surface():
    """Every field the requestor protocol reads/writes exists in the CRD
    schema — the fixture can't drift from the code silently."""
    path = os.path.join(MANIFESTS, "nodemaintenances.yaml")
    (crd,) = parse_crds_from_file(path)
    assert crd.raw["spec"]["group"] == "maintenance.nvidia.com"
    version = crd.raw["spec"]["versions"][0]
    assert (
        f"{crd.raw['spec']['group']}/{version['name']}"
        == NodeMaintenance.API_VERSION
    )
    props = version["schema"]["openAPIV3Schema"]["properties"]
    spec_props = props["spec"]["properties"]
    for field in (
        "nodeName",
        "requestorID",
        "additionalRequestors",
        "waitForPodCompletion",
        "drainSpec",
    ):
        assert field in spec_props, field
    drain_props = spec_props["drainSpec"]["properties"]
    for field in (
        "force",
        "podSelector",
        "timeoutSeconds",
        "deleteEmptyDir",
        "podEvictionFilters",
    ):
        assert field in drain_props, field
    assert "conditions" in props["status"]["properties"]


def test_nodemaintenance_fixture_delete_tolerates_absence():
    cluster = FakeCluster()
    # Delete-before-apply must not fail (reference: crdutil.go:252-272).
    process_crds(cluster, [MANIFESTS], "delete")
    process_crds(cluster, [MANIFESTS], "apply")
    process_crds(cluster, [MANIFESTS], "delete")
    assert cluster.list("CustomResourceDefinition") == []
