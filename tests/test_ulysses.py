"""Ulysses all-to-all sequence parallelism: numerics, grads, burn-in wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_operator_libs_tpu.models import BurninConfig, make_sharded_train_step
from k8s_operator_libs_tpu.ops import (
    reference_attention,
    ring_attention,
    ulysses_attention,
    ulysses_probe,
)
from k8s_operator_libs_tpu.parallel import build_mesh


def _qkv(shape, dtype=jnp.float32, seed=3):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(kq, shape, dtype=jnp.float32).astype(dtype),
        jax.random.normal(kk, shape, dtype=jnp.float32).astype(dtype),
        jax.random.normal(kv, shape, dtype=jnp.float32).astype(dtype),
    )


class TestUlyssesAttention:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_reference(self, sp):
        mesh = build_mesh({"sp": sp})
        q, k, v = _qkv((2, 8, 16 * sp, 8))
        out = ulysses_attention(q, k, v, mesh, "sp", causal=True)
        expected = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), expected, atol=1e-5, rtol=1e-4
        )

    def test_matches_ring_attention(self):
        """Both SP schemes compute the same function."""
        mesh = build_mesh({"sp": 4})
        q, k, v = _qkv((1, 4, 64, 16))
        u = ulysses_attention(q, k, v, mesh, "sp", causal=True)
        r = ring_attention(q, k, v, mesh, "sp", causal=True)
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(r), atol=1e-5, rtol=1e-4
        )

    def test_heads_not_divisible_raises(self):
        mesh = build_mesh({"sp": 4})
        q, k, v = _qkv((1, 6, 32, 8))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, mesh, "sp")

    def test_local_heads_checked_when_tp_shards_heads(self):
        """With heads also sharded over tp, the divisibility check must use
        per-shard heads: 2 global heads over tp=2 leaves 1 per shard, which
        sp=2 cannot split — a clear ValueError, not an XLA error."""
        mesh = build_mesh({"tp": 2, "sp": 2}, jax.devices("cpu")[:4])
        spec = P(None, "tp", "sp", None)
        q, k, v = _qkv((1, 2, 32, 8))
        with pytest.raises(ValueError, match="per-shard heads"):
            ulysses_attention(q, k, v, mesh, "sp", spec=spec)

    def test_composes_with_tp_sharded_heads(self):
        """4 heads over tp=2 → 2 per shard, sp=2 splits them: must match."""
        mesh = build_mesh({"tp": 2, "sp": 2}, jax.devices("cpu")[:4])
        spec = P(None, "tp", "sp", None)
        q, k, v = _qkv((1, 4, 32, 8))
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(t, sharding) for t in (q, k, v))
        out = ulysses_attention(q, k, v, mesh, "sp", causal=True, spec=spec)
        expected = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), expected, atol=1e-5, rtol=1e-4
        )

    def test_gradients_finite(self):
        mesh = build_mesh({"sp": 4})
        q, k, v = _qkv((1, 4, 32, 8))

        def loss(q, k, v):
            return jnp.sum(ulysses_attention(q, k, v, mesh, "sp") ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g in grads:
            assert np.all(np.isfinite(np.asarray(g)))

    def test_composes_with_dp(self):
        mesh = build_mesh({"dp": 2, "sp": 4})
        spec = P("dp", None, "sp", None)
        q, k, v = _qkv((2, 4, 32, 8))
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(t, sharding) for t in (q, k, v))
        out = ulysses_attention(q, k, v, mesh, "sp", causal=True, spec=spec)
        expected = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), expected, atol=1e-5, rtol=1e-4
        )


class TestUlyssesProbe:
    def test_probe_passes(self):
        mesh = build_mesh({"sp": 4})
        report = ulysses_probe(mesh, "sp", seq_per_device=32, head_dim=16)
        assert report.ok, report.error
        assert report.tokens_per_s > 0


class TestUlyssesBurnin:
    def test_train_step_matches_ring(self):
        cfg = BurninConfig(
            d_model=32, n_heads=4, d_ff=64, n_layers=1, seq_len=16, batch=4
        )
        cpus = jax.devices("cpu")
        mesh = build_mesh({"dp": 2, "sp": 4}, cpus)
        step_u, params_u, batch_u = make_sharded_train_step(
            mesh, cfg, sp_impl="ulysses"
        )
        _, loss_u = step_u(params_u, batch_u)
        step_r, params_r, batch_r = make_sharded_train_step(
            mesh, cfg, sp_impl="ring"
        )
        _, loss_r = step_r(params_r, batch_r)
        np.testing.assert_allclose(
            float(loss_u), float(loss_r), rtol=1e-3
        )
