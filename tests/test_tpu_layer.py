"""TPU device-class tests: node detection, slice-aware planning, libtpu
DaemonSet management. Pure control-plane — no JAX needed."""


from k8s_operator_libs_tpu.api import DriverUpgradePolicySpec
from k8s_operator_libs_tpu.kube import DaemonSet, FakeCluster
from k8s_operator_libs_tpu.kube.sim import DaemonSetSimulator
from k8s_operator_libs_tpu.parallel.topology import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
)
from k8s_operator_libs_tpu.tpu import (
    IciHealthGate,
    LibtpuDaemonSetManager,
    LibtpuSpec,
    TpuNodeDetector,
    enable_slice_aware_planning,
)
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    TaskRunner,
    UpgradeKeys,
)
from k8s_operator_libs_tpu.utils import IntOrString
from builders import make_node

DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
NS = "driver-ns"
LABELS = {"app": "driver"}


def tpu_labels(pool: str, topology: str = "4x4") -> dict[str, str]:
    return {
        GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
        GKE_TPU_TOPOLOGY_LABEL: topology,
        GKE_NODEPOOL_LABEL: pool,
    }


class TestDetector:
    def test_detects_v5e_node(self):
        node = make_node("n1", labels=tpu_labels("pool-a"))
        det = TpuNodeDetector()
        assert det.is_tpu_node(node)
        info = det.detect(node)
        assert info is not None
        assert info.slice_id == "pool-a"
        assert info.topology.total_chips == 16
        assert info.topology.num_hosts == 4

    def test_non_tpu_node(self):
        node = make_node("n1", labels={"foo": "bar"})
        det = TpuNodeDetector()
        assert not det.is_tpu_node(node)
        assert det.detect(node) is None

    def test_explicit_slice_label_wins(self):
        labels = tpu_labels("pool-a")
        labels["tpu-operator.dev/slice-id"] = "slice-7"
        info = TpuNodeDetector().detect(make_node("n1", labels=labels))
        assert info.slice_id == "slice-7"

    def test_group_by_slice(self):
        det = TpuNodeDetector()
        nodes = [
            make_node("a0", labels=tpu_labels("pool-a")),
            make_node("a1", labels=tpu_labels("pool-a")),
            make_node("b0", labels=tpu_labels("pool-b")),
            make_node("plain"),
        ]
        groups = det.group_by_slice(nodes)
        assert {k: len(v) for k, v in groups.items()} == {
            "pool-a": 2, "pool-b": 1, "plain": 1,
        }

    def test_unknown_accelerator_still_tpu(self):
        node = make_node(
            "n1",
            labels={
                GKE_TPU_ACCELERATOR_LABEL: "tpu-v9-hyperslice",
                GKE_TPU_TOPOLOGY_LABEL: "2x2",
            },
        )
        info = TpuNodeDetector().detect(node)
        assert info is not None
        assert info.topology.total_chips == 4
        # The unknown generation label is preserved verbatim, not mapped to
        # some known generation.
        assert info.topology.accelerator == "tpu-v9-hyperslice"


def make_tpu_harness(pools, node_states=None):
    """pools: dict slice_id -> node count. All nodes host driver pods."""
    cluster = FakeCluster()
    idx = 0
    for pool, count in pools.items():
        for i in range(count):
            labels = tpu_labels(pool, topology="2x2")
            if node_states and node_states.get(f"{pool}-{i}"):
                labels[KEYS.state_label] = node_states[f"{pool}-{i}"]
            cluster.create(make_node(f"{pool}-{i}", labels=labels))
            idx += 1
    sim = DaemonSetSimulator(cluster, name="driver", namespace=NS, match_labels=LABELS)
    sim.settle()
    mgr = ClusterUpgradeStateManager(cluster, DEVICE, runner=TaskRunner(inline=True))
    enable_slice_aware_planning(mgr)
    return cluster, sim, mgr


def states(cluster):
    return {
        n.name: n.labels.get(KEYS.state_label, "") for n in cluster.list("Node")
    }


class TestSliceAwarePlanner:
    def test_whole_slice_starts_together(self):
        cluster, sim, mgr = make_tpu_harness({"pool-a": 2, "pool-b": 2})
        sim.set_template_hash("rev-2")
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=1,
            max_unavailable=IntOrString(1),
        )
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)  # unknown->required
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)  # slice selection
        st = states(cluster)
        # Exactly ONE slice (both its nodes) moved to cordon-required.
        cordoned_pools = {
            name.rsplit("-", 1)[0]
            for name, s in st.items() if s == "cordon-required"
        }
        assert len(cordoned_pools) == 1
        pool = cordoned_pools.pop()
        assert st[f"{pool}-0"] == "cordon-required"
        assert st[f"{pool}-1"] == "cordon-required"

    def test_budget_counts_slices_not_nodes(self):
        # 2 slices of 2 nodes; maxUnavailable=1 (slice!) must allow both
        # nodes of one slice at once but never touch the second slice.
        cluster, sim, mgr = make_tpu_harness({"pool-a": 2, "pool-b": 2})
        sim.set_template_hash("rev-2")
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=0,
            max_unavailable=IntOrString(1),
        )
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)
        st = states(cluster)
        pools_started = {
            name.rsplit("-", 1)[0]
            for name, s in st.items() if s == "cordon-required"
        }
        assert len(pools_started) == 1

    def test_disrupted_slice_preferred(self):
        cluster, sim, mgr = make_tpu_harness({"pool-a": 2, "pool-b": 2})
        # pool-b already has one cordoned node -> its slice is disrupted.
        cluster.patch("Node", "pool-b-0", patch={"spec": {"unschedulable": True}})
        sim.set_template_hash("rev-2")
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=1,
            max_unavailable=IntOrString(1),
        )
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)
        st = states(cluster)
        # The disrupted slice proceeds (even though budget is consumed by
        # its own unavailability); the healthy slice waits.
        assert st["pool-b-0"] == "cordon-required"
        assert st["pool-b-1"] == "cordon-required"
        assert st["pool-a-0"] == "upgrade-required"
        assert st["pool-a-1"] == "upgrade-required"

    def test_full_roll_one_slice_at_a_time(self):
        cluster, sim, mgr = make_tpu_harness({"pool-a": 2, "pool-b": 2})
        sim.set_template_hash("rev-2")
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=1,
            max_unavailable=IntOrString(1),
        )
        det = TpuNodeDetector()
        max_disrupted_slices = 0
        for _ in range(40):
            sim.step()
            mgr.apply_state(mgr.build_state(NS, LABELS), policy)
            sim.step()
            # Count disrupted slices (any node cordoned/not-ready).
            groups = det.group_by_slice(
                [type(n)(n.raw) for n in cluster.list("Node")]
            )
            disrupted = sum(
                1 for nodes in groups.values()
                if any(n.raw["spec"].get("unschedulable") for n in nodes)
            )
            max_disrupted_slices = max(max_disrupted_slices, disrupted)
            if all(s == "upgrade-done" for s in states(cluster).values()):
                break
        assert all(s == "upgrade-done" for s in states(cluster).values())
        assert max_disrupted_slices == 1
        assert sim.all_pods_ready_and_current()

    def test_unlimited_parallel_still_respects_slice_budget(self):
        # Regression: with max_parallel_upgrades=0 (unlimited) the budget
        # clamp must count slices that are committed to the pipeline
        # (cordon-required label written, cordon not yet landed) as
        # disrupted. Before the fix, pass N started slice A, pass N+1 saw
        # unavailable_slices empty and started slice B — two slices down
        # at once under maxUnavailable=1.
        cluster, sim, mgr = make_tpu_harness(
            {"pool-a": 2, "pool-b": 2, "pool-c": 2}
        )
        sim.set_template_hash("rev-2")
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=0,
            max_unavailable=IntOrString(1),
        )
        max_pipeline_slices = 0
        for _ in range(60):
            sim.step()
            mgr.apply_state(mgr.build_state(NS, LABELS), policy)
            sim.step()
            st = states(cluster)
            in_pipeline = {
                name.rsplit("-", 1)[0]
                for name, s in st.items()
                if s not in ("", "upgrade-done", "upgrade-required")
            }
            max_pipeline_slices = max(max_pipeline_slices, len(in_pipeline))
            if all(s == "upgrade-done" for s in st.values()):
                break
        assert all(s == "upgrade-done" for s in states(cluster).values())
        assert max_pipeline_slices == 1
        assert sim.all_pods_ready_and_current()

    def test_non_tpu_nodes_degrade_to_per_node(self):
        cluster, sim, mgr = make_tpu_harness({})
        for i in range(3):
            cluster.create(make_node(f"plain-{i}"))
        sim.settle()  # pods land at the current revision first
        sim.set_template_hash("rev-2")  # ...then go stale
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=1,
            max_unavailable=IntOrString(1),
        )
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)
        mgr.apply_state(mgr.build_state(NS, LABELS), policy)
        st = states(cluster)
        assert list(st.values()).count("cordon-required") == 1


class TestLibtpuDaemonSet:
    def test_build_shape(self):
        spec = LibtpuSpec(version="1.2.3")
        mgr = LibtpuDaemonSetManager(FakeCluster(), spec)
        ds = mgr.build_daemonset()
        tmpl = ds.spec["template"]["spec"]
        assert tmpl["initContainers"][0]["name"] == "safe-load-gate"
        assert KEYS.safe_driver_load_annotation in " ".join(
            tmpl["initContainers"][0]["command"]
        )
        assert any(
            t.get("key") == "google.com/tpu" for t in tmpl["tolerations"]
        )
        sel = tmpl["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"][0]["matchExpressions"][0]
        assert sel["key"] == GKE_TPU_ACCELERATOR_LABEL
        assert ds.spec["template"]["metadata"]["labels"]["version"] == "1.2.3"

    def test_apply_create_then_update(self):
        cluster = FakeCluster()
        mgr = LibtpuDaemonSetManager(cluster, LibtpuSpec(version="1.0.0"))
        ds1 = mgr.apply()
        assert ds1.uid
        mgr2 = LibtpuDaemonSetManager(cluster, LibtpuSpec(version="2.0.0"))
        ds2 = mgr2.apply()
        assert ds2.uid == ds1.uid  # updated, not recreated
        stored = DaemonSet(
            cluster.get("DaemonSet", mgr2.name, "kube-system").raw
        )
        assert stored.spec["template"]["metadata"]["labels"]["version"] == "2.0.0"

    def test_disable_safe_load(self):
        spec = LibtpuSpec(version="1.0.0", enable_safe_load=False)
        ds = LibtpuDaemonSetManager(FakeCluster(), spec).build_daemonset()
        assert ds.spec["template"]["spec"]["initContainers"] == []

    def test_delete(self):
        cluster = FakeCluster()
        mgr = LibtpuDaemonSetManager(cluster, LibtpuSpec(version="1.0.0"))
        mgr.apply()
        assert mgr.delete() is True
        assert mgr.delete() is False


class TestCalibratedFloors:
    """VERDICT item 7: the gate's perf floors are armed by default for the
    TPU device class, calibrated from real-v5e measurements (health.py
    TPU_DEFAULT_*), and a throttled probe fails validation."""

    def test_tpu_defaults_arm_floors_and_kernels(self):
        from k8s_operator_libs_tpu.tpu.health import (
            TPU_DEFAULT_MIN_MXU_TFLOPS,
            TPU_DEFAULT_MIN_RING_GBYTES_PER_S,
        )

        gate = IciHealthGate.tpu_defaults()
        assert gate.min_mxu_tflops == TPU_DEFAULT_MIN_MXU_TFLOPS > 0
        assert (
            gate.min_ring_gbytes_per_s
            == TPU_DEFAULT_MIN_RING_GBYTES_PER_S
            > 0
        )
        assert gate.use_pallas_matmul and gate.run_flash_attention
        # Deep-fabric ring/ulysses probes are on by default (run() skips
        # them, logged, on single-device meshes).
        assert gate.run_seq_parallel_probes
        # Overrides win (per-device-class retuning).
        assert IciHealthGate.tpu_defaults(min_mxu_tflops=7.5).min_mxu_tflops == 7.5
        assert not IciHealthGate.tpu_defaults(
            run_seq_parallel_probes=False
        ).run_seq_parallel_probes

    def test_throttled_mxu_fails_the_gate(self):
        import jax

        gate = IciHealthGate(
            min_mxu_tflops=1e9,  # no real device reaches this: "throttled"
            payload_mb=0.05,
            matmul_size=64,
            run_burnin=False,
        )
        report = gate.run()
        assert not report.ok
        assert any("below floor" in f for f in report.failures)

    def test_throttled_ring_fails_the_gate_on_multi_device(self):
        gate = IciHealthGate(
            min_ring_gbytes_per_s=1e9,
            payload_mb=0.05,
            matmul_size=64,
            run_burnin=False,
        )
        report = gate.run()  # conftest: 8 virtual devices → links exist
        assert not report.ok
        assert any("ring bandwidth" in f and "below floor" in f for f in report.failures)

    def test_ring_floor_vacuous_on_single_device(self):
        import jax

        gate = IciHealthGate(
            min_ring_gbytes_per_s=1e9,
            payload_mb=0.05,
            matmul_size=64,
            run_burnin=False,
            devices=[jax.devices()[0]],  # no ICI links to gate
        )
        report = gate.run()
        assert not any("ring bandwidth" in f for f in report.failures)

    def test_validation_pod_serializes_armed_floors(self):
        from k8s_operator_libs_tpu.tpu import ValidationPodSpec
        from k8s_operator_libs_tpu.tpu.health import (
            TPU_DEFAULT_MIN_MXU_TFLOPS,
            TPU_DEFAULT_MIN_RING_GBYTES_PER_S,
        )

        cmd = ValidationPodSpec().probe_command()
        assert str(TPU_DEFAULT_MIN_MXU_TFLOPS) in cmd
        assert str(TPU_DEFAULT_MIN_RING_GBYTES_PER_S) in cmd


class TestSliceScopedGate:
    """Slice-granular memoization: one probe run admits the slice's other
    nodes, failures never cached, passes expire so one rollout's probes
    cannot vouch for the next rollout's driver."""

    class StubGate:
        def __init__(self, ok=True):
            self.ok = ok
            self.runs = 0

        def run(self):
            from k8s_operator_libs_tpu.tpu.health import HealthReport

            self.runs += 1
            return HealthReport(
                ok=self.ok, failures=[] if self.ok else ["stub failure"]
            )

    @staticmethod
    def slice_nodes(pool, n=2):
        return [
            make_node(f"{pool}-{i}", labels=tpu_labels(pool)) for i in range(n)
        ]

    def test_one_run_admits_whole_slice(self):
        from k8s_operator_libs_tpu.tpu import SliceScopedGate

        stub = self.StubGate(ok=True)
        hook = SliceScopedGate(stub).validation_hook()
        a, b = self.slice_nodes("pool-a")
        assert hook(a) and hook(b)
        assert stub.runs == 1  # second node served from the cached pass

    def test_distinct_slices_probe_separately(self):
        from k8s_operator_libs_tpu.tpu import SliceScopedGate

        stub = self.StubGate(ok=True)
        hook = SliceScopedGate(stub).validation_hook()
        (a,) = self.slice_nodes("pool-a", 1)
        (b,) = self.slice_nodes("pool-b", 1)
        assert hook(a) and hook(b)
        assert stub.runs == 2

    def test_failures_never_cached(self):
        from k8s_operator_libs_tpu.tpu import SliceScopedGate

        stub = self.StubGate(ok=False)
        hook = SliceScopedGate(stub).validation_hook()
        a, b = self.slice_nodes("pool-a")
        assert not hook(a) and not hook(b)
        assert stub.runs == 2  # flapping link re-probed every pass

    def test_pass_expires_for_next_rollout(self):
        from k8s_operator_libs_tpu.tpu import SliceScopedGate

        stub = self.StubGate(ok=True)
        hook = SliceScopedGate(stub, max_age_seconds=0.0).validation_hook()
        a, _ = self.slice_nodes("pool-a")
        assert hook(a) and hook(a)
        assert stub.runs == 2  # expired immediately: re-probed

    def test_reset_clears_cached_passes(self):
        from k8s_operator_libs_tpu.tpu import SliceScopedGate

        stub = self.StubGate(ok=True)
        gate = SliceScopedGate(stub)
        hook = gate.validation_hook()
        a, _ = self.slice_nodes("pool-a")
        assert hook(a)
        gate.reset()  # rollout boundary
        assert hook(a)
        assert stub.runs == 2
