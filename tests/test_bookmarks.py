"""Watch bookmarks (``allowWatchBookmarks``).

client-go reflectors opt into periodic BOOKMARK events — objects
carrying only a fresh ``metadata.resourceVersion`` — so a QUIET watch
(e.g. selector-scoped, nothing matching for minutes) keeps a current
resume point while the shared event journal advances under it.
Without bookmarks, resuming such a watch from its last-seen revision
eventually answers 410 Gone and costs a full re-list. Pinned at the
FakeCluster generator, the HTTP wire, and the informer riding it.
"""

import threading
import time

from builders import make_node
from k8s_operator_libs_tpu.kube import (
    FakeCluster,
    Informer,
    LocalApiServer,
    RestClient,
    RestConfig,
)


def collect(watch_iter, deadline_s, want=1, types=("BOOKMARK",)):
    got = []
    deadline = time.monotonic() + deadline_s
    for event_type, obj in watch_iter:
        if event_type in types:
            got.append((event_type, obj))
            if len(got) >= want:
                break
        if time.monotonic() > deadline:
            break
    return got


class TestFakeClusterBookmarks:
    def test_quiet_watch_receives_fresh_resume_points(self):
        cluster = FakeCluster()
        cluster.create(make_node("bm-seed"))
        rv_at_start = cluster.current_resource_version()
        got = collect(
            cluster.watch(
                "Node",
                timeout_seconds=5,
                resource_version=rv_at_start,
                allow_bookmarks=True,
                bookmark_interval_s=0.1,
            ),
            deadline_s=5,
        )
        assert got, "no bookmark within the window"
        event_type, obj = got[0]
        assert event_type == "BOOKMARK"
        assert obj.raw["kind"] == "Node"
        assert obj.resource_version == rv_at_start  # current, no churn
        assert set(obj.raw["metadata"]) == {"resourceVersion"}

    def test_bookmarks_track_journal_advance(self):
        cluster = FakeCluster()
        cluster.create(make_node("bm-a"))
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                cluster.create(make_node(f"bm-churn-{i}"))
                i += 1
                time.sleep(0.02)

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            got = collect(
                cluster.watch(
                    "Node",
                    label_selector="app=never-matches",
                    timeout_seconds=5,
                    allow_bookmarks=True,
                    bookmark_interval_s=0.15,
                ),
                deadline_s=5,
                want=2,
            )
        finally:
            stop.set()
            t.join()
        assert len(got) == 2
        first, second = (int(o.resource_version) for _, o in got)
        assert second > first  # resume point moved with the journal

    def test_no_bookmarks_without_opt_in(self):
        cluster = FakeCluster()
        cluster.create(make_node("bm-quiet"))
        got = collect(
            cluster.watch("Node", timeout_seconds=1),
            deadline_s=1.5,
        )
        assert got == []


class TestWireBookmarks:
    def test_http_stream_interleaves_bookmarks(self):
        with LocalApiServer(bookmark_interval_s=0.15) as server:
            client = RestClient(RestConfig(server=server.url))
            try:
                server.cluster.create(make_node("bm-wire"))
                got = collect(
                    client.watch(
                        "Node", timeout_seconds=5, allow_bookmarks=True
                    ),
                    deadline_s=5,
                )
                assert got and got[0][0] == "BOOKMARK"
                assert got[0][1].raw["kind"] == "Node"
            finally:
                client.close()

    def test_plain_watch_never_sees_bookmarks(self):
        with LocalApiServer(bookmark_interval_s=0.1) as server:
            client = RestClient(RestConfig(server=server.url))
            try:
                server.cluster.create(make_node("bm-none"))
                got = collect(
                    client.watch("Node", timeout_seconds=1),
                    deadline_s=1.5,
                    types=("BOOKMARK",),
                )
                assert got == []
            finally:
                client.close()


class TestInformerRidesBookmarks:
    def test_quiet_scoped_informer_keeps_resume_point_fresh(self):
        with LocalApiServer(bookmark_interval_s=0.15) as server:
            client = RestClient(RestConfig(server=server.url))
            dispatched = []
            informer = Informer(
                client, "Node", label_selector="app=never-matches"
            )
            informer.add_event_handler(
                lambda t, obj, old: dispatched.append(t)
            )
            try:
                informer.start()
                assert informer.wait_for_sync(timeout=30)
                rv_after_sync = int(informer._resource_version)
                # Churn objects the selector never matches: the journal
                # advances, the informer sees zero events.
                for i in range(40):
                    server.cluster.create(make_node(f"bm-other-{i}"))
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    rv = int(informer._resource_version or 0)
                    if rv > rv_after_sync:
                        break
                    time.sleep(0.05)
                assert int(informer._resource_version) > rv_after_sync, (
                    "bookmark never refreshed the quiet informer's "
                    "resume point"
                )
                assert dispatched == []  # fresh WITHOUT any events
            finally:
                informer.stop()
                client.close()


class TestBookmarkOrdering:
    def test_bookmark_never_overtakes_undelivered_events(self):
        """The contract: a bookmark's rv promises every event up to it
        was already delivered. Stream events and bookmarks under churn
        and assert no bookmark carries an rv >= a later-delivered
        event's rv."""
        cluster = FakeCluster()
        cluster.create(make_node("bm-order-seed"))
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                cluster.create(make_node(f"bm-order-{i}"))
                i += 1
                time.sleep(0.01)

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            seen = []  # (type, rv) in delivery order
            deadline = time.monotonic() + 4
            for event_type, obj in cluster.watch(
                "Node",
                timeout_seconds=5,
                allow_bookmarks=True,
                bookmark_interval_s=0.05,
            ):
                rv = int(obj.resource_version)
                seen.append((event_type, rv))
                if time.monotonic() > deadline:
                    break
        finally:
            stop.set()
            t.join()
        bookmarks = [i for i, (t_, _) in enumerate(seen) if t_ == "BOOKMARK"]
        assert bookmarks, "churn starved every bookmark out of the window"
        for i in bookmarks:
            _, bm_rv = seen[i]
            later_events = [
                rv for t_, rv in seen[i + 1:] if t_ != "BOOKMARK"
            ]
            assert all(rv > bm_rv for rv in later_events), (
                f"bookmark rv={bm_rv} overtook undelivered events "
                f"{[rv for rv in later_events if rv <= bm_rv]}"
            )
