"""Tests for the domain-aware static analyzer (tools/analyze/).

Each pass must catch its seeded violation in tests/analyze_fixtures/ and
stay silent on the clean twin; plus the framework behaviors the gate
depends on: targeted noqa, the baseline lifecycle, JSON output, exit
codes — and the acceptance criterion itself: the real package is clean
under the checked-in baseline.
"""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "analyze_fixtures"

sys.path.insert(0, str(REPO / "tools"))

from analyze import cli  # noqa: E402
from analyze.baseline import load_baseline, split_findings  # noqa: E402
from analyze.core import parse_noqa, run_analysis, suppressed  # noqa: E402


def codes(findings):
    return {f.code for f in findings}


# -- lock discipline -------------------------------------------------------

def test_lock_pass_flags_seeded_violations():
    findings = run_analysis([str(FIXTURES / "lock_bad.py")])
    assert codes(findings) == {"LCK101", "LCK102"}
    lck101 = [f for f in findings if f.code == "LCK101"]
    # _count unguarded in reset(), _last unguarded in touch().
    assert len(lck101) == 2
    assert {"_count" in f.message or "_last" in f.message for f in lck101} == {True}
    lck102 = [f for f in findings if f.code == "LCK102"]
    assert len(lck102) == 2
    reasons = " ".join(f.message for f in lck102)
    assert "time.sleep" in reasons and "join" in reasons


def test_lock_pass_silent_on_clean_twin():
    assert run_analysis([str(FIXTURES / "lock_clean.py")]) == []


# -- state machine ---------------------------------------------------------

def test_state_machine_pass_flags_all_seeded_violations():
    findings = run_analysis([str(FIXTURES / "sm_bad")])
    got = codes(findings)
    assert {"STM201", "STM202", "STM203", "STM204", "STM205"} <= got
    unpartitioned = [f for f in findings if f.code == "STM201"]
    assert len(unpartitioned) == 2  # RETIRED and LOST
    unhandled = [f for f in findings if f.code == "STM203"]
    # CHECKPOINTING is the ISSUE 6 twin and QUARANTINED the ISSUE 8 twin:
    # correctly partitioned, but the orchestrator ships no handler — the
    # deliberately-missing arc for each machine-growing PR.
    assert {
        m
        for f in unhandled
        for m in ("JAMMED", "RETIRED", "LOST", "CHECKPOINTING",
                  "QUARANTINED")
        if m in f.message
    } == {"JAMMED", "RETIRED", "LOST", "CHECKPOINTING", "QUARANTINED"}
    stale = [f for f in findings if f.code == "STM204"]
    assert len(stale) == 1 and "process_melted_nodes" in stale[0].message
    literal = [f for f in findings if f.code == "STM205"]
    assert len(literal) == 1 and "widget-jammed" in literal[0].message


def test_state_machine_pass_silent_on_clean_twin():
    assert run_analysis([str(FIXTURES / "sm_clean")]) == []


def test_real_upgrade_machine_is_exhaustive():
    """The production state machine itself satisfies the invariants —
    15 states (13 reference states + checkpoint-required + quarantined)
    partitioned and handled. Regresses loudly if a state is added
    without a handler or partition slot."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu" / "upgrade")],
        pass_names=["state-machine"],
    )
    assert findings == [], [str(f) for f in findings]


# -- literal keys ----------------------------------------------------------

def test_literal_key_pass_flags_seeded_violations():
    findings = run_analysis([str(FIXTURES / "key_bad.py")])
    assert codes(findings) == {"KEY301"}
    assert len(findings) == 2


def test_literal_key_pass_silent_on_clean_twin_and_honors_noqa():
    # key_clean.py contains an upgrade-shaped literal under # noqa: KEY301
    # and an other-namespace key; both must stay silent.
    assert run_analysis([str(FIXTURES / "key_clean.py")]) == []


# -- swallowed exceptions --------------------------------------------------

def test_swallowed_pass_flags_seeded_violation():
    findings = run_analysis([str(FIXTURES / "swallow_bad.py")])
    assert codes(findings) == {"EXC401"}
    assert len(findings) == 1
    assert findings[0].scope == "reconcile"


def test_swallowed_pass_silent_on_clean_twin():
    # Logging, error-as-data, import-fallback and narrow handlers are all
    # legitimate shapes.
    assert run_analysis([str(FIXTURES / "swallow_clean.py")]) == []


# -- framework: noqa grammar ----------------------------------------------

def test_parse_noqa_grammar():
    noqa = parse_noqa(
        "x = 1  # noqa\n"
        "y = 2  # noqa: LCK101\n"
        "z = 3  # noqa: LCK101, EXC401\n"
        "w = 4\n"
    )
    assert suppressed(noqa, 1, "ANY999")
    assert suppressed(noqa, 2, "LCK101") and not suppressed(noqa, 2, "EXC401")
    assert suppressed(noqa, 3, "EXC401")
    assert not suppressed(noqa, 4, "LCK101")


def test_parse_noqa_stops_at_prose():
    # Trailing prose after the code list must not widen the suppression
    # to rule codes it merely mentions.
    noqa = parse_noqa(
        "x = 1  # noqa: E501 long url, see E722 docs\n"
        "y = 2  # noqa: BLE001 - the monitor must outlive blips\n"
    )
    assert suppressed(noqa, 1, "E501") and not suppressed(noqa, 1, "E722")
    assert suppressed(noqa, 2, "BLE001")


def test_parse_noqa_malformed_codes_suppress_nothing():
    # `# noqa: keep` (unparseable code list) must NOT degrade to a
    # blanket suppression — the finding surfaces and the typo gets
    # fixed.
    noqa = parse_noqa(
        "x = 1  # noqa: somereason\n"
        "y = 2  # noqa: KEY-301\n"
        "z = 3  # noqa\n"
    )
    assert not suppressed(noqa, 1, "LCK101")
    assert not suppressed(noqa, 2, "KEY301")
    assert suppressed(noqa, 3, "ANY999")  # bare blanket still works


def test_cli_select_run_does_not_report_unselected_stale(tmp_path, capsys):
    # Baseline an EXC401, then run ONLY the lock pass over the same file:
    # the EXC401 entry is out of the run's scope, not "fixed".
    baseline = tmp_path / "b.json"
    target = str(FIXTURES / "swallow_bad.py")
    cli.main([target, "--baseline", str(baseline), "--write-baseline"])
    rc = cli.main([target, "--baseline", str(baseline),
                   "--select", "lock-discipline"])
    err = capsys.readouterr().err
    assert rc == 0
    assert "fixed? remove it" not in err


def test_lock_pass_accepts_local_lock_alias(tmp_path):
    mod = tmp_path / "alias.py"
    mod.write_text(
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0\n"
        "\n"
        "    def guarded(self):\n"
        "        with self._lock:\n"
        "            self._x = 1\n"
        "\n"
        "    def alias_guarded(self):\n"
        "        lock = self._lock\n"
        "        with lock:\n"
        "            self._x = 2\n"
    )
    assert run_analysis([str(mod)]) == []


def test_cli_rejects_nonexistent_file_argument(capsys):
    rc = cli.main([str(FIXTURES / "no_such_file.py"), "--baseline", "-"])
    assert rc == 2
    capsys.readouterr()


def test_cli_subset_run_does_not_report_out_of_scope_stale(tmp_path, capsys):
    # Baseline an EXC401 in swallow_bad.py, then analyze ONLY the clean
    # twin: the out-of-scope entry must not be called "fixed".
    baseline = tmp_path / "b.json"
    cli.main([str(FIXTURES / "swallow_bad.py"), "--baseline", str(baseline),
              "--write-baseline"])
    rc = cli.main([str(FIXTURES / "swallow_clean.py"),
                   "--baseline", str(baseline)])
    err = capsys.readouterr().err
    assert rc == 0
    assert "stale" not in err.split("\n")[0] or "0 stale" in err


def test_parse_noqa_ignores_string_literals():
    # 'noqa' inside a string (help text, a linter's own messages) is not
    # a directive — only real comments suppress.
    noqa = parse_noqa(
        'msg = "add # noqa: EXC401 to silence"\n'
        's = """\n'
        "# noqa\n"
        '"""\n'
        "y = 1  # noqa: EXC401\n"
    )
    assert not suppressed(noqa, 1, "EXC401")
    assert not suppressed(noqa, 3, "ANY")
    assert suppressed(noqa, 5, "EXC401")


# -- framework: baseline lifecycle ----------------------------------------

def test_baseline_roundtrip(tmp_path):
    baseline = tmp_path / "baseline.json"
    target = str(FIXTURES / "swallow_bad.py")
    assert cli.main([target, "--baseline", str(baseline)]) == 1
    assert cli.main([target, "--baseline", str(baseline),
                     "--write-baseline"]) == 0
    # Baselined: the gate is green while the finding stays recorded.
    assert cli.main([target, "--baseline", str(baseline)]) == 0
    entries = load_baseline(baseline)
    assert len(entries) == 1


def test_baseline_reports_stale_entries(tmp_path):
    baseline = tmp_path / "baseline.json"
    bad = str(FIXTURES / "swallow_bad.py")
    clean = str(FIXTURES / "swallow_clean.py")
    cli.main([bad, "--baseline", str(baseline), "--write-baseline"])
    findings = run_analysis([clean])
    new, baselined, stale = split_findings(findings, load_baseline(baseline))
    assert new == [] and baselined == [] and len(stale) == 1


def test_baseline_fingerprints_distinguish_scopes():
    findings = run_analysis([str(FIXTURES / "lock_bad.py")])
    prints = {f.fingerprint() for f in findings}
    assert len(prints) == len(findings)  # no two findings collapse


def test_baseline_fingerprints_distinguish_repeats_in_one_scope(tmp_path):
    # A SECOND identical violation in an already-baselined scope must not
    # be absorbed by the first one's justification.
    one = tmp_path / "one.py"
    one.write_text(
        "def reconcile(c):\n"
        "    try:\n"
        "        c.sync()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    findings_one = run_analysis([str(one)])
    assert len(findings_one) == 1
    two = tmp_path / "one.py"  # same path: simulate the edit
    two.write_text(
        "def reconcile(c):\n"
        "    try:\n"
        "        c.sync()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        c.flush()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    findings_two = run_analysis([str(two)])
    assert len(findings_two) == 2
    prints = {f.fingerprint() for f in findings_two}
    assert len(prints) == 2
    # The first occurrence keeps its original fingerprint (the baseline
    # entry stays valid); only the new one is unmatched.
    assert findings_one[0].fingerprint() in prints


def test_literal_key_pass_covers_modules_with_unrelated_key_helpers(tmp_path):
    # A `_key` helper alone (FakeCluster/Informer shape) must NOT exempt
    # a module from KEY301 — only the full builder shape does.
    mod = tmp_path / "fakeish.py"
    mod.write_text(
        "class FakeCluster:\n"
        "    def _key(self, kind, ns, name):\n"
        "        return (kind, ns, name)\n"
        "\n"
        'LABEL = "acme.dev/widget-driver-upgrade-state"\n'
    )
    findings = run_analysis([str(mod)], pass_names=["literal-key"])
    assert [f.code for f in findings] == ["KEY301"]


def test_state_machine_allows_two_handlers_for_one_state(tmp_path):
    # Splitting one state's processing across two mapped calls is not
    # staleness.
    pkg = tmp_path / "sm"
    pkg.mkdir()
    (pkg / "consts.py").write_text(
        "from enum import Enum\n\n\n"
        "class FooState(str, Enum):\n"
        '    DRAIN_REQUIRED = "foo-drain-required"\n'
        "\n\n"
        "MANAGED_STATES = (FooState.DRAIN_REQUIRED,)\n"
        "MAINTENANCE_STATES = ()\n"
    )
    (pkg / "manager.py").write_text(
        "class M:\n"
        "    def apply_state(self, state):\n"
        "        self.process_drain_nodes(state)\n"
        "        self.process_drain_timeout_nodes(state)\n"
    )
    findings = run_analysis([str(pkg)], pass_names=["state-machine"])
    assert findings == [], [str(f) for f in findings]


# -- framework: CLI behaviors ---------------------------------------------

def test_cli_text_output(capsys):
    rc = cli.main([str(FIXTURES / "swallow_bad.py"), "--baseline", "-"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "EXC401" in out and "swallow_bad.py:" in out


def test_cli_json_report_and_output_file(tmp_path, capsys):
    report_file = tmp_path / "report.json"
    rc = cli.main([
        str(FIXTURES / "swallow_bad.py"), "--json", "--baseline", "-",
        "--output", str(report_file),
    ])
    assert rc == 1
    printed = json.loads(capsys.readouterr().out)
    on_disk = json.loads(report_file.read_text())
    assert printed == on_disk
    assert on_disk["counts"] == {
        "new": 1, "baselined": 0, "stale_baseline_entries": 0,
    }
    finding = on_disk["findings"][0]
    assert finding["code"] == "EXC401" and finding["scope"] == "reconcile"


def test_cli_select_single_pass():
    rc_all = run_analysis([str(FIXTURES / "lock_bad.py")],
                          pass_names=["swallowed-exception"])
    assert rc_all == []  # the lock violations are another pass's


def test_cli_fails_loudly_when_gate_would_be_off(tmp_path, capsys):
    # A mistyped path or pass name must not print "clean" and exit 0 —
    # that is the gate silently turning itself off.
    assert cli.main([str(tmp_path / "no_such_dir"), "--baseline", "-"]) == 2
    assert cli.main([str(FIXTURES / "lock_bad.py"), "--baseline", "-",
                     "--select", "lockdiscipline-typo"]) == 2
    capsys.readouterr()


def test_write_baseline_keeps_out_of_scope_entries(tmp_path):
    # A subset --write-baseline must not drop suppressions it could not
    # have re-observed.
    baseline = tmp_path / "b.json"
    cli.main([str(FIXTURES / "swallow_bad.py"), "--baseline", str(baseline),
              "--write-baseline"])
    cli.main([str(FIXTURES / "lock_bad.py"), "--baseline", str(baseline),
              "--write-baseline"])
    entries = load_baseline(baseline)
    assert any("EXC401" in fp for fp in entries)  # survived the 2nd write
    assert any("LCK101" in fp for fp in entries)


# -- the acceptance criterion itself --------------------------------------

def test_package_gate_is_clean_via_entrypoint():
    """`python tools/analyze.py k8s_operator_libs_tpu tools/chaos_run.py
    tools/trace_view.py` (what make lint and CI run — the chaos driver
    and flight recorder are in scope since ISSUE 15) exits 0 against
    the checked-in baseline."""
    proc = subprocess.run(
        [sys.executable, "tools/analyze.py", "k8s_operator_libs_tpu",
         "tools/chaos_run.py", "tools/trace_view.py"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- lock-order (LCK110) ---------------------------------------------------

def test_lock_order_flags_seeded_cycle():
    findings = run_analysis([str(FIXTURES / "deadlock_bad.py")])
    assert codes(findings) == {"LCK110"}
    assert len(findings) == 1
    msg = findings[0].message
    assert "Cache._lock" in msg and "Queue._lock" in msg
    # Every edge of the cycle carries its witness call chain.
    assert "Cache.refresh -> Queue.requeue_all" in msg
    assert "Queue.drop -> Cache.invalidate" in msg


def test_lock_order_silent_on_clean_twin():
    assert run_analysis([str(FIXTURES / "deadlock_clean.py")]) == []


def test_lock_order_self_deadlock_through_call(tmp_path):
    # A plain Lock re-acquired via a helper is a self-deadlock; the
    # reentrant twin (RLock) is the sanctioned idiom and stays silent.
    bad = tmp_path / "self_deadlock.py"
    bad.write_text(
        "import threading\n\n\n"
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._bump_two()\n"
        "\n"
        "    def _bump_two(self):\n"
        "        with self._lock:\n"
        "            self._n += 2\n"
    )
    findings = run_analysis([str(bad)])
    assert codes(findings) == {"LCK110"}
    assert "Counter._lock -> Counter._lock" in findings[0].message
    good = tmp_path / "self_reentrant.py"
    good.write_text(bad.read_text().replace("threading.Lock()",
                                            "threading.RLock()"))
    assert run_analysis([str(good)]) == []


def test_lock_order_module_level_lock_identity(tmp_path):
    # A cycle between a module-level lock and a class lock, each edge
    # crossing a function boundary.
    mod = tmp_path / "registry.py"
    mod.write_text(
        "import threading\n\n"
        "_REGISTRY_LOCK = threading.Lock()\n\n\n"
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    def grow(self):\n"
        "        with self._lock:\n"
        "            register(self)\n"
        "\n"
        "    def audit(self):\n"
        "        with _REGISTRY_LOCK:\n"
        "            self.reap()\n"
        "\n"
        "    def reap(self):\n"
        "        with self._lock:\n"
        "            pass\n"
        "\n\n"
        'def register(pool: "Pool"):\n'
        "    with _REGISTRY_LOCK:\n"
        "        pass\n"
    )
    findings = run_analysis([str(mod)])
    assert codes(findings) == {"LCK110"}
    assert len(findings) == 1
    assert "_REGISTRY_LOCK" in findings[0].message
    assert "Pool._lock" in findings[0].message


def test_condition_alias_shares_lock_identity(tmp_path):
    # Condition(self._lock) IS self._lock for ordering purposes: nesting
    # them is the fake-apiserver idiom, not an inversion.
    mod = tmp_path / "journal.py"
    mod.write_text(
        "import threading\n\n\n"
        "class Journal:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "        self._changed = threading.Condition(self._lock)\n"
        "\n"
        "    def append(self, item):\n"
        "        with self._lock:\n"
        "            with self._changed:\n"
        "                self._changed.notify_all()\n"
    )
    assert run_analysis([str(mod)]) == []


def test_package_lock_graph_is_acyclic():
    """The production lock graph (KeyedMutex -> client/cluster locks,
    Informer dispatch -> store) must stay a DAG. Regresses loudly if a
    cross-module inversion is introduced."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu")], pass_names=["lock-order"]
    )
    assert findings == [], [str(f) for f in findings]


# -- transitive blocking (LCK111) ------------------------------------------

def test_blocking_chain_flags_seeded_violation():
    findings = run_analysis([str(FIXTURES / "chain_bad.py")])
    assert codes(findings) == {"LCK111"}
    assert len(findings) == 1
    msg = findings[0].message
    assert "time.sleep" in msg
    assert "Poller._refresh -> Poller._fetch -> Poller._backoff" in msg
    assert "Poller._lock" in msg


def test_blocking_chain_silent_on_clean_twin():
    assert run_analysis([str(FIXTURES / "chain_clean.py")]) == []


def test_keyed_mutex_direct_blocking_reported(tmp_path):
    # Blocking under a keyed mutex is invisible to LCK102 (no lock
    # attribute involved) — LCK111 owns it, with the keyed identity.
    mod = tmp_path / "keyed.py"
    mod.write_text(
        "import threading\n"
        "import time\n"
        "from contextlib import contextmanager\n\n\n"
        "class KeyedMutex:\n"
        "    def __init__(self):\n"
        "        self._guard = threading.Lock()\n"
        "        self._locks = {}\n"
        "\n"
        "    @contextmanager\n"
        "    def locked(self, key):\n"
        "        with self._guard:\n"
        "            lock = self._locks.setdefault(key, threading.Lock())\n"
        "        lock.acquire()\n"
        "        try:\n"
        "            yield\n"
        "        finally:\n"
        "            lock.release()\n"
        "\n\n"
        "class Writer:\n"
        "    def __init__(self):\n"
        "        self._mutex = KeyedMutex()\n"
        "\n"
        "    def write(self, key):\n"
        "        with self._mutex.locked(key):\n"
        "            time.sleep(0.01)\n"
    )
    findings = run_analysis([str(mod)])
    assert codes(findings) == {"LCK111"}
    assert "KeyedMutex[Writer._mutex]" in findings[0].message


def test_batch_flush_under_keyed_mutex_flagged():
    """The write-batching discipline (docs/reconcile-data-path.md, "The
    write path"): a batch flush reachable inside the per-node keyed
    mutex is an LCK111 with the keyed identity — the exact regression
    the provider's split critical section prevents."""
    findings = run_analysis([str(FIXTURES / "batch_bad.py")])
    assert codes(findings) == {"LCK111"}
    assert len(findings) == 1
    msg = findings[0].message
    assert "Batcher.stage -> Batcher._flush" in msg
    assert "KeyedMutex[BadBatchedWriter._mutex]" in msg


def test_batch_flush_outside_keyed_mutex_clean():
    """The sanctioned shape — optimistic apply under the mutex, flush
    outside, bookkeeping rejoin back under it — stays silent."""
    assert run_analysis([str(FIXTURES / "batch_clean.py")]) == []


def test_package_transitive_blocking_all_baselined():
    """Every LCK111 the package produces today is the state provider's
    deliberate hold-the-keyed-mutex-across-the-write contract — each is
    baselined with a written justification, and nothing else fires."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu")],
        pass_names=["blocking-transitive"],
    )
    assert findings, "the deliberate state-provider holds disappeared?"
    assert all(f.path.endswith("upgrade/state_provider.py")
               for f in findings), [str(f) for f in findings]
    baseline = load_baseline(REPO / "tools" / "analyze_baseline.json")
    for f in findings:
        # The baseline stores repo-relative fingerprints (make/CI run
        # from the repo root); strip this run's absolute prefix.
        fingerprint = f.fingerprint().replace(f"{REPO}/", "", 1)
        assert fingerprint in baseline, fingerprint
        assert len(baseline[fingerprint]) > 40  # a real justification


# -- call-graph resolution edge cases --------------------------------------

def _lck111_codes(tmp_path, source: str):
    mod = tmp_path / "mod.py"
    mod.write_text(source)
    return run_analysis([str(mod)])


def test_callgraph_resolves_inherited_methods(tmp_path):
    findings = _lck111_codes(
        tmp_path,
        "import threading\n"
        "import time\n\n\n"
        "class Base:\n"
        "    def slow(self):\n"
        "        time.sleep(0.01)\n"
        "\n\n"
        "class Sub(Base):\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    def work(self):\n"
        "        with self._lock:\n"
        "            self.slow()\n",
    )
    assert codes(findings) == {"LCK111"}
    assert "Base.slow" in findings[0].message


def test_callgraph_dispatches_to_subclass_overrides(tmp_path):
    # A call through a base-typed attribute may land on ANY override at
    # runtime — the conservative model includes them all.
    findings = _lck111_codes(
        tmp_path,
        "import threading\n"
        "import time\n\n\n"
        "class Transport:\n"
        "    def send(self):\n"
        "        pass\n"
        "\n\n"
        "class SlowTransport(Transport):\n"
        "    def send(self):\n"
        "        time.sleep(0.01)\n"
        "\n\n"
        "class Mgr:\n"
        "    def __init__(self, transport: Transport):\n"
        "        self._lock = threading.Lock()\n"
        "        self._transport = transport\n"
        "\n"
        "    def flush(self):\n"
        "        with self._lock:\n"
        "            self._transport.send()\n",
    )
    assert codes(findings) == {"LCK111"}
    assert "SlowTransport.send" in findings[0].message


def test_callgraph_resolves_aliased_self_methods(tmp_path):
    findings = _lck111_codes(
        tmp_path,
        "import threading\n"
        "import time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    def run(self):\n"
        "        helper = self._helper\n"
        "        with self._lock:\n"
        "            helper()\n"
        "\n"
        "    def _helper(self):\n"
        "        time.sleep(0.01)\n",
    )
    assert codes(findings) == {"LCK111"}
    assert "C._helper" in findings[0].message


def test_callgraph_resolves_decorated_callees(tmp_path):
    findings = _lck111_codes(
        tmp_path,
        "import functools\n"
        "import threading\n"
        "import time\n\n\n"
        "def logged(fn):\n"
        "    @functools.wraps(fn)\n"
        "    def inner(*args, **kwargs):\n"
        "        return fn(*args, **kwargs)\n"
        "    return inner\n"
        "\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    @logged\n"
        "    def _helper(self):\n"
        "        time.sleep(0.01)\n"
        "\n"
        "    def run(self):\n"
        "        with self._lock:\n"
        "            self._helper()\n",
    )
    assert codes(findings) == {"LCK111"}


def test_callgraph_resolves_super_calls(tmp_path):
    findings = _lck111_codes(
        tmp_path,
        "import threading\n"
        "import time\n\n\n"
        "class Base:\n"
        "    def close(self):\n"
        "        time.sleep(0.01)\n"
        "\n\n"
        "class Sub(Base):\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    def close(self):\n"
        "        with self._lock:\n"
        "            super().close()\n",
    )
    assert codes(findings) == {"LCK111"}
    assert "Base.close" in findings[0].message


def test_callgraph_resolves_locked_convention_untyped(tmp_path):
    # An untyped receiver still resolves a *_locked call when the name
    # is defined exactly once project-wide; the helper's caller-holds
    # contract also puts ITS calls under the class lock.
    findings = _lck111_codes(
        tmp_path,
        "import threading\n"
        "import time\n\n"
        "_LOCK = threading.Lock()\n\n\n"
        "class Store:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    def _flush_locked(self):\n"
        "        self._io()\n"
        "\n"
        "    def _io(self):\n"
        "        time.sleep(0.01)\n"
        "\n\n"
        "def helper(store):\n"
        "    with _LOCK:\n"
        "        store._flush_locked()\n",
    )
    assert codes(findings) == {"LCK111"}
    messages = " | ".join(f.message for f in findings)
    assert "_LOCK" in messages  # untyped receiver resolved the helper
    assert "Store._lock" in messages  # caller-holds contract enforced


# -- dry-run purity (DRY501) -----------------------------------------------

def test_dryrun_flags_seeded_violations():
    findings = run_analysis([str(FIXTURES / "dryrun_bad.py")])
    assert codes(findings) == {"DRY501"}
    assert len(findings) == 3
    scopes = {f.scope for f in findings}
    assert scopes == {"NodeOps.cordon", "NodeOps.purge", "NodeOps.maintenance"}
    transitive = [f for f in findings if f.scope == "NodeOps.maintenance"]
    assert "NodeOps._wipe" in transitive[0].message


def test_dryrun_silent_on_clean_twin():
    assert run_analysis([str(FIXTURES / "dryrun_clean.py")]) == []


def test_dryrun_early_return_inside_with_cleans_tail(tmp_path):
    # The FakeCluster shape: `if dry_run: return` INSIDE a with block
    # makes everything after it (in and below the block) real-path-only.
    mod = tmp_path / "store.py"
    mod.write_text(
        "class Client:\n"
        "    def create(self, obj, dry_run=False):\n"
        "        ...\n"
        "\n\n"
        "class Store:\n"
        "    def __init__(self, client: Client):\n"
        "        self._client = client\n"
        "\n"
        "    def _tx(self):\n"
        "        return None\n"
        "\n"
        "    def write(self, obj, dry_run=False):\n"
        "        with self._tx():\n"
        "            if dry_run:\n"
        "                return None\n"
        "            self._client.create(obj)\n"
        "        return obj\n"
    )
    assert run_analysis([str(mod)]) == []


def test_dryrun_unlinked_query_dict_is_flagged(tmp_path):
    # The clean twin's query-dict idiom only counts when the dict is
    # actually derived from the taint.
    mod = tmp_path / "raw.py"
    mod.write_text(
        "class Client:\n"
        "    def _request(self, verb, path, query=None):\n"
        "        ...\n"
        "\n\n"
        "class Ops:\n"
        "    def __init__(self, client: Client):\n"
        "        self._client = client\n"
        "\n"
        "    def raw_write(self, path, dry_run=False):\n"
        "        query = {}\n"
        '        return self._client._request("POST", path, query=query)\n'
    )
    findings = run_analysis([str(mod)])
    assert codes(findings) == {"DRY501"}


def test_package_dryrun_layers_are_pure():
    """kube/{client,rest,drain,apiserver,fake,cache}.py forward the
    dry-run flag through every mutation on every tainted path."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu")], pass_names=["dryrun-purity"]
    )
    assert findings == [], [str(f) for f in findings]


# -- CLI: --stats and --sarif ----------------------------------------------

def test_cli_stats_line_and_json_stats(tmp_path, capsys):
    report_file = tmp_path / "report.json"
    rc = cli.main([
        str(FIXTURES / "chain_bad.py"), "--baseline", "-", "--stats",
        "--output", str(report_file),
    ])
    assert rc == 1
    err = capsys.readouterr().err
    line = next(ln for ln in err.splitlines()
                if ln.startswith("analyze stats:"))
    assert "files=1" in line and "functions=" in line
    assert "call_edges=" in line and "lock_sites=1" in line
    stats = json.loads(report_file.read_text())["stats"]
    assert stats["files"] == 1 and stats["findings"] == 1


def test_cli_sarif_output(tmp_path, capsys):
    sarif_file = tmp_path / "report.sarif"
    rc = cli.main([
        str(FIXTURES / "deadlock_bad.py"), "--baseline", "-",
        "--sarif", str(sarif_file),
    ])
    assert rc == 1
    capsys.readouterr()
    doc = json.loads(sarif_file.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"LCK110", "LCK111", "DRY501", "LCK101"} <= rule_ids
    result = run["results"][0]
    assert result["ruleId"] == "LCK110"
    location = result["locations"][0]["physicalLocation"]
    assert location["artifactLocation"]["uri"].endswith("deadlock_bad.py")
    assert location["region"]["startLine"] > 0
    assert "analyzeFingerprint/v1" in result["partialFingerprints"]


def test_cli_sarif_marks_baselined_as_suppressed(tmp_path, capsys):
    baseline = tmp_path / "b.json"
    target = str(FIXTURES / "swallow_bad.py")
    cli.main([target, "--baseline", str(baseline), "--write-baseline"])
    sarif_file = tmp_path / "report.sarif"
    rc = cli.main([target, "--baseline", str(baseline),
                   "--sarif", str(sarif_file)])
    assert rc == 0
    capsys.readouterr()
    results = json.loads(sarif_file.read_text())["runs"][0]["results"]
    assert len(results) == 1
    suppression = results[0]["suppressions"][0]
    assert suppression["kind"] == "external"
    assert suppression["justification"]


def test_dryrun_except_handler_keeps_entry_taint(tmp_path):
    # An exception can leave the try body while dry_run is True, so an
    # early `if dry_run: return` in the body must NOT clean the handler:
    # a mutation there still runs on the tainted path.
    mod = tmp_path / "handler.py"
    mod.write_text(
        "class Client:\n"
        "    def delete(self, kind, name, dry_run=False):\n"
        "        ...\n"
        "\n\n"
        "class Ops:\n"
        "    def __init__(self, client: Client):\n"
        "        self._client = client\n"
        "\n"
        "    def _prepare(self, name):\n"
        "        return name\n"
        "\n"
        "    def write(self, name, dry_run=False):\n"
        "        try:\n"
        "            self._prepare(name)\n"
        "            if dry_run:\n"
        "                return None\n"
        "            return name\n"
        "        except ValueError:\n"
        '            self._client.delete("Node", name)\n'
        "            raise\n"
    )
    findings = run_analysis([str(mod)])
    assert codes(findings) == {"DRY501"}
    assert len(findings) == 1


def test_lambda_bodies_do_not_inherit_lock_context(tmp_path):
    # A lambda stored under the lock runs at an unknown time, exactly
    # like a nested def — its body must not count as blocking-under-lock
    # (neither directly nor through the call graph).
    mod = tmp_path / "deferred.py"
    mod.write_text(
        "import threading\n"
        "import time\n\n\n"
        "class Registry:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._callbacks = {}\n"
        "\n"
        "    def _slow(self):\n"
        "        time.sleep(0.01)\n"
        "\n"
        "    def install(self):\n"
        "        with self._lock:\n"
        "            self._callbacks.update(\n"
        "                {'direct': lambda: time.sleep(1),\n"
        "                 'chained': lambda: self._slow()}\n"
        "            )\n"
    )
    assert run_analysis([str(mod)]) == []


def test_lambda_default_args_still_evaluate_under_lock(tmp_path):
    # Lambda BODIES are deferred, but default-argument expressions run
    # at definition time — a blocking default under the lock must still
    # be flagged (LCK102's pre-pruning behavior, kept).
    mod = tmp_path / "defaults.py"
    mod.write_text(
        "import threading\n"
        "import time\n\n\n"
        "class Registry:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cb = None\n"
        "\n"
        "    def install(self):\n"
        "        with self._lock:\n"
        "            self._cb = lambda t=time.sleep(1): t\n"
    )
    findings = run_analysis([str(mod)])
    assert codes(findings) == {"LCK102"}


def test_lck102_urlencode_under_lock_is_not_blocking(tmp_path):
    # urllib.parse is pure string work — the shared classifier's
    # carve-out must apply to LCK102 exactly as it does to LCK111.
    mod = tmp_path / "enc.py"
    mod.write_text(
        "import threading\n"
        "import urllib.parse\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = None\n"
        "\n"
        "    def encode(self, params):\n"
        "        with self._lock:\n"
        "            self._q = urllib.parse.urlencode(params)\n"
    )
    assert run_analysis([str(mod)]) == []


def test_dryrun_continue_guard_inside_loop(tmp_path):
    # `if dry_run: continue` makes the rest of the loop body
    # real-path-only — the mutation after it must not be flagged.
    mod = tmp_path / "sweep.py"
    mod.write_text(
        "class Client:\n"
        "    def delete(self, kind, name, dry_run=False):\n"
        "        ...\n"
        "\n\n"
        "class Ops:\n"
        "    def __init__(self, client: Client):\n"
        "        self._client = client\n"
        "\n"
        "    def sweep(self, names, dry_run=False):\n"
        "        for name in names:\n"
        "            if dry_run:\n"
        "                continue\n"
        '            self._client.delete("Node", name)\n'
    )
    assert run_analysis([str(mod)]) == []


def test_dryrun_defining_a_callback_is_not_mutating(tmp_path):
    # A function that only DEFINES a deferred callback must not be
    # classified as transitively mutating — the callback has its own
    # summary and only counts where it is actually called.
    mod = tmp_path / "cb.py"
    mod.write_text(
        "class Client:\n"
        "    def _request(self, verb, path):\n"
        "        ...\n"
        "\n\n"
        "class Ops:\n"
        "    def __init__(self, client: Client):\n"
        "        self._client = client\n"
        "        self._cb = None\n"
        "\n"
        "    def install_callback(self):\n"
        "        def cb():\n"
        '            self._client._request("POST", "/x")\n'
        "        self._cb = cb\n"
        "\n"
        "    def preview(self, dry_run=False):\n"
        "        if dry_run:\n"
        "            self.install_callback()\n"
    )
    assert run_analysis([str(mod)]) == []


# -- asyncio discipline (ASY601-ASY604) ------------------------------------

def test_asy_bad_fixture_flags_all_seeded_violations():
    findings = run_analysis([str(FIXTURES / "asy_bad.py")])
    assert codes(findings) == {"ASY601", "ASY602", "ASY603", "ASY604"}
    by_code = {}
    for f in findings:
        by_code.setdefault(f.code, []).append(f)
    # pump (sleep + queue put), refresh (transitive), the async
    # generator, the decorated coroutine, the dispatched callback.
    assert len(by_code["ASY601"]) == 6
    assert len(by_code["ASY602"]) == 2
    assert len(by_code["ASY603"]) == 2
    assert len(by_code["ASY604"]) == 1


def test_asy_clean_twin_silent():
    assert run_analysis([str(FIXTURES / "asy_clean.py")]) == []


def _asy(tmp_path, source: str):
    mod = tmp_path / "mod.py"
    mod.write_text(source)
    return run_analysis([str(mod)])


def test_asy601_direct_blocking_in_coroutine(tmp_path):
    findings = _asy(
        tmp_path,
        "import time\n\n\n"
        "async def pump():\n"
        "    time.sleep(0.1)\n",
    )
    assert codes(findings) == {"ASY601"}
    assert "time.sleep" in findings[0].message


def test_asy601_transitive_chain_carries_witness(tmp_path):
    findings = _asy(
        tmp_path,
        "import time\n\n\n"
        "def backoff():\n"
        "    time.sleep(1)\n"
        "\n\n"
        "def fetch():\n"
        "    return backoff()\n"
        "\n\n"
        "async def refresh():\n"
        "    return fetch()\n",
    )
    assert codes(findings) == {"ASY601"}
    assert "fetch -> backoff" in findings[0].message


def test_asy601_awaited_asyncio_primitives_are_suspensions(tmp_path):
    findings = _asy(
        tmp_path,
        "import asyncio\n\n\n"
        "class Hub:\n"
        "    def __init__(self):\n"
        "        self._wake = asyncio.Event()\n"
        "        self._q: asyncio.Queue = asyncio.Queue()\n"
        "\n"
        "    async def drain(self):\n"
        "        await self._wake.wait()\n"
        "        item = await self._q.get()\n"
        "        await asyncio.sleep(0)\n"
        "        return item\n",
    )
    assert findings == [], [str(f) for f in findings]


def test_asy601_sync_client_facade_reached_from_coroutine(tmp_path):
    # The ISSUE 15 headline hazard: a coroutine calling the sync Client
    # facade parks the loop in Future.result over ITSELF — deadlock.
    findings = _asy(
        tmp_path,
        "import asyncio\n\n\n"
        "class Facade:\n"
        "    def __init__(self):\n"
        "        self._loop = asyncio.new_event_loop()\n"
        "\n"
        "    def _call(self, coro):\n"
        "        future = asyncio.run_coroutine_threadsafe(\n"
        "            coro, self._loop)\n"
        "        return future.result(10)\n"
        "\n"
        "    def get(self, name):\n"
        "        return self._call(name)\n"
        "\n\n"
        "class Handler:\n"
        "    def __init__(self, client: Facade):\n"
        "        self._client = client\n"
        "\n"
        "    async def handle(self):\n"
        "        return self._client.get('node-1')\n",
    )
    assert codes(findings) == {"ASY601"}
    assert "result" in findings[0].message
    assert "Facade.get -> Facade._call" in findings[0].message


def test_asy601_async_callee_reports_once(tmp_path):
    # The blocking coroutine is its own reporting point; awaiting it
    # must not duplicate the finding at every caller.
    findings = _asy(
        tmp_path,
        "import time\n\n\n"
        "class C:\n"
        "    async def leaf(self):\n"
        "        time.sleep(1)\n"
        "\n"
        "    async def outer(self):\n"
        "        await self.leaf()\n",
    )
    assert [f.code for f in findings] == ["ASY601"]
    assert findings[0].scope == "C.leaf"


def test_asy601_call_soon_threadsafe_method_reference(tmp_path):
    # A bound-method reference dispatched to the loop is loop-affine:
    # its body is held to coroutine discipline.
    findings = _asy(
        tmp_path,
        "import asyncio\n"
        "import time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._loop = asyncio.new_event_loop()\n"
        "\n"
        "    def _cb(self):\n"
        "        time.sleep(0.1)\n"
        "\n"
        "    def kick(self):\n"
        "        self._loop.call_soon_threadsafe(self._cb)\n",
    )
    assert codes(findings) == {"ASY601"}
    assert findings[0].scope == "C._cb"


def test_asy601_lock_acquire_nonblocking_is_clean(tmp_path):
    findings = _asy(
        tmp_path,
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    async def try_once(self):\n"
        "        if self._lock.acquire(blocking=False):\n"
        "            self._lock.release()\n"
        "\n"
        "    async def block(self):\n"
        "        self._lock.acquire()\n"
        "        self._lock.release()\n",
    )
    assert [f.code for f in findings] == ["ASY601"]
    assert findings[0].scope == "C.block"


def test_asy602_bare_coroutine_call(tmp_path):
    findings = _asy(
        tmp_path,
        "async def job():\n"
        "    return 1\n"
        "\n\n"
        "async def main():\n"
        "    job()\n",
    )
    assert codes(findings) == {"ASY602"}
    assert "'job'" in findings[0].message


def test_asy602_retained_and_awaited_forms_clean(tmp_path):
    findings = _asy(
        tmp_path,
        "import asyncio\n\n\n"
        "async def job():\n"
        "    return 1\n"
        "\n\n"
        "async def main():\n"
        "    await job()\n"
        "    task = asyncio.create_task(job())\n"
        "    await task\n",
    )
    assert findings == [], [str(f) for f in findings]


def test_asy602_dropped_run_coroutine_threadsafe_future(tmp_path):
    findings = _asy(
        tmp_path,
        "import asyncio\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._loop = asyncio.new_event_loop()\n"
        "\n"
        "    async def pump(self):\n"
        "        return 1\n"
        "\n"
        "    def fire(self):\n"
        "        asyncio.run_coroutine_threadsafe(self.pump(), self._loop)\n",
    )
    assert codes(findings) == {"ASY602"}
    assert "run_coroutine_threadsafe" in findings[0].message


def test_asy603_lock_released_before_await_is_clean(tmp_path):
    findings = _asy(
        tmp_path,
        "import asyncio\n"
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "\n"
        "    async def ok(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "        await asyncio.sleep(0)\n",
    )
    assert findings == [], [str(f) for f in findings]


def test_asy603_async_for_implicit_await_under_lock(tmp_path):
    findings = _asy(
        tmp_path,
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    async def drain(self, source):\n"
        "        with self._lock:\n"
        "            async for _ in source:\n"
        "                pass\n",
    )
    assert codes(findings) == {"ASY603"}


def test_asy603_module_level_lock_identity(tmp_path):
    findings = _asy(
        tmp_path,
        "import asyncio\n"
        "import threading\n\n"
        "_REG = threading.Lock()\n\n\n"
        "async def publish():\n"
        "    with _REG:\n"
        "        await asyncio.sleep(0)\n",
    )
    assert codes(findings) == {"ASY603"}
    assert "_REG" in findings[0].message


def test_asy604_docstring_convention_silences(tmp_path):
    bad = (
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self._idle = []\n"
        "\n"
        "    async def acquire(self):\n"
        "        return self._idle.pop()\n"
        "\n"
        "    def release(self, conn):\n"
        "        self._idle.append(conn)\n"
    )
    findings = _asy(tmp_path, bad)
    assert codes(findings) == {"ASY604"}
    good = bad.replace(
        "    def release(self, conn):\n",
        "    def release(self, conn):\n"
        '        """Runs on the wire loop only."""\n',
    )
    mod = tmp_path / "good.py"
    mod.write_text(good)
    assert run_analysis([str(mod)]) == []


def test_asy604_dispatched_callback_is_loop_context(tmp_path):
    # A call_soon_threadsafe-dispatched nested def marks the state it
    # mutates loop-bound; a plain thread mutation of the same attr fires.
    findings = _asy(
        tmp_path,
        "import asyncio\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._loop = asyncio.new_event_loop()\n"
        "        self._buf = []\n"
        "\n"
        "    def push(self, item):\n"
        "        def _put():\n"
        "            self._buf.append(item)\n"
        "        self._loop.call_soon_threadsafe(_put)\n"
        "\n"
        "    def drop(self):\n"
        "        self._buf.clear()\n",
    )
    assert codes(findings) == {"ASY604"}
    assert findings[0].scope == "C.drop"


def test_asy604_dispatched_lambda_is_loop_context(tmp_path):
    # The pass's own recommended fix — routing the write through
    # call_soon_threadsafe with a LAMBDA — must never fire; and a plain
    # (undispatched) lambda's body runs at an unknown time on an
    # unknown thread, so it claims neither context (like a nested def).
    findings = _asy(
        tmp_path,
        "import asyncio\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._loop = asyncio.new_event_loop()\n"
        "        self._buf = []\n"
        "        self._cbs = []\n"
        "\n"
        "    async def drain(self):\n"
        "        self._buf.clear()\n"
        "\n"
        "    def push(self, item):\n"
        "        self._loop.call_soon_threadsafe(\n"
        "            lambda: self._buf.append(item))\n"
        "\n"
        "    def defer(self, item):\n"
        "        self._cbs.append(lambda: self._buf.append(item))\n",
    )
    # push() is clean; defer()'s lambda claims no context, but its
    # OWN self._cbs.append is a plain thread mutation of thread-only
    # state — also clean (no loop-side writer of _cbs).
    assert findings == []


def test_asy_noqa_suppresses(tmp_path):
    findings = _asy(
        tmp_path,
        "import time\n\n\n"
        "async def pump():\n"
        "    time.sleep(0.1)  # noqa: ASY601\n",
    )
    assert findings == []


def test_lck102_asyncio_sleep_under_lock_is_asy603_not_lck102(tmp_path):
    # Suspending under a threading lock is ASY603's finding; the sync
    # blocking classifiers must not double-report asyncio awaitable
    # factories as thread-blocking calls.
    findings = _asy(
        tmp_path,
        "import asyncio\n"
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    async def bad(self):\n"
        "        with self._lock:\n"
        "            await asyncio.sleep(0)\n",
    )
    assert codes(findings) == {"ASY603"}


def test_cli_stats_include_async_coverage(capsys):
    rc = cli.main([str(FIXTURES / "asy_bad.py"), "--baseline", "-",
                   "--stats"])
    assert rc == 1
    err = capsys.readouterr().err
    line = next(ln for ln in err.splitlines()
                if ln.startswith("analyze stats:"))
    assert "coroutines=" in line and "await_edges=" in line
    assert "loop_affine=" in line


def test_sarif_rules_include_asy_family(tmp_path, capsys):
    sarif_file = tmp_path / "report.sarif"
    rc = cli.main([str(FIXTURES / "asy_bad.py"), "--baseline", "-",
                   "--sarif", str(sarif_file)])
    assert rc == 1
    capsys.readouterr()
    doc = json.loads(sarif_file.read_text())
    rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"ASY601", "ASY602", "ASY603", "ASY604"} <= rule_ids
    assert {res["ruleId"] for res in doc["runs"][0]["results"]} == {
        "ASY601", "ASY602", "ASY603", "ASY604"
    }


def test_package_is_asy_clean():
    """The shipped wire path is provably loop-disciplined: zero ASY6xx
    findings outside the baseline (today: zero, period — the watch_pump
    put_nowait fix and the pool's loop-affinity docstrings landed with
    the pass). Regresses loudly if a blocking call, an unawaited
    coroutine, a lock-across-await, or a cross-thread mutation of
    loop-bound state enters kube/rest.py, kube/apiserver.py, or
    anything else on the loop."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu")],
        pass_names=["asyncio-discipline", "loop-affinity"],
    )
    assert findings == [], [str(f) for f in findings]


def test_pr12_14_modules_are_exc_key_clean():
    """EXC401/KEY301 sweep over the chaos/tracing/faultpoints modules
    (in analyze scope since ISSUE 15): clean, no baseline entries."""
    targets = [
        str(REPO / "k8s_operator_libs_tpu" / "utils" / "tracing.py"),
        str(REPO / "k8s_operator_libs_tpu" / "utils" / "faultpoints.py"),
        str(REPO / "k8s_operator_libs_tpu" / "testing" / "chaos.py"),
        str(REPO / "tools" / "chaos_run.py"),
        str(REPO / "tools" / "trace_view.py"),
    ]
    findings = run_analysis(
        targets, pass_names=["swallowed-exception", "literal-key"]
    )
    assert findings == [], [str(f) for f in findings]


# -- policy discipline (POL701-POL705) -------------------------------------

def test_pol_bad_fixture_flags_all_seeded_violations():
    findings = run_analysis([str(FIXTURES / "policy_bad.py")])
    assert codes(findings) == {
        "POL701", "POL702", "POL703", "POL704", "POL705"
    }
    by_code = {}
    for f in findings:
        by_code.setdefault(f.code, []).append(f)
    # admit (transitive), _push (direct), order (clock), budget (RNG).
    assert len(by_code["POL701"]) == 4
    # The while loop, plus the _spin self-recursion seen from budget
    # and from _spin itself.
    assert len(by_code["POL702"]) == 3
    # self-stash, self-held container, module-level store, global.
    assert len(by_code["POL703"]) == 4
    # Dead ShadowPolicy + unreferenced 'ghost-policy'.
    assert len(by_code["POL704"]) == 2
    # Truthy stand-in, bare return, fall-through.
    assert len(by_code["POL705"]) == 3
    # The transitive-mutator finding names its witness chain.
    transitive = [f for f in by_code["POL701"]
                  if "MutatorPolicy.admit" in f.message]
    assert transitive and "-> MutatorPolicy._push" in transitive[0].message


def test_pol_clean_twin_silent():
    assert run_analysis([str(FIXTURES / "policy_clean.py")]) == []


def test_package_is_pol_clean():
    """Every registered policy the package ships (default,
    maintenance-window, cost-tiers, and the two composition markers) is
    provably pure, bounded, stateless, reachable, and total: zero
    POL7xx findings, no baseline entries."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu")],
        pass_names=["policy-discipline"],
    )
    assert findings == [], [str(f) for f in findings]


def test_cli_stats_include_policy_coverage(capsys):
    rc = cli.main([str(FIXTURES / "policy_bad.py"), "--baseline", "-",
                   "--stats"])
    assert rc == 1
    err = capsys.readouterr().err
    line = next(ln for ln in err.splitlines()
                if ln.startswith("analyze stats:"))
    # Three registered classes in the fixture (the dead ShadowPolicy
    # does not count — it is exactly what the counter must not see).
    assert "policies=3" in line


def test_sarif_rules_include_pol_family(tmp_path, capsys):
    sarif_file = tmp_path / "report.sarif"
    rc = cli.main([str(FIXTURES / "policy_bad.py"), "--baseline", "-",
                   "--sarif", str(sarif_file)])
    assert rc == 1
    capsys.readouterr()
    doc = json.loads(sarif_file.read_text())
    rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"POL701", "POL702", "POL703", "POL704", "POL705"} <= rule_ids
    assert {res["ruleId"] for res in doc["runs"][0]["results"]} == {
        "POL701", "POL702", "POL703", "POL704", "POL705"
    }


# -- lifecycle discipline (LIF8xx) -----------------------------------------

def test_lif_bad_fixture_flags_all_seeded_violations():
    findings = run_analysis([str(FIXTURES / "lifecycle_bad.py")])
    assert codes(findings) == {
        "LIF801", "LIF802", "LIF803", "LIF804", "LIF805"
    }
    by_code = {}
    for f in findings:
        by_code.setdefault(f.code, []).append(f)
    # Leaked informer, thread never joined on shutdown, transitively
    # acquired server with no release path.
    assert len(by_code["LIF801"]) == 3
    # Mid-frame raise skips the stop, early return skips it, and the
    # except-reraise path without a finally.
    assert len(by_code["LIF802"]) == 3
    # Non-daemon thread never joined, join() without timeout, and the
    # loop-spawned batch joined without a bound.
    assert len(by_code["LIF803"]) == 3
    # Producer stopped before its consumer (hub before informer).
    assert len(by_code["LIF804"]) == 1
    # Lock acquisition, blocking I/O, and a join reachable from the
    # registered signal handler.
    assert len(by_code["LIF805"]) == 3
    assert len(findings) == 13


def test_lif_clean_twin_silent():
    assert run_analysis([str(FIXTURES / "lifecycle_clean.py")]) == []


def test_package_is_lif_clean():
    """Every background resource the package ships (informers, watch
    hub pumps, electors, servers, the runtime/ supervision tree) has a
    verified shutdown path: zero LIF8xx findings, no baseline
    entries."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu")],
        pass_names=["lifecycle-discipline"],
    )
    assert findings == [], [str(f) for f in findings]


def test_bench_is_lif_clean():
    """The LIF802 sweep (the PR-7 degraded_first_roll informer-leak
    class): bench.py's harness sections acquire informers, workers,
    hubs, and servers — all of them now release in finally. Analyzed
    WITH the package in scope so cross-module acquire/release pairs
    resolve."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu"), str(REPO / "bench.py")],
        pass_names=["lifecycle-discipline"],
    )
    bench_findings = [f for f in findings if "bench.py" in f.path]
    assert bench_findings == [], [str(f) for f in bench_findings]


def test_cli_stats_include_resource_coverage(capsys, monkeypatch):
    # Relative path from the repo root so the checked-in baseline's
    # path keys match (the same shape `make analyze` runs).
    monkeypatch.chdir(REPO)
    rc = cli.main(["k8s_operator_libs_tpu", "--stats"])
    assert rc == 0
    err = capsys.readouterr().err
    line = next(ln for ln in err.splitlines()
                if ln.startswith("analyze stats:"))
    # The registered (acquire, release) resource classes the lifecycle
    # pass verifies — @lifecycle_resource registrations plus the
    # built-in registry.
    assert "resources=14" in line


def test_sarif_rules_include_lif_family(tmp_path, capsys):
    sarif_file = tmp_path / "report.sarif"
    rc = cli.main([str(FIXTURES / "lifecycle_bad.py"), "--baseline", "-",
                   "--sarif", str(sarif_file)])
    assert rc == 1
    capsys.readouterr()
    doc = json.loads(sarif_file.read_text())
    rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"LIF801", "LIF802", "LIF803", "LIF804", "LIF805"} <= rule_ids
    assert {res["ruleId"] for res in doc["runs"][0]["results"]} == {
        "LIF801", "LIF802", "LIF803", "LIF804", "LIF805"
    }
