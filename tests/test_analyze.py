"""Tests for the domain-aware static analyzer (tools/analyze/).

Each pass must catch its seeded violation in tests/analyze_fixtures/ and
stay silent on the clean twin; plus the framework behaviors the gate
depends on: targeted noqa, the baseline lifecycle, JSON output, exit
codes — and the acceptance criterion itself: the real package is clean
under the checked-in baseline.
"""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "analyze_fixtures"

sys.path.insert(0, str(REPO / "tools"))

from analyze import cli  # noqa: E402
from analyze.baseline import load_baseline, split_findings  # noqa: E402
from analyze.core import parse_noqa, run_analysis, suppressed  # noqa: E402


def codes(findings):
    return {f.code for f in findings}


# -- lock discipline -------------------------------------------------------

def test_lock_pass_flags_seeded_violations():
    findings = run_analysis([str(FIXTURES / "lock_bad.py")])
    assert codes(findings) == {"LCK101", "LCK102"}
    lck101 = [f for f in findings if f.code == "LCK101"]
    # _count unguarded in reset(), _last unguarded in touch().
    assert len(lck101) == 2
    assert {"_count" in f.message or "_last" in f.message for f in lck101} == {True}
    lck102 = [f for f in findings if f.code == "LCK102"]
    assert len(lck102) == 2
    reasons = " ".join(f.message for f in lck102)
    assert "time.sleep" in reasons and "join" in reasons


def test_lock_pass_silent_on_clean_twin():
    assert run_analysis([str(FIXTURES / "lock_clean.py")]) == []


# -- state machine ---------------------------------------------------------

def test_state_machine_pass_flags_all_seeded_violations():
    findings = run_analysis([str(FIXTURES / "sm_bad")])
    got = codes(findings)
    assert {"STM201", "STM202", "STM203", "STM204", "STM205"} <= got
    unpartitioned = [f for f in findings if f.code == "STM201"]
    assert len(unpartitioned) == 2  # RETIRED and LOST
    unhandled = [f for f in findings if f.code == "STM203"]
    assert {m for f in unhandled for m in ("JAMMED", "RETIRED", "LOST")
            if m in f.message} == {"JAMMED", "RETIRED", "LOST"}
    stale = [f for f in findings if f.code == "STM204"]
    assert len(stale) == 1 and "process_melted_nodes" in stale[0].message
    literal = [f for f in findings if f.code == "STM205"]
    assert len(literal) == 1 and "widget-jammed" in literal[0].message


def test_state_machine_pass_silent_on_clean_twin():
    assert run_analysis([str(FIXTURES / "sm_clean")]) == []


def test_real_upgrade_machine_is_exhaustive():
    """The production state machine itself satisfies the invariants —
    13 states partitioned and handled. Regresses loudly if a state is
    added without a handler or partition slot."""
    findings = run_analysis(
        [str(REPO / "k8s_operator_libs_tpu" / "upgrade")],
        pass_names=["state-machine"],
    )
    assert findings == [], [str(f) for f in findings]


# -- literal keys ----------------------------------------------------------

def test_literal_key_pass_flags_seeded_violations():
    findings = run_analysis([str(FIXTURES / "key_bad.py")])
    assert codes(findings) == {"KEY301"}
    assert len(findings) == 2


def test_literal_key_pass_silent_on_clean_twin_and_honors_noqa():
    # key_clean.py contains an upgrade-shaped literal under # noqa: KEY301
    # and an other-namespace key; both must stay silent.
    assert run_analysis([str(FIXTURES / "key_clean.py")]) == []


# -- swallowed exceptions --------------------------------------------------

def test_swallowed_pass_flags_seeded_violation():
    findings = run_analysis([str(FIXTURES / "swallow_bad.py")])
    assert codes(findings) == {"EXC401"}
    assert len(findings) == 1
    assert findings[0].scope == "reconcile"


def test_swallowed_pass_silent_on_clean_twin():
    # Logging, error-as-data, import-fallback and narrow handlers are all
    # legitimate shapes.
    assert run_analysis([str(FIXTURES / "swallow_clean.py")]) == []


# -- framework: noqa grammar ----------------------------------------------

def test_parse_noqa_grammar():
    noqa = parse_noqa(
        "x = 1  # noqa\n"
        "y = 2  # noqa: LCK101\n"
        "z = 3  # noqa: LCK101, EXC401\n"
        "w = 4\n"
    )
    assert suppressed(noqa, 1, "ANY999")
    assert suppressed(noqa, 2, "LCK101") and not suppressed(noqa, 2, "EXC401")
    assert suppressed(noqa, 3, "EXC401")
    assert not suppressed(noqa, 4, "LCK101")


def test_parse_noqa_stops_at_prose():
    # Trailing prose after the code list must not widen the suppression
    # to rule codes it merely mentions.
    noqa = parse_noqa(
        "x = 1  # noqa: E501 long url, see E722 docs\n"
        "y = 2  # noqa: BLE001 - the monitor must outlive blips\n"
    )
    assert suppressed(noqa, 1, "E501") and not suppressed(noqa, 1, "E722")
    assert suppressed(noqa, 2, "BLE001")


def test_parse_noqa_malformed_codes_suppress_nothing():
    # `# noqa: keep` (unparseable code list) must NOT degrade to a
    # blanket suppression — the finding surfaces and the typo gets
    # fixed.
    noqa = parse_noqa(
        "x = 1  # noqa: somereason\n"
        "y = 2  # noqa: KEY-301\n"
        "z = 3  # noqa\n"
    )
    assert not suppressed(noqa, 1, "LCK101")
    assert not suppressed(noqa, 2, "KEY301")
    assert suppressed(noqa, 3, "ANY999")  # bare blanket still works


def test_cli_select_run_does_not_report_unselected_stale(tmp_path, capsys):
    # Baseline an EXC401, then run ONLY the lock pass over the same file:
    # the EXC401 entry is out of the run's scope, not "fixed".
    baseline = tmp_path / "b.json"
    target = str(FIXTURES / "swallow_bad.py")
    cli.main([target, "--baseline", str(baseline), "--write-baseline"])
    rc = cli.main([target, "--baseline", str(baseline),
                   "--select", "lock-discipline"])
    err = capsys.readouterr().err
    assert rc == 0
    assert "fixed? remove it" not in err


def test_lock_pass_accepts_local_lock_alias(tmp_path):
    mod = tmp_path / "alias.py"
    mod.write_text(
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0\n"
        "\n"
        "    def guarded(self):\n"
        "        with self._lock:\n"
        "            self._x = 1\n"
        "\n"
        "    def alias_guarded(self):\n"
        "        lock = self._lock\n"
        "        with lock:\n"
        "            self._x = 2\n"
    )
    assert run_analysis([str(mod)]) == []


def test_cli_rejects_nonexistent_file_argument(capsys):
    rc = cli.main([str(FIXTURES / "no_such_file.py"), "--baseline", "-"])
    assert rc == 2
    capsys.readouterr()


def test_cli_subset_run_does_not_report_out_of_scope_stale(tmp_path, capsys):
    # Baseline an EXC401 in swallow_bad.py, then analyze ONLY the clean
    # twin: the out-of-scope entry must not be called "fixed".
    baseline = tmp_path / "b.json"
    cli.main([str(FIXTURES / "swallow_bad.py"), "--baseline", str(baseline),
              "--write-baseline"])
    rc = cli.main([str(FIXTURES / "swallow_clean.py"),
                   "--baseline", str(baseline)])
    err = capsys.readouterr().err
    assert rc == 0
    assert "stale" not in err.split("\n")[0] or "0 stale" in err


def test_parse_noqa_ignores_string_literals():
    # 'noqa' inside a string (help text, a linter's own messages) is not
    # a directive — only real comments suppress.
    noqa = parse_noqa(
        'msg = "add # noqa: EXC401 to silence"\n'
        's = """\n'
        "# noqa\n"
        '"""\n'
        "y = 1  # noqa: EXC401\n"
    )
    assert not suppressed(noqa, 1, "EXC401")
    assert not suppressed(noqa, 3, "ANY")
    assert suppressed(noqa, 5, "EXC401")


# -- framework: baseline lifecycle ----------------------------------------

def test_baseline_roundtrip(tmp_path):
    baseline = tmp_path / "baseline.json"
    target = str(FIXTURES / "swallow_bad.py")
    assert cli.main([target, "--baseline", str(baseline)]) == 1
    assert cli.main([target, "--baseline", str(baseline),
                     "--write-baseline"]) == 0
    # Baselined: the gate is green while the finding stays recorded.
    assert cli.main([target, "--baseline", str(baseline)]) == 0
    entries = load_baseline(baseline)
    assert len(entries) == 1


def test_baseline_reports_stale_entries(tmp_path):
    baseline = tmp_path / "baseline.json"
    bad = str(FIXTURES / "swallow_bad.py")
    clean = str(FIXTURES / "swallow_clean.py")
    cli.main([bad, "--baseline", str(baseline), "--write-baseline"])
    findings = run_analysis([clean])
    new, baselined, stale = split_findings(findings, load_baseline(baseline))
    assert new == [] and baselined == [] and len(stale) == 1


def test_baseline_fingerprints_distinguish_scopes():
    findings = run_analysis([str(FIXTURES / "lock_bad.py")])
    prints = {f.fingerprint() for f in findings}
    assert len(prints) == len(findings)  # no two findings collapse


def test_baseline_fingerprints_distinguish_repeats_in_one_scope(tmp_path):
    # A SECOND identical violation in an already-baselined scope must not
    # be absorbed by the first one's justification.
    one = tmp_path / "one.py"
    one.write_text(
        "def reconcile(c):\n"
        "    try:\n"
        "        c.sync()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    findings_one = run_analysis([str(one)])
    assert len(findings_one) == 1
    two = tmp_path / "one.py"  # same path: simulate the edit
    two.write_text(
        "def reconcile(c):\n"
        "    try:\n"
        "        c.sync()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        c.flush()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    findings_two = run_analysis([str(two)])
    assert len(findings_two) == 2
    prints = {f.fingerprint() for f in findings_two}
    assert len(prints) == 2
    # The first occurrence keeps its original fingerprint (the baseline
    # entry stays valid); only the new one is unmatched.
    assert findings_one[0].fingerprint() in prints


def test_literal_key_pass_covers_modules_with_unrelated_key_helpers(tmp_path):
    # A `_key` helper alone (FakeCluster/Informer shape) must NOT exempt
    # a module from KEY301 — only the full builder shape does.
    mod = tmp_path / "fakeish.py"
    mod.write_text(
        "class FakeCluster:\n"
        "    def _key(self, kind, ns, name):\n"
        "        return (kind, ns, name)\n"
        "\n"
        'LABEL = "acme.dev/widget-driver-upgrade-state"\n'
    )
    findings = run_analysis([str(mod)], pass_names=["literal-key"])
    assert [f.code for f in findings] == ["KEY301"]


def test_state_machine_allows_two_handlers_for_one_state(tmp_path):
    # Splitting one state's processing across two mapped calls is not
    # staleness.
    pkg = tmp_path / "sm"
    pkg.mkdir()
    (pkg / "consts.py").write_text(
        "from enum import Enum\n\n\n"
        "class FooState(str, Enum):\n"
        '    DRAIN_REQUIRED = "foo-drain-required"\n'
        "\n\n"
        "MANAGED_STATES = (FooState.DRAIN_REQUIRED,)\n"
        "MAINTENANCE_STATES = ()\n"
    )
    (pkg / "manager.py").write_text(
        "class M:\n"
        "    def apply_state(self, state):\n"
        "        self.process_drain_nodes(state)\n"
        "        self.process_drain_timeout_nodes(state)\n"
    )
    findings = run_analysis([str(pkg)], pass_names=["state-machine"])
    assert findings == [], [str(f) for f in findings]


# -- framework: CLI behaviors ---------------------------------------------

def test_cli_text_output(capsys):
    rc = cli.main([str(FIXTURES / "swallow_bad.py"), "--baseline", "-"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "EXC401" in out and "swallow_bad.py:" in out


def test_cli_json_report_and_output_file(tmp_path, capsys):
    report_file = tmp_path / "report.json"
    rc = cli.main([
        str(FIXTURES / "swallow_bad.py"), "--json", "--baseline", "-",
        "--output", str(report_file),
    ])
    assert rc == 1
    printed = json.loads(capsys.readouterr().out)
    on_disk = json.loads(report_file.read_text())
    assert printed == on_disk
    assert on_disk["counts"] == {
        "new": 1, "baselined": 0, "stale_baseline_entries": 0,
    }
    finding = on_disk["findings"][0]
    assert finding["code"] == "EXC401" and finding["scope"] == "reconcile"


def test_cli_select_single_pass():
    rc_all = run_analysis([str(FIXTURES / "lock_bad.py")],
                          pass_names=["swallowed-exception"])
    assert rc_all == []  # the lock violations are another pass's


def test_cli_fails_loudly_when_gate_would_be_off(tmp_path, capsys):
    # A mistyped path or pass name must not print "clean" and exit 0 —
    # that is the gate silently turning itself off.
    assert cli.main([str(tmp_path / "no_such_dir"), "--baseline", "-"]) == 2
    assert cli.main([str(FIXTURES / "lock_bad.py"), "--baseline", "-",
                     "--select", "lockdiscipline-typo"]) == 2
    capsys.readouterr()


def test_write_baseline_keeps_out_of_scope_entries(tmp_path):
    # A subset --write-baseline must not drop suppressions it could not
    # have re-observed.
    baseline = tmp_path / "b.json"
    cli.main([str(FIXTURES / "swallow_bad.py"), "--baseline", str(baseline),
              "--write-baseline"])
    cli.main([str(FIXTURES / "lock_bad.py"), "--baseline", str(baseline),
              "--write-baseline"])
    entries = load_baseline(baseline)
    assert any("EXC401" in fp for fp in entries)  # survived the 2nd write
    assert any("LCK101" in fp for fp in entries)


# -- the acceptance criterion itself --------------------------------------

def test_package_gate_is_clean_via_entrypoint():
    """`python tools/analyze.py k8s_operator_libs_tpu` (what make lint and
    CI run) exits 0 against the checked-in baseline."""
    proc = subprocess.run(
        [sys.executable, "tools/analyze.py", "k8s_operator_libs_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
