"""Fleet tier: sharded multi-pool control plane (ISSUE 10;
docs/fleet-control-plane.md).

What must hold, layer by layer:

* **hashring** — process-stable ownership, every member used, and the
  consistent-hashing churn bound: membership change moves only the keys
  adjacent to the changed member (a reshuffle would invalidate every
  worker's incremental baseline at once).
* **scope** — a shard worker's snapshot sees exactly its shards' world:
  the completeness invariant holds WITHIN scope (a missing driver pod on
  an owned node aborts the pass) and ignores other shards (another
  worker's drain cannot wedge this one).
* **orchestrator** — grants never exceed the global budget, degraded
  pools (worst-member health fold) are granted first, completions free
  budget, and the whole decision re-derives from the CR (restart-free).
* **worker e2e** — N workers roll the fleet to convergence with ZERO
  global-budget violations; killing a worker mid-roll loses no nodes:
  lease failover re-claims its shards and the roll completes (the ISSUE
  acceptance pin).
* **failure injection** — lease Conflict/ServerTimeout and ledger-write
  conflicts on the fleet path are absorbed, never a crash or a stall.
"""

from __future__ import annotations

import itertools

import pytest

from k8s_operator_libs_tpu.api import (
    DriverUpgradePolicySpec,
    make_fleet_rollout,
    make_node_health_report,
    pool_phase,
    pools_in_phase,
)
from k8s_operator_libs_tpu.api.fleet_v1alpha1 import (
    FLEET_ROLLOUT_KIND,
    POOL_DONE,
    POOL_GRANTED,
    POOL_PENDING,
)
from k8s_operator_libs_tpu.fleet import (
    FleetHealthAggregator,
    FleetOrchestrator,
    FleetWorkerConfig,
    HashRing,
    ShardWorker,
    shard_id,
)
from k8s_operator_libs_tpu.kube import FakeCluster, Node
from k8s_operator_libs_tpu.kube.client import ApiError, ConflictError
from k8s_operator_libs_tpu.kube.objects import KubeObject
from k8s_operator_libs_tpu.kube.sim import DaemonSetSimulator
from k8s_operator_libs_tpu.upgrade import (
    BuildStateError,
    DeviceClass,
    UpgradeKeys,
)
from k8s_operator_libs_tpu.upgrade.health_source import HealthSource
from k8s_operator_libs_tpu.utils import IntOrString

DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
NS = "driver-ns"
LABELS = {"app": "driver"}
ROLLOUT = "fleet-roll"

POLICY = DriverUpgradePolicySpec(
    auto_upgrade=True,
    max_parallel_upgrades=0,
    # Permissive per-pool budget: in the fleet shape the GRANT is the
    # budget (docs/fleet-control-plane.md, budget math).
    max_unavailable=IntOrString("100%"),
)


def pool_of(node_name: str) -> str:
    return node_name.split("-")[0]


class Clock:
    def __init__(self, start: float = 100.0) -> None:
        self.t = start

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class ServerTimeoutError(ApiError):
    """A 504-shaped transient apiserver failure."""


class Flaky:
    """Reactor failing the next ``times`` matching calls, then passing."""

    def __init__(self, exc_type, times=3):
        self.exc_type = exc_type
        self.remaining = times
        self.fired = 0

    def __call__(self, verb, kind, payload):
        if self.remaining > 0:
            self.remaining -= 1
            self.fired += 1
            raise self.exc_type(f"injected {self.exc_type.__name__}")


# ---------------------------------------------------------------------------
# Harness
# ---------------------------------------------------------------------------


def build_fleet(pools=8, hosts=2, budget="25%"):
    cluster = FakeCluster()
    pool_names = [f"p{i}" for i in range(pools)]
    for pool in pool_names:
        for h in range(hosts):
            node = Node.new(f"{pool}-h{h}")
            node.set_ready(True)
            cluster.create(node)
    sim = DaemonSetSimulator(
        cluster, name="driver", namespace=NS, match_labels=LABELS
    )
    sim.settle()
    cluster.create(KubeObject(make_fleet_rollout(ROLLOUT, pool_names, budget)))
    return cluster, sim, pool_names


def make_worker(cluster, clock, identity, workers, shards=2, **overrides):
    kwargs = dict(
        identity=identity,
        shards=shards,
        namespace=NS,
        driver_labels=LABELS,
        pool_of=pool_of,
        rollout_name=ROLLOUT,
        workers=tuple(workers),
        lease_duration_s=3.0,
        renew_deadline_s=2.0,
        retry_period_s=0.5,
    )
    kwargs.update(overrides)
    worker = ShardWorker(
        cluster, FleetWorkerConfig(**kwargs),
        now_fn=clock.now, wall_fn=clock.now,
    )
    worker.start(sync_timeout=5)
    return worker


def node_state(cluster, name: str):
    raw = cluster.peek("Node", name) or {}
    return ((raw.get("metadata") or {}).get("labels") or {}).get(
        KEYS.state_label
    )


def disrupted_pools(cluster) -> set[str]:
    out = set()
    for obj in cluster.list("Node"):
        node = Node(obj.raw)
        if node.unschedulable or not node.is_ready():
            out.add(pool_of(node.name))
    return out


def drive_fleet(
    cluster,
    sim,
    orch,
    workers,
    clock,
    pool_names,
    budget: int,
    max_iters=400,
    mid_roll_hook=None,
):
    """Tick sim + orchestrator + workers until the ledger says every
    pool is done; samples the global budget every iteration and returns
    (iterations, violations). Deadline-capped, never silently
    truncated."""
    violations = 0
    for i in range(max_iters):
        # Hook first (fault arming, crash injection) so a hook can act
        # before the very first campaign round of an iteration.
        if mid_roll_hook is not None:
            workers = mid_roll_hook(i, workers) or workers
        sim.step()
        orch.tick()
        for worker in workers:
            try:
                worker.tick(POLICY)
            except (ApiError, BuildStateError):
                pass  # a pass aborts; the next one resumes from labels
        sim.step()
        if len(disrupted_pools(cluster)) > budget:
            violations += 1
        clock.advance(0.6)
        # The convergence check shares the flaky apiserver: an injected
        # get-fault here is chaos too, not a harness crash.
        try:
            raw = cluster.peek(FLEET_ROLLOUT_KIND, ROLLOUT) or {}
        except ApiError:
            continue
        if len(pools_in_phase(raw, POOL_DONE)) == len(pool_names):
            return i + 1, violations
    raise AssertionError(
        f"fleet roll did not converge in {max_iters} iterations "
        f"(done={len(pools_in_phase(raw, POOL_DONE))}/{len(pool_names)})"
    )


def assert_fleet_converged(cluster, sim):
    assert sim.all_pods_ready_and_current()
    for obj in cluster.list("Node"):
        assert node_state(cluster, obj.name) == "upgrade-done"
        assert not Node(obj.raw).unschedulable


# ---------------------------------------------------------------------------
# Hash ring
# ---------------------------------------------------------------------------


class TestHashRing:
    KEYS = [f"pool-{i}" for i in range(96)]

    def test_deterministic_across_instances(self):
        a = HashRing(["w1", "w2", "w3"])
        b = HashRing(["w3", "w1", "w2"])  # insertion order must not matter
        assert [a.owner(k) for k in self.KEYS] == [
            b.owner(k) for k in self.KEYS
        ]

    def test_every_member_owns_keys(self):
        ring = HashRing(["w1", "w2", "w3", "w4"])
        assignment = ring.assignment(self.KEYS)
        assert set(assignment) == {"w1", "w2", "w3", "w4"}
        assert all(owned for owned in assignment.values())

    def test_add_moves_only_keys_to_the_new_member(self):
        ring = HashRing(["w1", "w2", "w3"])
        before = {k: ring.owner(k) for k in self.KEYS}
        ring.add("w4")
        moved = {
            k: (before[k], ring.owner(k))
            for k in self.KEYS
            if ring.owner(k) != before[k]
        }
        # Bounded churn: every moved key moved TO the new member, and
        # roughly K/N moved (loose bound: strictly fewer than half).
        assert moved, "a new member must take some keys"
        assert all(new == "w4" for _, new in moved.values())
        assert len(moved) < len(self.KEYS) / 2

    def test_remove_moves_only_the_removed_members_keys(self):
        ring = HashRing(["w1", "w2", "w3", "w4"])
        before = {k: ring.owner(k) for k in self.KEYS}
        ring.remove("w4")
        for k in self.KEYS:
            if before[k] != "w4":
                assert ring.owner(k) == before[k], (
                    f"{k} moved despite its owner surviving"
                )
            else:
                assert ring.owner(k) != "w4"

    def test_empty_ring_raises(self):
        with pytest.raises(ValueError):
            HashRing().owner("anything")

    def test_validation(self):
        with pytest.raises(ValueError):
            HashRing(replicas=0)
        with pytest.raises(ValueError):
            HashRing().add("")


# ---------------------------------------------------------------------------
# Shard-scoped snapshots
# ---------------------------------------------------------------------------


class TestShardScope:
    def _one_worker(self, cluster, clock, shards=2, preferred=None):
        return make_worker(
            cluster,
            clock,
            "w-a",
            workers=("w-a",),
            shards=shards,
            preferred_shards=preferred,
            rollout_name="",
        )

    def test_scoped_worker_touches_only_its_shards(self):
        cluster, sim, pool_names = build_fleet()
        clock = Clock()
        worker = self._one_worker(cluster, clock)
        try:
            first = shard_id(0)
            worker.config.preferred_shards = None
            # Restrict scope to shard-00 by owning only its lease: give
            # the worker a single preferred shard and never probe the
            # other (probe cadence pushed beyond the test horizon).
            worker._claims[shard_id(1)].preferred = False
            worker._claims[shard_id(1)]._probe = 10_000.0
            in_scope = {
                p for p in pool_names if worker.pool_ring.owner(p) == first
            }
            assert in_scope and in_scope != set(pool_names)
            sim.set_template_hash("v2")
            for _ in range(120):
                sim.step()
                try:
                    worker.tick(POLICY)
                except BuildStateError:
                    # The documented tick contract: reconcile errors
                    # propagate and "the caller's loop owns retry
                    # policy". A completeness check racing an in-flight
                    # kubelet pod delivery aborts THIS pass; the next
                    # iteration's full rebuild resumes (same tolerance
                    # as drive_fleet above and incremental-state
                    # settle()).
                    pass
                sim.step()
                clock.advance(0.6)
                if all(
                    node_state(cluster, f"{p}-h{h}") == "upgrade-done"
                    and cluster.peek(
                        "Pod", sim.pod_name(f"{p}-h{h}"), NS
                    )["metadata"]["labels"]["controller-revision-hash"]
                    == "v2"
                    for p in in_scope
                    for h in range(2)
                ):
                    break
            else:
                raise AssertionError("owned shard never converged")
            # The other shard's nodes were never managed: no state label,
            # stale driver pods, never cordoned.
            for p in set(pool_names) - in_scope:
                for h in range(2):
                    name = f"{p}-h{h}"
                    assert node_state(cluster, name) is None
                    raw = cluster.peek("Node", name)
                    assert not (raw.get("spec") or {}).get("unschedulable")
        finally:
            worker.stop()

    @staticmethod
    def _wait_dirty(source, node_name, timeout=5.0):
        """Deadline-wait for the watch thread to deliver a node's delta
        (the dirty mark) — the build below must consume the event, not
        race it."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while node_name not in source.dirty().nodes:
            if _time.monotonic() > deadline:
                raise AssertionError(
                    f"delta for {node_name} never delivered"
                )
            _time.sleep(0.01)

    def test_completeness_invariant_is_shard_scoped(self):
        cluster, sim, pool_names = build_fleet()
        clock = Clock()
        worker = self._one_worker(cluster, clock)
        try:
            worker._claims[shard_id(1)].preferred = False
            worker._claims[shard_id(1)]._probe = 10_000.0
            worker.tick(POLICY)  # claim + settle
            first = shard_id(0)
            in_scope = next(
                p for p in pool_names if worker.pool_ring.owner(p) == first
            )
            out_of_scope = next(
                p for p in pool_names if worker.pool_ring.owner(p) != first
            )
            # Another shard's missing driver pod must NOT wedge this
            # worker (its own scoped desired count shrinks with it).
            cluster.delete("Pod", sim.pod_name(f"{out_of_scope}-h0"), NS)
            self._wait_dirty(worker.source, f"{out_of_scope}-h0")
            worker.mgr.build_state(NS, LABELS)  # no BuildStateError
            # An OWNED node's missing driver pod must abort the pass —
            # the node would silently escape management otherwise.
            cluster.delete("Pod", sim.pod_name(f"{in_scope}-h0"), NS)
            self._wait_dirty(worker.source, f"{in_scope}-h0")
            aborts_before = worker.mgr.completeness_aborts_total
            with pytest.raises(BuildStateError):
                worker.mgr.build_state(NS, LABELS)
            # The tolerated race is a COUNTED signal now (ISSUE 13):
            # PassStats carries the lifetime total so the chaos harness
            # (and the pass gauge) can assert it stays bounded instead
            # of silently swallowing every abort.
            assert worker.mgr.completeness_aborts_total == aborts_before + 1
            assert (
                worker.mgr.last_pass_stats.aborted_completeness_races
                == worker.mgr.completeness_aborts_total
            )
        finally:
            worker.stop()

    def test_scope_change_invalidates_baseline(self):
        cluster, sim, pool_names = build_fleet()
        clock = Clock()
        worker = self._one_worker(cluster, clock)
        try:
            worker.tick(POLICY)
            source = worker.source
            assert not source.dirty().full
            assert source.set_owned_shards(frozenset([shard_id(0)]))
            assert source.dirty().full, (
                "an ownership change must force a full rebuild"
            )
            assert not source.set_owned_shards(frozenset([shard_id(0)]))
        finally:
            worker.stop()


# ---------------------------------------------------------------------------
# Health fold: scoped sources -> global degraded-first queue
# ---------------------------------------------------------------------------


class TestHealthAggregation:
    def _publish(self, cluster, node_name, score_metrics):
        cluster.create(
            KubeObject(
                make_node_health_report(node_name, *score_metrics)
            )
        )

    def test_scoped_source_filters_and_refolds(self):
        cluster = FakeCluster()
        for name in ("p0-h0", "p1-h0", "p2-h0"):
            self._publish(cluster, name, ({"ring": True}, {}))
        scope = {"p0-h0"}
        source = HealthSource(cluster, node_filter=lambda n: n in scope)
        with source:
            assert set(source.snapshot()) == {"p0-h0"}
            # Scope grows (shard acquired): refold picks up the stored
            # reports the filter previously dropped.
            scope.add("p1-h0")
            source.refold()
            assert set(source.snapshot()) == {"p0-h0", "p1-h0"}
            # Scope shrinks (shard lost): refold evicts.
            scope.remove("p0-h0")
            source.refold()
            assert set(source.snapshot()) == {"p1-h0"}

    def test_aggregator_folds_worst_member_per_pool(self):
        cluster = FakeCluster()
        # p0: one healthy, one degraded host -> pool reads degraded.
        self._publish(cluster, "p0-h0", ({"ring": True}, {}))
        self._publish(
            cluster, "p0-h1",
            ({"ring": False}, {"probe_latency_s": 300.0}),
        )
        self._publish(cluster, "p1-h0", ({"ring": True}, {}))
        source = HealthSource(cluster)
        with source:
            agg = FleetHealthAggregator(pool_of)
            agg.add_source(source)
            health = agg.pool_health()
            assert health["p0"][0] < health["p1"][0]
            # Degraded-first: p0 outranks p1; unknown pools read healthy
            # and order by name after scored ones.
            assert agg.ordered(["p9", "p1", "p0"]) == ["p0", "p1", "p9"]


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------


class TestOrchestrator:
    def test_grants_respect_global_budget(self):
        cluster, _, pool_names = build_fleet(pools=8, budget="25%")  # 2
        orch = FleetOrchestrator(cluster, ROLLOUT)
        summary = orch.tick()
        assert summary["budget"] == 2
        assert summary["granted"] == 2
        raw = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT).raw
        assert len(pools_in_phase(raw, POOL_GRANTED)) == 2
        assert len(pools_in_phase(raw, POOL_PENDING)) == 6
        # Steady state: no further grants while nothing completes, and
        # the deferred pools are counted as budget denials.
        denials = orch.budget_denials
        orch.tick()
        assert orch.grants_issued == 2
        assert orch.budget_denials > denials

    def test_completion_frees_budget(self):
        cluster, _, pool_names = build_fleet(pools=4, budget=1)
        orch = FleetOrchestrator(cluster, ROLLOUT)
        orch.tick()
        raw = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT).raw
        granted = pools_in_phase(raw, POOL_GRANTED)
        assert len(granted) == 1
        obj = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT)
        from k8s_operator_libs_tpu.api import set_pool_phase

        set_pool_phase(obj.raw, granted[0], POOL_DONE)
        cluster.update_status(obj)
        orch.tick()
        raw = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT).raw
        assert len(pools_in_phase(raw, POOL_GRANTED)) == 1
        assert pool_phase(raw, granted[0]) == POOL_DONE

    def test_degraded_pools_granted_first(self):
        cluster, _, pool_names = build_fleet(pools=8, budget=2)
        for host in ("p5-h0", "p3-h1"):
            cluster.create(
                KubeObject(
                    make_node_health_report(
                        host, {"ring_allreduce": False},
                        {"ring_gbytes_per_s": 1.0, "probe_latency_s": 200.0},
                    )
                )
            )
        source = HealthSource(cluster)
        with source:
            agg = FleetHealthAggregator(pool_of)
            agg.add_source(source)
            orch = FleetOrchestrator(cluster, ROLLOUT, aggregator=agg)
            orch.tick()
        assert set(orch.grant_order) == {"p3", "p5"}, (
            "the two degraded pools must win the first grant batch"
        )

    def test_stateless_resume(self):
        cluster, _, pool_names = build_fleet(pools=6, budget=2)
        FleetOrchestrator(cluster, ROLLOUT).tick()
        raw = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT).raw
        first = set(pools_in_phase(raw, POOL_GRANTED))
        # A FRESH orchestrator (restart) re-derives everything from the
        # CR: same budget view, no duplicate grants, same ledger.
        second = FleetOrchestrator(cluster, ROLLOUT)
        summary = second.tick()
        assert summary["granted"] == 2 and not summary["new_grants"]
        raw = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT).raw
        assert set(pools_in_phase(raw, POOL_GRANTED)) == first

    def test_missing_rollout_is_a_noop(self):
        cluster = FakeCluster()
        orch = FleetOrchestrator(cluster, "nope")
        assert orch.tick() == {"missing": True}
        assert orch.grants_issued == 0


# ---------------------------------------------------------------------------
# Worker fleet e2e
# ---------------------------------------------------------------------------


class TestFleetRoll:
    def test_two_workers_roll_the_fleet_within_budget(self):
        cluster, sim, pool_names = build_fleet(pools=8, budget="25%")  # 2
        clock = Clock()
        idents = ("w-a", "w-b")
        workers = [
            make_worker(cluster, clock, ident, idents) for ident in idents
        ]
        orch = FleetOrchestrator(cluster, ROLLOUT)
        try:
            sim.set_template_hash("v2")
            iters, violations = drive_fleet(
                cluster, sim, orch, workers, clock, pool_names, budget=2
            )
            assert violations == 0
            assert_fleet_converged(cluster, sim)
            # Both workers participated and split the completions.
            assert all(w.passes > 0 for w in workers)
            assert sum(w.pools_reported_done for w in workers) == len(
                pool_names
            )
            assert all(w.pools_reported_done > 0 for w in workers)
            # Shard balance followed the worker-preference ring.
            owned = [sorted(w.owned_shards()) for w in workers]
            assert sorted(itertools.chain(*owned)) == [
                shard_id(0), shard_id(1)
            ]
        finally:
            for w in workers:
                w.stop()

    def test_worker_crash_mid_roll_fails_over_and_converges(self):
        """The ISSUE acceptance pin: kill a shard worker mid-roll (its
        lease expires), its shards are re-claimed, the roll completes,
        no node is lost, and the global budget holds across the
        handoff."""
        cluster, sim, pool_names = build_fleet(pools=8, budget="25%")
        clock = Clock()
        idents = ("w-a", "w-b")
        workers = [
            make_worker(cluster, clock, ident, idents) for ident in idents
        ]
        victim, survivor = workers
        orch = FleetOrchestrator(cluster, ROLLOUT)
        state = {"killed_at": None}

        def kill_mid_roll(i, active):
            # Kill the victim the first time one of ITS granted pools is
            # visibly mid-pipeline (some node cordoned) — a genuinely
            # half-rolled shard changes hands.
            if state["killed_at"] is None and disrupted_pools(cluster):
                victim_pools = {
                    p
                    for p in pool_names
                    if victim.pool_ring.owner(p) in victim.owned_shards()
                }
                if disrupted_pools(cluster) & victim_pools:
                    state["killed_at"] = i
                    state["victim_shards"] = victim.owned_shards()
                    return [survivor]  # stop ticking the victim (crash)
            return active

        try:
            sim.set_template_hash("v2")
            iters, violations = drive_fleet(
                cluster, sim, orch, workers, clock, pool_names,
                budget=2, mid_roll_hook=kill_mid_roll,
            )
            assert state["killed_at"] is not None, (
                "the victim was never killed mid-roll — dead scenario"
            )
            assert state["victim_shards"], "victim held no shards at kill"
            assert violations == 0, (
                "global budget violated across the failover handoff"
            )
            assert_fleet_converged(cluster, sim)
            # The survivor re-claimed the victim's shards via the stale
            # lease and finished the whole fleet.
            assert survivor.owned_shards() == frozenset(
                [shard_id(0), shard_id(1)]
            )
        finally:
            for w in workers:
                w.stop()

    @pytest.mark.parametrize(
        "verb,kind,exc_type",
        [
            (v, k, e)
            for (v, k), e in itertools.product(
                [("update", "Lease"), ("create", "Lease"),
                 ("update_status", "FleetRollout"),
                 ("get", "FleetRollout")],
                [ConflictError, ServerTimeoutError],
            )
        ],
        ids=lambda p: getattr(p, "__name__", str(p)),
    )
    def test_fleet_path_survives_transient_faults(self, verb, kind, exc_type):
        """Failure-injection matrix on the fleet coordination surfaces:
        lease campaigns and ledger reads/writes absorb transient
        Conflict/ServerTimeout and the roll still converges with the
        budget intact."""
        cluster, sim, pool_names = build_fleet(pools=4, budget=2)
        clock = Clock()
        idents = ("w-a", "w-b")
        workers = [
            make_worker(cluster, clock, ident, idents) for ident in idents
        ]
        orch = FleetOrchestrator(cluster, ROLLOUT)
        fault = Flaky(exc_type, times=4)
        injected = {"armed": False}
        # Lease CREATE happens exactly once per shard, at the very first
        # campaign round — the fault must be armed before it; the other
        # surfaces recur, so arming mid-roll exercises a live path.
        arm_at = 0 if verb == "create" else 2

        def arm(i, active):
            if i == arm_at and not injected["armed"]:
                injected["armed"] = True
                cluster.add_reactor(verb, kind, fault)
            return active

        try:
            sim.set_template_hash("v2")
            iters, violations = drive_fleet(
                cluster, sim, orch, workers, clock, pool_names,
                budget=2, mid_roll_hook=arm,
            )
            assert fault.fired > 0, (
                "fault point never exercised — dead parameter"
            )
            assert violations == 0
            assert_fleet_converged(cluster, sim)
        finally:
            for w in workers:
                w.stop()

    def test_single_worker_owns_everything_without_peers(self):
        cluster, sim, pool_names = build_fleet(pools=4, budget="100%")
        clock = Clock()
        worker = make_worker(
            cluster, clock, "solo", workers=("solo",), shards=3
        )
        orch = FleetOrchestrator(cluster, ROLLOUT)
        try:
            sim.set_template_hash("v2")
            iters, violations = drive_fleet(
                cluster, sim, orch, [worker], clock, pool_names, budget=4
            )
            assert worker.owned_shards() == frozenset(
                shard_id(i) for i in range(3)
            )
            assert_fleet_converged(cluster, sim)
        finally:
            worker.stop()


class TestDoneReportSafety:
    def test_requestor_mode_refuses_grant_gating(self):
        """Grant gating composes with the in-place strategy only: in
        maintenance-operator mode the orchestrator dispatches planning
        to the requestor, which would silently bypass the global budget
        — construction must refuse loudly."""
        from k8s_operator_libs_tpu.upgrade import (
            ClusterUpgradeStateManager,
            TaskRunner,
        )
        from k8s_operator_libs_tpu.upgrade.requestor import (
            RequestorOptions,
            enable_requestor_mode,
        )

        cluster = FakeCluster()
        mgr = ClusterUpgradeStateManager(
            cluster, DEVICE, runner=TaskRunner(inline=True)
        )
        enable_requestor_mode(
            mgr, RequestorOptions(use_maintenance_operator=True)
        )
        with pytest.raises(ValueError, match="grant gating"):
            ShardWorker(
                cluster,
                FleetWorkerConfig(
                    identity="x", shards=1, namespace=NS,
                    driver_labels=LABELS, rollout_name=ROLLOUT,
                ),
                manager=mgr,
            )

    def test_stale_revision_view_cannot_retire_a_grant(self):
        """Regression pin for the one stale read the level-driven
        machinery cannot heal: a worker whose ControllerRevision watch
        has not yet delivered the rollout's new revision sees every pod
        'current' and every node 'done' — it must NOT report its granted
        pools done (the ledger write is irreversible; an unrolled pool
        whose grant retired would never roll). The done report verifies
        pod currency against a LIVE revision read instead."""
        cluster, sim, pool_names = build_fleet(pools=4, budget="100%")
        clock = Clock()
        worker = make_worker(cluster, clock, "solo", workers=("solo",))
        orch = FleetOrchestrator(cluster, ROLLOUT)
        try:
            worker.tick(POLICY)  # claim + classify everyone done (v1)

            # Freeze the worker's revision view at the pre-rollout CRs:
            # the informer-backed read the pod manager consults stays
            # stale while the CLUSTER moves on to the new revision.
            stale = [
                cr for cr in worker.source.controller_revisions(NS, LABELS)
            ]
            worker.source.controller_revisions = (
                lambda namespace, labels: list(stale)
            )
            sim.set_template_hash("v2")
            orch.tick()  # grants land against the new revision
            for _ in range(6):
                clock.advance(0.6)
                worker.tick(POLICY)
            # The stale view says "nothing to roll" — but no grant may
            # retire: the live read sees v2 vs rev-1 pods.
            raw = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT).raw
            assert pools_in_phase(raw, POOL_DONE) == []
            assert worker.pools_reported_done == 0
        finally:
            worker.stop()

    def test_ghost_pool_grant_is_retired_not_leaked(self):
        """Review pin: a granted pool with no nodes anywhere (operator
        typo in spec.pools, or its nodes deleted after the grant) must
        be retired as vacuously done by its shard's owner — a leaked
        grant would hold a global budget slot forever, and enough
        ghosts would deadlock the rollout. Budget 1 + a ghost granted
        first = the full deadlock scenario; the roll must still
        converge."""
        cluster, sim, pool_names = build_fleet(pools=3, budget=1)
        # Widen the roll set with a pool no node belongs to, named so
        # the health-less orchestrator (sorted order) grants it FIRST —
        # the worst case: the single budget slot goes to the ghost.
        obj = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT)
        obj.raw["spec"]["pools"] = ["a-ghost"] + list(pool_names)
        cluster.update(obj)
        clock = Clock()
        worker = make_worker(cluster, clock, "solo", workers=("solo",))
        orch = FleetOrchestrator(cluster, ROLLOUT)
        try:
            sim.set_template_hash("v2")
            iters, violations = drive_fleet(
                cluster, sim, orch, [worker], clock,
                ["a-ghost"] + list(pool_names), budget=1,
            )
            assert violations == 0
            assert_fleet_converged(cluster, sim)
            raw = cluster.get(FLEET_ROLLOUT_KIND, ROLLOUT).raw
            assert pool_phase(raw, "a-ghost") == POOL_DONE
        finally:
            worker.stop()
