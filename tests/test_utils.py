"""Tests for concurrency primitives and int-or-percent scaling.

Coverage model: reference pkg/upgrade/util.go (StringSet/KeyedMutex) and the
maxUnavailable scaling behavior of upgrade_inplace.go:54-60.
"""

import threading

import pytest

from k8s_operator_libs_tpu.utils import IntOrString, KeyedMutex, StringSet


class TestStringSet:
    def test_add_has_remove(self):
        s = StringSet()
        assert "a" not in s
        s.add("a")
        assert "a" in s and s.has("a")
        assert len(s) == 1
        s.remove("a")
        assert "a" not in s

    def test_remove_missing_is_noop(self):
        s = StringSet()
        s.remove("missing")
        assert len(s) == 0

    def test_concurrent_adds(self):
        s = StringSet()

        def worker(base):
            for i in range(200):
                s.add(f"{base}-{i}")

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(s) == 8 * 200

    def test_snapshot_is_frozen(self):
        s = StringSet()
        s.add("x")
        snap = s.snapshot()
        s.add("y")
        assert snap == frozenset({"x"})


class TestKeyedMutex:
    def test_serializes_same_key(self):
        km = KeyedMutex()
        order = []

        def worker(tag):
            with km.locked("node-1"):
                order.append((tag, "enter"))
                order.append((tag, "exit"))

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Entries and exits must be properly nested per-holder.
        for i in range(0, len(order), 2):
            assert order[i][0] == order[i + 1][0]
            assert order[i][1] == "enter" and order[i + 1][1] == "exit"

    def test_different_keys_do_not_block(self):
        km = KeyedMutex()
        release = threading.Event()
        entered = threading.Event()

        def holder():
            with km.locked("a"):
                entered.set()
                release.wait(timeout=5)

        t = threading.Thread(target=holder)
        t.start()
        assert entered.wait(timeout=5)
        # Should acquire immediately; a deadlock here would hang the test.
        with km.locked("b"):
            pass
        release.set()
        t.join()


class TestIntOrString:
    def test_int_passthrough(self):
        assert IntOrString(5).scaled_value(100) == 5
        assert not IntOrString(5).is_percent

    def test_percent_rounds_up(self):
        # 25% of 3 nodes -> ceil(0.75) = 1 (reference default "25%").
        assert IntOrString("25%").scaled_value(3) == 1
        assert IntOrString("25%").scaled_value(16) == 4
        assert IntOrString("50%").scaled_value(5) == 3

    def test_percent_round_down(self):
        assert IntOrString("50%").scaled_value(5, round_up=False) == 2

    def test_numeric_string(self):
        v = IntOrString("7")
        assert not v.is_percent
        assert v.scaled_value(10) == 7

    @pytest.mark.parametrize("bad", ["abc", "-5%", "-5", -1, "%", None, 1.5])
    def test_invalid(self, bad):
        with pytest.raises((ValueError, TypeError)):
            IntOrString(bad)

    def test_parse_helpers(self):
        assert IntOrString.parse(None) is None
        v = IntOrString.parse("25%")
        assert v is not None and v.is_percent
        assert IntOrString.parse(v) is v
