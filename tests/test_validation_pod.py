"""Validation-pod deployment shape: the framework provisions the probe pod.

Reference semantics under test: validation gates uncordon on a pod matching
pod_selector becoming Ready on the node (validation_manager.go:71-116) —
but here the framework itself creates that pod (tpu/validation_pod.py), the
simulated kubelet (ValidationPodSimulator) runs its payload, and readiness
follows probe success/failure.
"""

import time

from k8s_operator_libs_tpu.api import DriverUpgradePolicySpec
from k8s_operator_libs_tpu.kube import FakeCluster, Node, Pod
from k8s_operator_libs_tpu.kube.sim import DaemonSetSimulator, ValidationPodSimulator
from k8s_operator_libs_tpu.tpu import ValidationPodManager, ValidationPodSpec
from k8s_operator_libs_tpu.tpu.validation_pod import READY_FILE, VALIDATION_APP
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    TaskRunner,
    UpgradeKeys,
)
from k8s_operator_libs_tpu.utils import IntOrString

DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
NS = "kube-system"
DS_LABELS = {"app": "libtpu-installer"}


def make_ready_node(cluster, name):
    node = Node.new(name)
    node.set_ready(True)
    cluster.create(node)
    return node


class TestPodShape:
    def test_build_pod_pins_node_and_requests_tpus(self):
        mgr = ValidationPodManager(FakeCluster(), ValidationPodSpec(tpu_chips=4))
        pod = mgr.build_pod("node-a")
        assert pod.node_name == "node-a"
        assert pod.labels["app"] == VALIDATION_APP
        assert pod.spec["restartPolicy"] == "Never"
        container = pod.spec["containers"][0]
        assert container["resources"]["limits"]["google.com/tpu"] == "4"
        # Tolerates the TPU taint so kubelet admits it on a TPU node.
        assert any(
            t.get("key") == "google.com/tpu" for t in pod.spec["tolerations"]
        )
        # Readiness = probe success: the readinessProbe watches the marker
        # file the payload writes on pass.
        probe = container["readinessProbe"]["exec"]["command"]
        assert READY_FILE in probe
        assert "--ready-file" in container["command"]
        assert "k8s_operator_libs_tpu.tpu.health" in container["command"]

    def test_command_serializes_floors(self):
        spec = ValidationPodSpec(
            min_ring_gbytes_per_s=12.5, min_mxu_tflops=40.0
        )
        cmd = spec.probe_command()
        assert "--min-ring-gbps" in cmd and "12.5" in cmd
        assert "--min-mxu-tflops" in cmd and "40.0" in cmd

    def test_pod_selector_matches_pod_labels(self):
        spec = ValidationPodSpec()
        pod = ValidationPodManager(FakeCluster(), spec).build_pod("n")
        key, value = spec.pod_selector.split("=")
        assert pod.labels[key] == value


class TestEnsureAndCleanup:
    def test_ensure_creates_once(self):
        cluster = FakeCluster()
        node = make_ready_node(cluster, "node-a")
        mgr = ValidationPodManager(cluster, ValidationPodSpec())
        first = mgr.ensure(node)
        again = mgr.ensure(node)
        assert first.name == again.name
        assert len(cluster.list("Pod", namespace=NS)) == 1

    def test_ensure_replaces_finished_pod(self):
        cluster = FakeCluster()
        node = make_ready_node(cluster, "node-a")
        mgr = ValidationPodManager(cluster, ValidationPodSpec())
        pod = mgr.ensure(node)
        cluster.patch("Pod", pod.name, NS, patch={"status": {"phase": "Failed"}})
        fresh = mgr.ensure(node)
        assert fresh.phase != "Failed"

    def test_cleanup_is_idempotent(self):
        cluster = FakeCluster()
        node = make_ready_node(cluster, "node-a")
        mgr = ValidationPodManager(cluster, ValidationPodSpec())
        mgr.ensure(node)
        mgr.cleanup(node)
        mgr.cleanup(node)  # second delete: no NotFoundError escapes
        assert cluster.list("Pod", namespace=NS) == []


def build_pool(n=3):
    cluster = FakeCluster()
    for i in range(n):
        make_ready_node(cluster, f"node-{i}")
    sim = DaemonSetSimulator(
        cluster,
        name="libtpu-installer",
        namespace=NS,
        match_labels=DS_LABELS,
        initial_hash="v1",
    )
    sim.settle()
    return cluster, sim


def make_manager(cluster, provisioner, timeout_seconds=None):
    mgr = ClusterUpgradeStateManager(
        cluster, DEVICE, runner=TaskRunner(inline=True)
    )
    kwargs = {}
    if timeout_seconds is not None:
        kwargs["timeout_seconds"] = timeout_seconds
    mgr.with_validation_enabled(pod_provisioner=provisioner, **kwargs)
    return mgr


POLICY = DriverUpgradePolicySpec(
    auto_upgrade=True,
    max_parallel_upgrades=0,
    max_unavailable=IntOrString("100%"),
)


class TestEndToEnd:
    def test_roll_gated_by_framework_provisioned_pods(self):
        cluster, sim = build_pool()
        spec = ValidationPodSpec()
        provisioner = ValidationPodManager(cluster, spec)
        vps = ValidationPodSimulator(cluster, namespace=spec.namespace)
        mgr = make_manager(cluster, provisioner)

        sim.set_template_hash("v2")
        saw_probe_pod = False
        for _ in range(40):
            sim.step()
            vps.step()
            state = mgr.build_state(NS, DS_LABELS)
            mgr.apply_state(state, POLICY)
            sim.step()
            if cluster.list("Pod", namespace=NS, label_selector=spec.pod_selector):
                saw_probe_pod = True
            if all(
                n.labels.get(KEYS.state_label) == "upgrade-done"
                for n in cluster.list("Node")
            ) and sim.all_pods_ready_and_current():
                break
        else:
            raise AssertionError("roll did not converge")
        # Validation really happened through pods the framework created...
        assert saw_probe_pod
        # ...and passed probes were cleaned up, releasing the TPU chips.
        assert (
            cluster.list("Pod", namespace=NS, label_selector=spec.pod_selector)
            == []
        )
        # No node skipped the cordon/validate cycle.
        for node in cluster.list("Node"):
            assert not Node(node.raw).unschedulable

    def test_unhealthy_node_fails_validation(self):
        cluster, sim = build_pool(n=2)
        spec = ValidationPodSpec()
        provisioner = ValidationPodManager(cluster, spec)

        def decide(pod: Pod) -> bool:
            return pod.node_name != "node-0"  # node-0's fabric is broken

        vps = ValidationPodSimulator(
            cluster, namespace=spec.namespace, decide=decide
        )
        mgr = make_manager(cluster, provisioner, timeout_seconds=0)

        sim.set_template_hash("v2")

        def one_pass():
            sim.step()
            vps.step()
            state = mgr.build_state(NS, DS_LABELS)
            mgr.apply_state(state, POLICY)
            sim.step()
            return {
                n.name: n.labels.get(KEYS.state_label)
                for n in cluster.list("Node")
            }

        saw_failed = False
        deadline = time.time() + 30
        while time.time() < deadline:
            labels = one_pass()
            saw_failed = saw_failed or labels.get("node-0") == "upgrade-failed"
            if saw_failed and labels.get("node-1") == "upgrade-done":
                break
            # the zero-second validation timeout still needs the wall clock
            # to advance one whole second between passes
            time.sleep(0.35)
        else:
            raise AssertionError(
                "expected node-0 to hit upgrade-failed and node-1 to finish"
            )
        # The broken node must stay quarantined: auto-recovery routes a
        # validation failure back through the gate (which keeps failing),
        # NOT around it — it cycles validation-required ↔ upgrade-failed,
        # cordoned throughout, and never reaches upgrade-done.
        for _ in range(6):
            labels = one_pass()
            assert labels["node-0"] in ("validation-required", "upgrade-failed")
            assert Node(cluster.get("Node", "node-0").raw).unschedulable
            time.sleep(0.25)


class TestRealPayloadExecution:
    """VERDICT r3 item 1 (closing the loop): readiness comes from the REAL
    payload process — `health.main()` in a subprocess writes the
    ready-file, the simulated kubelet's exec readinessProbe reads it, and
    only then does the node uncordon. No scripted verdict anywhere."""

    def _cheap_spec(self, **overrides):
        kwargs = dict(
            payload_mb=0.05,
            matmul_size=64,
            min_ring_gbytes_per_s=0.0,
            min_mxu_tflops=0.0,
            use_pallas_matmul=False,
            run_flash_attention=False,
            run_seq_parallel_probes=False,
            run_burnin=False,
            compile_cache_dir="",
        )
        kwargs.update(overrides)
        return ValidationPodSpec(**kwargs)

    def _drive(self, spec, n=1, budget_s=240.0):
        from k8s_operator_libs_tpu.kube.sim import KubeletPayloadExecutor
        from k8s_operator_libs_tpu.utils.jaxenv import hermetic_cpu_env

        cluster, sim = build_pool(n=n)
        provisioner = ValidationPodManager(cluster, spec)
        executor = KubeletPayloadExecutor(
            env=hermetic_cpu_env(4),
            extra_args=["--no-compile-cache"],
            timeout_seconds=budget_s,
        )
        vps = ValidationPodSimulator(
            cluster, namespace=spec.namespace, executor=executor
        )
        mgr = make_manager(cluster, provisioner, timeout_seconds=600)
        sim.set_template_hash("v2")
        deadline = time.monotonic() + budget_s
        ready_contents: dict[str, str] = {}

        def snapshot_ready_files():
            for pod_name in executor.tracked_pods():
                content = executor.ready_file_content(pod_name)
                if content is not None:
                    ready_contents[pod_name] = content

        with executor:
            # Loop on the DEADLINE, never a pass cap: the real JAX child's
            # wall-clock is load-dependent, and a pass cap binds long
            # before the budget on a busy machine (VERDICT r4 weak #1 —
            # 40 passes × 0.5 s ≈ 25 s of loop against a 240 s budget).
            while True:
                sim.step()
                vps.step()
                snapshot_ready_files()
                state = mgr.build_state(NS, DS_LABELS)
                mgr.apply_state(state, POLICY)
                sim.step()
                labels = {
                    n_.name: n_.labels.get(KEYS.state_label)
                    for n_ in cluster.list("Node")
                }
                if all(v == "upgrade-done" for v in labels.values()) and (
                    sim.all_pods_ready_and_current()
                ):
                    break
                if time.monotonic() > deadline:
                    break
                # The battery takes seconds; don't spin passes dry.
                time.sleep(0.5)
        return cluster, executor, labels, ready_contents

    def test_uncordon_gated_by_real_payload_process(self):
        spec = self._cheap_spec()
        cluster, executor, labels, ready_contents = self._drive(spec)
        assert labels == {"node-0": "upgrade-done"}, labels
        # The verdict came from a real child process passing the battery.
        assert executor.history, "no payload process ever ran"
        assert all(executor.history.values())
        content = ready_contents.get(f"{VALIDATION_APP}-node-0")
        assert content is not None and "ok=True" in content
        assert not Node(cluster.get("Node", "node-0").raw).unschedulable

    def test_real_payload_floor_violation_fails_validation(self):
        # An impossible MXU floor: the probe battery runs fine but the
        # real child exits 1 without writing the ready-file, so the pod
        # goes Failed and the node stays cordoned; once the validation
        # timeout lapses, it lands in upgrade-failed — the failure path
        # through the same real chain.
        from k8s_operator_libs_tpu.kube.sim import KubeletPayloadExecutor
        from k8s_operator_libs_tpu.utils.jaxenv import hermetic_cpu_env

        spec = self._cheap_spec(min_mxu_tflops=1e9)
        cluster, sim = build_pool(n=1)
        provisioner = ValidationPodManager(cluster, spec)
        executor = KubeletPayloadExecutor(
            env=hermetic_cpu_env(4),
            extra_args=["--no-compile-cache"],
            timeout_seconds=240.0,
        )
        vps = ValidationPodSimulator(
            cluster, namespace=spec.namespace, executor=executor
        )
        # Long timeout while the real battery runs: the node must fail on
        # the payload's VERDICT lapsing the clock, not on a clock that
        # expires before the payload ever finishes.
        mgr = make_manager(cluster, provisioner, timeout_seconds=600)
        sim.set_template_hash("v2")
        deadline = time.monotonic() + 240.0
        pod_name = f"{VALIDATION_APP}-node-0"

        def one_pass():
            sim.step()
            vps.step()
            state = mgr.build_state(NS, DS_LABELS)
            mgr.apply_state(state, POLICY)
            sim.step()
            return Node(cluster.get("Node", "node-0").raw)

        with executor:
            # Phase 1: the real child runs the battery, misses the floor,
            # exits 1 with no ready-file; the kubelet marks the pod Failed.
            while True:
                node = one_pass()
                if executor.history.get(pod_name) is not None:
                    break
                assert time.monotonic() < deadline, "battery never finished"
                time.sleep(0.5)
            assert executor.history[pod_name] is False
            # (The Failed pod itself is promptly REPLACED by ensure() so
            # every validation attempt gets a live probe — assert the
            # node-level consequences, which are the gate's contract.)
            node = one_pass()
            assert node.labels.get(KEYS.state_label) == "validation-required"
            assert node.unschedulable  # wounded node stays quarantined
            # Phase 2: the validation timeout lapses (shrunk to 0 now that
            # the real verdict is in) -> upgrade-failed.
            mgr.common.validation_manager._timeout = 0
            for _ in range(10):
                node = one_pass()
                if node.labels.get(KEYS.state_label) == "upgrade-failed":
                    break
                time.sleep(0.35)  # the 0s timeout still needs 1 wall second
            else:
                raise AssertionError("never reached upgrade-failed")
            assert node.unschedulable


class TestHealthCli:
    def test_payload_writes_ready_file_on_pass(self, tmp_path):
        from k8s_operator_libs_tpu.tpu.health import main

        ready = tmp_path / "ready"
        rc = main(
            [
                "--no-burnin",
                "--no-compile-cache",
                "--payload-mb", "0.05",
                "--matmul-size", "64",
                "--ready-file", str(ready),
            ]
        )
        assert rc == 0
        assert "ok=True" in ready.read_text()

    def test_payload_exits_nonzero_on_floor_violation(self, tmp_path, capsys):
        import json

        from k8s_operator_libs_tpu.tpu.health import main

        ready = tmp_path / "ready"
        # An impossible MXU floor: the probe runs fine but the measured
        # TFLOP/s can never reach it, so the gate must fail closed.
        rc = main(
            [
                "--no-burnin",
                "--no-compile-cache",
                "--payload-mb", "0.05",
                "--matmul-size", "64",
                "--min-mxu-tflops", "1e9",
                "--ready-file", str(ready),
            ]
        )
        assert rc == 1
        assert not ready.exists()
        report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert report["ok"] is False
        assert any("below floor" in f for f in report["failures"])


class TestCompileCache:
    def test_pod_mounts_host_compile_cache(self):
        from k8s_operator_libs_tpu.tpu.health import HEALTH_CACHE_DIR

        pod = ValidationPodManager(FakeCluster(), ValidationPodSpec()).build_pod("n")
        vol = pod.spec["volumes"][0]
        assert vol["hostPath"]["path"] == HEALTH_CACHE_DIR
        # Root-owned parent, never /tmp: a predictable world-writable
        # path invites cache poisoning of the privileged probe.
        assert HEALTH_CACHE_DIR.startswith("/var/cache/")
        container = pod.spec["containers"][0]
        assert {"name": "jax-cache", "mountPath": HEALTH_CACHE_DIR} in container[
            "volumeMounts"
        ]
        assert {
            "name": "JAX_COMPILATION_CACHE_DIR",
            "value": HEALTH_CACHE_DIR,
        } in container["env"]

    def test_cache_mount_can_be_disabled(self):
        pod = ValidationPodManager(
            FakeCluster(), ValidationPodSpec(compile_cache_dir="")
        ).build_pod("n")
        assert "volumes" not in pod.spec
        assert pod.spec["containers"][0]["env"] == []

    def test_cli_enables_cache_before_probing(self, tmp_path, monkeypatch):
        import jax

        from k8s_operator_libs_tpu.tpu.health import main

        cache = tmp_path / "xla-cache"
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(cache))
        prev = jax.config.jax_compilation_cache_dir
        try:
            rc = main(
                ["--no-burnin", "--payload-mb", "0.05", "--matmul-size", "64"]
            )
            assert rc == 0
            assert jax.config.jax_compilation_cache_dir == str(cache)
        finally:
            # jax config is process-global; leaking a pytest tmp_path as
            # the cache dir would make later tests order-dependent.
            jax.config.update("jax_compilation_cache_dir", prev)
