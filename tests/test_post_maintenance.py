"""post-maintenance-required, implemented for real (VERDICT r4 #7).

The reference declares the state and TODOs its adoption
(upgrade_state.go:249-250); this framework completes the flow behind
``RequestorOptions.use_post_maintenance``: maintenance-Ready nodes pass
through post-maintenance-required — where the hook runs on a node that is
still cordoned and drained (chips free; e.g. XLA compile-cache prefill) —
before pod-restart-required. Enabling the knob also makes the budget
count BOTH maintenance states as in-progress, resolving the reference's
accounting quirk (common_manager.go:714-731) that the base mode keeps
for parity.
"""

import time

from k8s_operator_libs_tpu.api import DrainSpec, DriverUpgradePolicySpec
from k8s_operator_libs_tpu.kube import FakeCluster, Node
from k8s_operator_libs_tpu.kube.sim import (
    DaemonSetSimulator,
    MaintenanceOperatorSimulator,
)
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    DeviceClass,
    RequestorOptions,
    TaskRunner,
    UpgradeKeys,
    enable_requestor_mode,
)
from k8s_operator_libs_tpu.utils import IntOrString
from builders import make_node

DEVICE = DeviceClass.tpu()
KEYS = UpgradeKeys(DEVICE)
NS = "driver-ns"
LABELS = {"app": "libtpu-installer"}
MAINT_NS = "maintenance-ns"

POLICY = DriverUpgradePolicySpec(
    auto_upgrade=True,
    max_parallel_upgrades=0,
    max_unavailable=IntOrString("100%"),
    drain=DrainSpec(enable=True, force=True, timeout_seconds=120),
)


def build_harness(node_count=2, **opt_overrides):
    cluster = FakeCluster()
    for i in range(node_count):
        cluster.create(make_node(f"node-{i}"))
    sim = DaemonSetSimulator(
        cluster, name="libtpu-installer", namespace=NS, match_labels=LABELS
    )
    sim.settle()
    opts = RequestorOptions(
        use_maintenance_operator=True,
        use_post_maintenance=True,
        namespace=MAINT_NS,
        **opt_overrides,
    )
    mgr = ClusterUpgradeStateManager(
        cluster, DEVICE, runner=TaskRunner(inline=True)
    )
    enable_requestor_mode(mgr, opts)
    operator = MaintenanceOperatorSimulator(cluster, namespace=MAINT_NS)
    return cluster, sim, mgr, operator


def labels_of(cluster):
    return {
        n.name: n.labels.get(KEYS.state_label)
        for n in cluster.list("Node")
    }


def drive(cluster, sim, mgr, operator, policy=POLICY, max_passes=80,
          observe=None):
    for i in range(max_passes):
        sim.step()
        operator.step()
        state = mgr.build_state(NS, LABELS)
        mgr.apply_state(state, policy)
        sim.step()
        if observe is not None:
            observe(state)
        done = all(
            v == "upgrade-done" for v in labels_of(cluster).values()
        )
        if done and sim.all_pods_ready_and_current():
            operator.step()
            return i + 1
    raise AssertionError(
        f"roll did not converge; labels={labels_of(cluster)}"
    )


class TestFlow:
    def test_nodes_pass_through_post_maintenance(self):
        cluster, sim, mgr, operator = build_harness()
        seen_states: set[str] = set()
        hook_calls: list[tuple[str, bool]] = []
        # The hook observes the post-maintenance contract: node still
        # cordoned (drained, chips free) when the work runs.
        def hook(node):
            hook_calls.append((node.name, node.unschedulable))
            return True

        mgr.requestor.opts.post_maintenance_hook = hook
        sim.set_template_hash("v2")

        def observe(state):
            for value in labels_of(cluster).values():
                if value:
                    seen_states.add(value)

        drive(cluster, sim, mgr, operator, observe=observe)
        assert "post-maintenance-required" in seen_states
        assert {name for name, _ in hook_calls} == {"node-0", "node-1"}
        assert all(cordoned for _, cordoned in hook_calls)
        # Clean terminal state: no leftover clock annotations.
        for obj in cluster.list("Node"):
            node = Node(obj.raw)
            assert (
                KEYS.post_maintenance_start_annotation not in node.annotations
            )
            assert not node.unschedulable

    def test_disabled_knob_skips_the_state(self):
        cluster, sim, mgr, operator = build_harness()
        mgr.requestor.opts.use_post_maintenance = False
        mgr.common.count_maintenance_states = False
        seen: set[str] = set()
        sim.set_template_hash("v2")
        drive(
            cluster, sim, mgr, operator,
            observe=lambda s: seen.update(
                v for v in labels_of(cluster).values() if v
            ),
        )
        assert "post-maintenance-required" not in seen

    def test_not_done_hook_retries_then_completes(self):
        cluster, sim, mgr, operator = build_harness(node_count=1)
        attempts = {"n": 0}

        def hook(node):
            attempts["n"] += 1
            return attempts["n"] >= 3  # done on the third pass

        mgr.requestor.opts.post_maintenance_hook = hook
        sim.set_template_hash("v2")
        drive(cluster, sim, mgr, operator)
        assert attempts["n"] >= 3
        node = Node(cluster.get("Node", "node-0").raw)
        assert node.labels[KEYS.state_label] == "upgrade-done"

    def test_timeout_fails_the_node(self):
        cluster, sim, mgr, operator = build_harness(node_count=1)
        mgr.requestor.opts.post_maintenance_hook = lambda node: False
        mgr.requestor.opts.post_maintenance_timeout_seconds = 0
        sim.set_template_hash("v2")
        deadline = time.time() + 30
        while time.time() < deadline:
            sim.step()
            operator.step()
            state = mgr.build_state(NS, LABELS)
            mgr.apply_state(state, POLICY)
            sim.step()
            labels = labels_of(cluster)
            if labels.get("node-0") == "upgrade-failed":
                break
            time.sleep(0.3)  # the 0s timeout still needs 1 wall second
        else:
            raise AssertionError(
                f"node never failed; labels={labels_of(cluster)}"
            )
        node = Node(cluster.get("Node", "node-0").raw)
        assert node.unschedulable  # quarantined, like a validation timeout
        assert KEYS.post_maintenance_start_annotation not in node.annotations

    def test_hook_crash_counts_as_not_done(self):
        cluster, sim, mgr, operator = build_harness(node_count=1)
        calls = {"n": 0}

        def hook(node):
            calls["n"] += 1
            if calls["n"] < 2:
                raise RuntimeError("warm-up infra hiccup")
            return True

        mgr.requestor.opts.post_maintenance_hook = hook
        sim.set_template_hash("v2")
        drive(cluster, sim, mgr, operator)
        assert calls["n"] >= 2


class TestBudgetAccounting:
    def test_maintenance_states_count_as_in_progress_with_knob(self):
        """maxParallel=1: while node A sits in node-maintenance-required
        (operator working), node B must NOT start — the honest accounting
        the reference's exclusion quirk (common_manager.go:714-731)
        forfeits."""
        cluster, sim, mgr, operator = build_harness(node_count=2)
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=1,
            max_unavailable=IntOrString("100%"),
            drain=DrainSpec(enable=True, force=True),
        )
        # A sluggish operator: nobody advances the CRs, so they sit
        # un-Ready for the whole window.
        sim.set_template_hash("v2")
        both_in_maintenance = False
        for _ in range(8):
            sim.step()
            state = mgr.build_state(NS, LABELS)
            mgr.apply_state(state, policy)
            sim.step()
            labels = labels_of(cluster)
            in_maint = [
                n for n, v in labels.items()
                if v in ("node-maintenance-required",
                         "post-maintenance-required")
            ]
            if len(in_maint) > 1:
                both_in_maintenance = True
        assert not both_in_maintenance, (
            "budget admitted a second node while the first was under "
            "external maintenance"
        )

    def test_base_mode_keeps_reference_quirk(self):
        """Parity guard: with the knob off, maintenance states stay
        excluded (test_consts pins MANAGED_STATES itself)."""
        cluster, sim, mgr, operator = build_harness(node_count=2)
        mgr.requestor.opts.use_post_maintenance = False
        mgr.common.count_maintenance_states = False
        policy = DriverUpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=1,
            max_unavailable=IntOrString("100%"),
            drain=DrainSpec(enable=True, force=True),
        )
        sim.set_template_hash("v2")
        saw_second_start = False
        for _ in range(8):
            sim.step()
            state = mgr.build_state(NS, LABELS)
            mgr.apply_state(state, policy)
            sim.step()
            labels = labels_of(cluster)
            in_maint = [
                n for n, v in labels.items()
                if v == "node-maintenance-required"
            ]
            if len(in_maint) > 1:
                saw_second_start = True
        assert saw_second_start, (
            "reference parity: base mode does not reserve budget for "
            "nodes under external maintenance"
        )


class TestEnv:
    def test_from_env_reads_post_maintenance_flag(self, monkeypatch):
        monkeypatch.setenv("MAINTENANCE_OPERATOR_ENABLED", "true")
        monkeypatch.setenv("MAINTENANCE_OPERATOR_POST_MAINTENANCE", "true")
        opts = RequestorOptions.from_env()
        assert opts.use_post_maintenance is True
        monkeypatch.delenv("MAINTENANCE_OPERATOR_POST_MAINTENANCE")
        assert RequestorOptions.from_env().use_post_maintenance is False


class TestWarmupHook:
    def test_cache_warmup_hook_runs_gate_and_always_reports_done(self):
        from k8s_operator_libs_tpu.tpu import cache_warmup_hook
        from k8s_operator_libs_tpu.tpu.health import HealthReport

        class FakeGate:
            def __init__(self, ok):
                self.ok = ok
                self.runs = 0

            def run(self):
                self.runs += 1
                return HealthReport(ok=self.ok)

        node = Node.new("n0")
        passing = FakeGate(ok=True)
        assert cache_warmup_hook(passing)(node) is True
        assert passing.runs == 1
        # A failed battery is the validation gate's business, not the
        # warm-up's: the hook still reports done.
        failing = FakeGate(ok=False)
        assert cache_warmup_hook(failing)(node) is True
