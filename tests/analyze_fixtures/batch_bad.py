"""Seeded write-batching violation: the batch flush — a pipelined wire
round trip that can also park the thread on a follower's event — runs
INSIDE the per-node keyed mutex. This is exactly the shape the
provider's split critical section exists to avoid (stage outside, rejoin
inside); LCK111 must flag the blocking chain with the keyed identity.

Analyzer fixture — analyzed as text by tests/test_analyze.py, never
imported.
"""

import threading
import time
from contextlib import contextmanager


class KeyedMutex:
    def __init__(self):
        self._guard = threading.Lock()
        self._locks = {}

    @contextmanager
    def locked(self, key):
        with self._guard:
            lock = self._locks.setdefault(key, threading.Lock())
        lock.acquire()
        try:
            yield
        finally:
            lock.release()


class Batcher:
    def __init__(self):
        self._pending = []

    def stage(self, name, patch):
        self._pending.append((name, patch))
        return self._flush()

    def _flush(self):
        batch, self._pending = self._pending, []
        time.sleep(0.001)  # the pipelined wire round trip
        return len(batch)


class BadBatchedWriter:
    def __init__(self):
        self._mutex = KeyedMutex()
        self._batcher = Batcher()

    def write(self, name, patch):
        with self._mutex.locked(name):
            # LCK111: stage -> _flush blocks while the node's keyed
            # mutex is held — every same-node writer stalls behind the
            # whole batch's round trip.
            return self._batcher.stage(name, patch)
