"""Seeded LIF8xx violations: every leg of the lifecycle discipline
(docs/daemon-lifecycle.md) broken once, with exact per-code counts
pinned by test_analyze.py.

* LIF801 ×3 — ``LeakyOwner`` starts a Pump its stop() never releases
  (the release is behind a helper that forgets); ``NoShutdownOwner``
  acquires with no shutdown method at all; ``DeepOwner`` releases one
  of its two pumps and leaks the other.
* LIF802 ×3 — a local Stream never released, one acquired in the gap
  BEFORE the protecting try/finally (the bench-informer bug class),
  and one whose release a raising call can skip (no finally).
* LIF803 ×3 — ``NeverJoins`` starts a non-daemon thread its stop()
  never joins; ``fire_and_forget`` leaks a local non-daemon thread;
  ``JoinsUnbounded`` joins with no timeout on the shutdown path.
* LIF804 ×1 — a frame stopping the WatchHub before the Informer it
  feeds (release order must reverse the dependency DAG).
* LIF805 ×3 — signal handlers that block, take a lock, and touch the
  event loop (a handler may only set an event).
"""

import signal
import threading
import time


def lifecycle_resource(acquire="start", release="stop"):
    def deco(cls):
        return cls

    return deco


@lifecycle_resource(acquire="start", release="stop")
class Pump:
    def start(self):
        ...

    def stop(self):
        ...


@lifecycle_resource(acquire="__init__", release=("stop", "close"))
class Stream:
    def __init__(self, client):
        self.client = client

    def read(self):
        ...

    def stop(self):
        ...

    def close(self):
        ...


@lifecycle_resource(acquire="__init__", release="stop")
class WatchHub:
    def __init__(self, client):
        self.client = client

    def stop(self):
        ...


@lifecycle_resource(acquire="start", release="stop")
class Informer:
    def __init__(self, hub):
        self.hub = hub

    def start(self):
        ...

    def stop(self):
        ...


def prime(stream):
    ...


def pump_once(stream):
    ...


def risky(stream):
    ...


def poll(informer):
    ...


def noop():
    ...


# -- LIF801: owned resources with no reachable release ---------------------


class LeakyOwner:
    def __init__(self):
        self._pump = Pump()
        self._running = False

    def start(self):
        self._pump.start()  # LIF801: stop() never reaches _pump.stop()
        self._running = True

    def stop(self):
        self._halt()

    def _halt(self):
        self._running = False  # forgets the pump


class NoShutdownOwner:
    def __init__(self):
        self._pump = Pump()

    def start(self):
        self._pump.start()  # LIF801: no shutdown method anywhere


class DeepOwner:
    def __init__(self):
        self._a = Pump()
        self._b = Pump()

    def start(self):
        self._a.start()
        self._b.start()  # LIF801: stop() releases _a but leaks _b

    def stop(self):
        self._a.stop()


# -- LIF802: same-frame exception-path leaks -------------------------------


def leak_local(client):
    stream = Stream(client)  # LIF802: never released, never escapes
    stream.read()


def gap_before_try(client):
    stream = Stream(client)  # LIF802: prime() can raise in the gap
    prime(stream)
    try:
        pump_once(stream)
    finally:
        stream.close()


def release_not_in_finally(client):
    stream = Stream(client)  # LIF802: risky() can skip the release
    risky(stream)
    stream.stop()


# -- LIF803: unjoined / unbounded threads ----------------------------------


class NeverJoins:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(  # LIF803: stop() never joins
            target=self._run, name="never-joined"
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _run(self):
        self._stop.wait(1.0)


class JoinsUnbounded:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, name="unbounded")
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join()  # LIF803: no timeout — unbounded shutdown

    def _run(self):
        self._stop.wait(1.0)


def fire_and_forget(work):
    worker = threading.Thread(target=work)  # LIF803: never joined
    worker.start()


# -- LIF804: releases out of dependency order ------------------------------


def stop_order_violation(client):
    hub = informer = None
    try:
        hub = WatchHub(client)
        informer = Informer(hub)
        informer.start()
        poll(informer)
    finally:
        hub.stop()  # LIF804: the hub feeds the informer — stop it last
        informer.stop()


# -- LIF805: signal handlers doing more than setting an event --------------


class BlockingHandler:
    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)  # LIF805: blocks

    def _on_term(self, signum, frame):
        time.sleep(0.1)


class LockingHandler:
    def __init__(self):
        self._lock = threading.Lock()
        self.drained = False

    def install(self):
        signal.signal(signal.SIGINT, self._on_int)  # LIF805: takes lock

    def _on_int(self, signum, frame):
        with self._lock:
            self.drained = True


class LoopTouchHandler:
    def __init__(self, loop):
        self._loop = loop

    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)  # LIF805: loop

    def _on_term(self, signum, frame):
        self._loop.call_soon_threadsafe(noop)
