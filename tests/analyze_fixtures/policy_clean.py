"""Clean twin: a registered policy obeying every POL7xx leg — pure
functions of the frozen views, ``for`` over finite snapshots, no
cross-call state, name referenced by a composition spec, and admit
returning a Decision on every path (time arrives through the injected
``view.now``, never a clock call).
"""


def register_policy(name):
    def wrap(cls):
        cls.name = name
        return cls

    return wrap


class Decision:
    def __init__(self, allowed, reason=""):
        self.allowed = allowed
        self.reason = reason


ALLOW = Decision(True)

#: The registered name's second quoted occurrence — the composition
#: spec POL704 leg 2 demands (an unreferenced name is unselectable).
COMPOSITIONS = (("window-clean",),)


@register_policy("window-clean")
class WindowCleanPolicy:
    def __init__(self, start_hour=8.0, end_hour=18.0):
        # Construction wires configuration; the decision methods below
        # never touch it mutably again.
        self._start = start_hour
        self._end = end_hour

    def admit(self, candidate, view):
        hour = (view.now % 86400.0) / 3600.0
        if candidate.disrupted:
            return ALLOW
        if hour < self._start or hour >= self._end:
            return Decision(False, "outside the maintenance window")
        return ALLOW

    def order(self, candidates):
        return sorted(
            candidates,
            key=lambda c: (not c.disrupted, c.score, c.trend, c.name),
        )

    def budget(self, view):
        available = view.candidates
        for cap in (view.max_unavailable, view.total):
            if available > cap:
                available = cap
        return available
