"""Seeded LCK111 violation: a blocking call three frames below a held
lock. ``tick`` holds the lock and calls ``_refresh``; the sleep lives in
``_backoff``, two more calls down — LCK102 (intraprocedural) cannot see
it, the call-graph propagation can.
"""

import threading
import time


class Poller:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._state: dict = {}

    def tick(self) -> None:
        with self._lock:
            self._state["latest"] = self._refresh()

    def _refresh(self) -> dict:
        return self._fetch()

    def _fetch(self) -> dict:
        self._backoff()
        return {}

    def _backoff(self) -> None:
        time.sleep(0.05)
