"""Seeded lock-discipline violations (analyzer fixture — analyzed as
text by tests/test_analyze.py, never imported)."""

import threading
import time


class BadCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._last = None
        self._worker = threading.Thread(target=self.bump)

    def bump(self):
        with self._lock:
            self._count += 1
            time.sleep(0.01)  # LCK102: blocking call under the lock

    def drain(self):
        with self._lock:
            self._worker.join()  # LCK102: thread join under the lock

    def reset(self):
        self._count = 0  # LCK101: guarded in bump, unguarded here
        with self._lock:
            self._last = "reset"

    def touch(self):
        self._last = "touched"  # LCK101: guarded in reset, unguarded here
