"""Seeded literal-key violations: upgrade-flow keys spelled inline
instead of flowing through the UpgradeKeys builders."""

STATE_LABEL = "acme.dev/widget-driver-upgrade-state"  # KEY301


def annotate(node):
    # KEY301: inline skip-label key.
    node.labels["acme.dev/widget-driver-upgrade.skip"] = "true"
    return node
