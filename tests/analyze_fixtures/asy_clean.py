"""Clean twin of asy_bad.py — sanctioned async shapes that must stay
silent under every ASY6xx (and every other) pass.

Parsed by the analyzer, never imported or executed.
"""

import asyncio
import queue
import threading


class CleanPump:
    def __init__(self):
        self._lock = threading.Lock()
        self._alock = asyncio.Lock()
        self._frames: asyncio.Queue = asyncio.Queue()
        self._out: queue.Queue = queue.Queue()
        self._loop = asyncio.new_event_loop()
        self._tasks = []

    async def pump(self):
        # Awaited asyncio primitives are suspensions, not blocks.
        await asyncio.sleep(0)
        frame = await self._frames.get()
        # Non-blocking handoff to the sync consumer side.
        self._out.put_nowait(frame)
        return frame

    async def guarded(self):
        # The asyncio lock across an await is the sanctioned form
        # (ASY603 tracks threading locks only).
        async with self._alock:
            await asyncio.sleep(0)

    async def try_lock(self):
        # Non-blocking acquire is loop-safe.
        if self._lock.acquire(blocking=False):
            self._lock.release()

    async def spawn(self):
        # Task handle retained: no GC-cancellation hazard.
        task = asyncio.create_task(self.pump())
        self._tasks.append(task)
        await task

    async def frames(self):
        # Clean async generator: awaits only.
        while True:
            item = await self._frames.get()
            if item is None:
                return
            yield item

    def _wake(self):
        """Runs on the wire loop (call_soon_threadsafe below) — the
        loop-affinity docstring convention; its body is non-blocking."""
        self._out.put_nowait(None)

    def kick(self):
        self._loop.call_soon_threadsafe(self._wake)

    async def drain(self):
        # Loop-side mutation of loop-bound state.
        self._tasks.clear()

    def push(self, task):
        # ASY604's own recommended fix: a lambda dispatched to the loop
        # mutates loop-bound state ON the loop — never a finding.
        self._loop.call_soon_threadsafe(lambda: self._tasks.append(task))


class SyncFacade:
    """The sync side of the boundary: blocking HERE is fine — these
    methods run on plain threads, never on the loop."""

    def __init__(self):
        self._loop = asyncio.new_event_loop()
        self._done: queue.Queue = queue.Queue()

    def call(self, coro):
        # run_coroutine_threadsafe boundary: the future is retained and
        # the PARKED side is the calling thread, not the loop.
        future = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return future.result(5)

    def next_frame(self):
        # Sync consumer of the loop's put_nowait handoff.
        return self._done.get(timeout=1)
