"""Clean twin: every broad handler logs, re-raises, propagates the
error as data, or is an import fallback."""

import logging

log = logging.getLogger("analyze-fixture")

try:  # import fallback gating an optional dep: structurally exempt
    from fixture_optional_dep import thing
except Exception:
    thing = None


def has_thing():
    return thing is not None


def reconcile(client):
    try:
        client.sync()
    except Exception:
        log.exception("sync failed")


def probe(client):
    try:
        client.sync()
    except Exception as e:
        return {"ok": False, "error": str(e)}  # error-as-data: exempt
    return {"ok": True}


def teardown(client):
    try:
        client.close()
    except ValueError:
        pass  # narrow handler: a decision about one failure mode
