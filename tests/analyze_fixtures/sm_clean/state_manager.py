"""Clean twin: apply_state covers every GadgetState member."""

from .consts import GadgetState


class GadgetMachine:
    def apply_state(self, state):
        self.process_idle_nodes(state, GadgetState.IDLE)
        self.process_spinning_nodes(state)
        self.process_jammed_nodes(state)
        self.process_checkpointing_nodes(state)
        self.process_quarantined_nodes(state)
        self.process_retired_nodes(state)
        self.process_lost_nodes(state)

    def process_idle_nodes(self, state, bucket):
        return state, bucket

    def process_spinning_nodes(self, state):
        return state

    def process_jammed_nodes(self, state):
        return state

    def process_checkpointing_nodes(self, state):
        return state

    def process_quarantined_nodes(self, state):
        return state

    def process_retired_nodes(self, state):
        return state

    def process_lost_nodes(self, state):
        return state
