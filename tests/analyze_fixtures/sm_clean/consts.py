"""Clean twin: a fully partitioned, fully handled miniature machine."""

from enum import Enum


class GadgetState(str, Enum):
    IDLE = "gadget-idle"
    SPINNING = "gadget-spinning"
    JAMMED = "gadget-jammed"
    RETIRED = "gadget-retired"
    LOST = "gadget-lost"


MANAGED_STATES = (
    GadgetState.IDLE,
    GadgetState.SPINNING,
    GadgetState.JAMMED,
)

MAINTENANCE_STATES = (
    GadgetState.RETIRED,
    GadgetState.LOST,
)
