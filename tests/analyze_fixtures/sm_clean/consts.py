"""Clean twin: a fully partitioned, fully handled miniature machine."""

from enum import Enum


class GadgetState(str, Enum):
    IDLE = "gadget-idle"
    SPINNING = "gadget-spinning"
    JAMMED = "gadget-jammed"
    RETIRED = "gadget-retired"
    LOST = "gadget-lost"
    CHECKPOINTING = "gadget-checkpointing"
    QUARANTINED = "gadget-quarantined"


MANAGED_STATES = (
    GadgetState.IDLE,
    GadgetState.SPINNING,
    GadgetState.JAMMED,
    GadgetState.CHECKPOINTING,
    GadgetState.QUARANTINED,
)

MAINTENANCE_STATES = (
    GadgetState.RETIRED,
    GadgetState.LOST,
)
