"""Seeded swallowed-exception violation: a broad handler in a reconcile
path that neither logs nor re-raises."""


def reconcile(client):
    try:
        client.sync()
    except Exception:
        pass  # EXC401: the outage becomes silence
    return True
