"""The lifecycle_bad.py twins done right — every legal shape the
LIF8xx pass must stay silent on (docs/daemon-lifecycle.md).

Covers: release via helper + alias one call below the shutdown method
(the propagation positive), multi-release kinds, acquire-inside-try,
ownership escape by return, ``with``-scoped resources, a daemon thread
legitimately unjoined, bounded joins through an alias, releases in
reverse dependency order, and an event-only signal handler.
"""

import signal
import threading
from typing import Optional


def lifecycle_resource(acquire="start", release="stop"):
    def deco(cls):
        return cls

    return deco


@lifecycle_resource(acquire="start", release="stop")
class Pump:
    def start(self):
        ...

    def stop(self):
        ...


@lifecycle_resource(acquire="__init__", release=("stop", "close"))
class Stream:
    def __init__(self, client):
        self.client = client

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def read(self):
        ...

    def stop(self):
        ...

    def close(self):
        ...


@lifecycle_resource(acquire="__init__", release="stop")
class WatchHub:
    def __init__(self, client):
        self.client = client

    def stop(self):
        ...


@lifecycle_resource(acquire="start", release="stop")
class Informer:
    def __init__(self, hub):
        self.hub = hub

    def start(self):
        ...

    def stop(self):
        ...


def prime(stream):
    ...


def pump_once(stream):
    ...


def poll(informer):
    ...


# -- owned resources, released through a helper and an alias ----------------


class CleanOwner:
    def __init__(self, client):
        self._client = client
        self._pump = Pump()
        self._stream: Optional[Stream] = None

    def start(self):
        self._pump.start()
        self._stream = Stream(self._client)

    def stop(self):
        self._drain()

    def _drain(self):
        pump = self._pump
        pump.stop()
        stream = self._stream
        if stream is not None:
            stream.close()
        self._stream = None


# -- frame-local resources, exception-safe ----------------------------------


def drains_in_finally(client):
    stream = Stream(client)
    try:
        pump_once(stream)
    finally:
        stream.close()


def acquired_inside_try(client):
    stream = None
    try:
        stream = Stream(client)
        prime(stream)
        pump_once(stream)
    finally:
        if stream is not None:
            stream.stop()


def returns_ownership(client):
    stream = Stream(client)
    prime(stream)
    return stream


def with_scoped(client):
    stream = Stream(client)
    with stream:
        pump_once(stream)


# -- threads: bounded joins, daemons exempt ----------------------------------


class CleanLoop:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, name="clean-loop")
        self._thread.start()

    def stop(self):
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
        self._thread = None

    def _run(self):
        self._stop.wait(1.0)


class DaemonHeartbeat:
    def __init__(self):
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._beat, name="heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self):
        ...

    def _beat(self):
        ...


def joined_locally(work):
    worker = threading.Thread(target=work)
    worker.start()
    worker.join(timeout=10.0)


# -- releases in reverse dependency order ------------------------------------


def stop_order_correct(client):
    hub = informer = None
    try:
        hub = WatchHub(client)
        informer = Informer(hub)
        informer.start()
        poll(informer)
    finally:
        informer.stop()
        hub.stop()


# -- signal handler: event-only ----------------------------------------------


class CleanDaemon:
    def __init__(self):
        self._stop_event = threading.Event()

    def install(self):
        signal.signal(signal.SIGTERM, self._on_signal)
        signal.signal(signal.SIGINT, self._on_signal)

    def _on_signal(self, signum, frame):
        self._stop_event.set()
