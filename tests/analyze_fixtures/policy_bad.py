"""Seeded POL7xx violations: registered policies breaking every leg of
the plugin discipline (docs/policy-plugins.md).

* ``MutatorPolicy`` — admit reaches a cluster mutation one call below
  (only transitive propagation sees it), order reads the wall clock,
  budget rolls an RNG, and admit returns a truthy stand-in instead of
  a Decision.
* ``StashPolicy`` — admit stashes cross-call state on ``self`` and in
  a module-level container, order declares ``global`` and spins a
  ``while`` loop, budget recurses through a helper; admit also has a
  bare return and can fall off the end.
* ``ShadowPolicy`` — implements the full protocol but is never
  registered (dead policy).
* ``GhostPolicy`` — registered under a name quoted nowhere else (no
  spec or composition can ever select it).
"""

import random
import time


def register_policy(name):
    def wrap(cls):
        cls.name = name
        return cls

    return wrap


class Client:
    def update_status(self, obj):
        ...


#: Second quoted occurrences for the names whose POL704 leg-2 check
#: should stay silent (the seeded leg-2 violation is GhostPolicy's).
COMPOSITIONS = (("mutator-policy", "stash-policy"),)

_SEEN: dict = {}
_TICKS = 0


@register_policy("mutator-policy")
class MutatorPolicy:
    def __init__(self, client):
        self.client = client

    def admit(self, candidate, view):
        self._push(candidate)  # POL701: mutation one call below
        return True  # POL705: truthy stand-in, not a Decision

    def _push(self, candidate):
        self.client.update_status(candidate)  # POL701: direct mutation

    def order(self, candidates):
        now = time.time()  # POL701: clock read
        return sorted(candidates, key=lambda c: (c.score, now))

    def budget(self, view):
        return random.random()  # POL701: RNG call


@register_policy("stash-policy")
class StashPolicy:
    def admit(self, candidate, view):
        self._last = candidate.name  # POL703: self-stash
        self._cache[candidate.name] = view.now  # POL703: self container
        _SEEN[candidate.name] = view.now  # POL703: module-level store
        if candidate.disrupted:
            return  # POL705: bare return
        # POL705: falls off the end (implicit None)

    def order(self, candidates):
        global _TICKS  # POL703: global declaration
        _TICKS += 1
        out = []
        i = 0
        while i < len(candidates):  # POL702: while loop
            out.append(candidates[i])
            i += 1
        return out

    def budget(self, view):
        return self._spin(view, 0)  # POL702: recursion reachable

    def _spin(self, view, depth):
        if depth > 3:
            return view
        return self._spin(view, depth + 1)  # POL702: recursion


class ShadowPolicy:  # POL704: full protocol, never registered
    def admit(self, candidate, view):
        return None

    def order(self, candidates):
        return list(candidates)

    def budget(self, view):
        return view


@register_policy("ghost-policy")  # POL704: name referenced nowhere else
class GhostPolicy:
    def admit(self, candidate, view):
        return ALLOW

    def order(self, candidates):
        return list(candidates)

    def budget(self, view):
        return view


ALLOW = object()
