"""Clean twin of dryrun_bad.py: every mutation on a tainted path carries
the dry-run flag — forwarded as a kwarg, hard-wired ``dry_run=True`` in
a preview branch, killed by an early return, or smuggled through a
taint-derived query dict (the REST-layer idiom).
"""


class Client:
    def patch(self, kind, name, patch=None, dry_run=False):
        ...

    def evict(self, pod, dry_run=False):
        ...

    def delete(self, kind, name, dry_run=False):
        ...

    def _request(self, verb, path, query=None):
        ...


class NodeOps:
    def __init__(self, client: Client) -> None:
        self._client = client

    def cordon(self, node: str, dry_run: bool = False) -> None:
        self._client.patch(
            "Node", node, patch={"spec": {"unschedulable": True}},
            dry_run=dry_run,
        )

    def purge(self, node: str, pod: str, dry_run: bool = False) -> int:
        if dry_run:
            self._client.evict(pod, dry_run=True)
            return 0
        self._client.evict(pod)
        return 1

    def maintenance(self, node: str, dry_run: bool = False) -> None:
        if dry_run:
            return
        self._wipe(node)

    def _wipe(self, node: str) -> None:
        self._client.delete("Node", node)

    def raw_write(self, path: str, body, dry_run: bool = False):
        # The REST-layer shape: the flag rides in a query dict built
        # under the taint, not in a dry_run kwarg.
        query: dict = {}
        if dry_run:
            query["dryRun"] = "All"
        return self._client._request("POST", path, query=query)
