"""Clean twin of lock_bad.py: every shared-state mutation is guarded,
blocking work happens outside the lock, and both caller-holds-lock
conventions (``*_locked`` name, docstring phrase) are exercised."""

import threading
import time


class CleanCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._count = 0
        self._last = None

    def wait_for_reset(self):
        with self._cond:
            # Condition.wait releases the lock while blocked — the
            # sanctioned way to block under it.
            self._cond.wait(timeout=1.0)
            return ", ".join(["a", "b"])  # sep.join: string building

    def bump(self):
        time.sleep(0.01)  # blocking, but before taking the lock
        with self._lock:
            self._count += 1

    def reset(self):
        with self._lock:
            self._reset_locked()
            self._touch()

    def _reset_locked(self):
        self._count = 0

    def _touch(self):
        """Caller holds the lock."""
        self._last = "touched"
