"""Seeded DRY501 violations: cluster mutations reachable on dry_run
paths without the flag forwarded.

* ``cordon`` — runs on both paths (no early return) and PATCHes without
  forwarding ``dry_run``: a dry-run cordon really mutates the node.
* ``purge`` — evicts INSIDE the ``if dry_run:`` branch without the
  flag: the preview path performs the real eviction.
* ``maintenance`` — the mutation is one call below, in a helper with no
  dry_run parameter: only transitive propagation sees it.
"""


class Client:
    def patch(self, kind, name, patch=None, dry_run=False):
        ...

    def evict(self, pod, dry_run=False):
        ...

    def delete(self, kind, name, dry_run=False):
        ...


class NodeOps:
    def __init__(self, client: Client) -> None:
        self._client = client

    def cordon(self, node: str, dry_run: bool = False) -> None:
        self._client.patch(
            "Node", node, patch={"spec": {"unschedulable": True}}
        )

    def purge(self, node: str, pod: str, dry_run: bool = False) -> int:
        if dry_run:
            self._client.evict(pod)
            return 0
        self._client.evict(pod)
        return 1

    def maintenance(self, node: str, dry_run: bool = False) -> None:
        self._wipe(node)

    def _wipe(self, node: str) -> None:
        self._client.delete("Node", node)
