"""Seeded LCK110 violation: an AB/BA lock-order inversion between two
classes, where each half of the cycle crosses a call boundary — invisible
to any per-function analysis.

``Cache.refresh`` holds ``Cache._lock`` and calls into the queue, which
takes ``Queue._lock``; ``Queue.drop`` holds ``Queue._lock`` and calls
back into the cache, which takes ``Cache._lock``. Two threads running
``refresh`` and ``drop`` concurrently deadlock.
"""

import threading


class Cache:
    def __init__(self, queue: "Queue") -> None:
        self._lock = threading.Lock()
        self.queue = queue

    def refresh(self) -> None:
        with self._lock:
            self.queue.requeue_all()

    def invalidate(self, key: str) -> None:
        with self._lock:
            del key


class Queue:
    def __init__(self, cache: Cache) -> None:
        self._lock = threading.Lock()
        self.cache = cache

    def requeue_all(self) -> None:
        with self._lock:
            pass

    def drop(self, key: str) -> None:
        with self._lock:
            self.cache.invalidate(key)
