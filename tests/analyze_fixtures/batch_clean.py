"""Clean twin of batch_bad.py: the provider's split critical section.
The keyed mutex covers only non-blocking work — the optimistic
in-memory apply before the flush and the bookkeeping rejoin after it —
while the batch flush (the wire round trip) runs outside any lock.
LCK111 must stay silent.

Analyzer fixture — analyzed as text by tests/test_analyze.py, never
imported.
"""

import threading
import time
from contextlib import contextmanager


class KeyedMutex:
    def __init__(self):
        self._guard = threading.Lock()
        self._locks = {}

    @contextmanager
    def locked(self, key):
        with self._guard:
            lock = self._locks.setdefault(key, threading.Lock())
        lock.acquire()
        try:
            yield
        finally:
            lock.release()


class Batcher:
    def __init__(self):
        self._pending = []

    def stage(self, name, patch):
        self._pending.append((name, patch))
        return self._flush()

    def _flush(self):
        batch, self._pending = self._pending, []
        time.sleep(0.001)  # the pipelined wire round trip
        return len(batch)


class CleanBatchedWriter:
    def __init__(self):
        self._mutex = KeyedMutex()
        self._batcher = Batcher()
        self._values = {}

    def write(self, name, patch):
        with self._mutex.locked(name):
            self._apply(name, patch)  # optimistic, in-memory only
        # The flush happens OUTSIDE the keyed mutex: same-node writers
        # observe the optimistic value instead of stalling on the wire.
        result = self._batcher.stage(name, patch)
        with self._mutex.locked(name):
            self._rejoin(name, result)  # non-blocking bookkeeping
        return result

    def _apply(self, name, patch):
        self._values[name] = patch

    def _rejoin(self, name, result):
        self._values[name] = (self._values.get(name), result)
