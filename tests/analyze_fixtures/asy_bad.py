"""Seeded ASY6xx violations — the event-loop discipline bad twin.

Every shape here must be CAUGHT (tests/test_analyze.py pins code and
count); the clean twin (asy_clean.py) holds the sanctioned forms. This
file is parsed by the analyzer, never imported or executed.
"""

import asyncio
import queue
import threading
import time


def fetch_sync(url):
    """The blocking leaf a coroutine must never reach, two frames up."""
    time.sleep(0.1)
    return url


def traced(fn):
    return fn


class WirePump:
    def __init__(self):
        self._lock = threading.Lock()
        self._frames: queue.Queue = queue.Queue()

    async def pump(self):
        # ASY601: direct blocking call in a coroutine.
        time.sleep(0.5)
        # ASY601: sync queue put — blocks the loop when the queue fills.
        self._frames.put("frame")

    async def refresh(self):
        # ASY601 (transitive): the sync helper sleeps one frame down.
        return fetch_sync("/nodes")

    async def roll(self):
        # ASY602: coroutine called but never awaited (object discarded).
        self.pump()
        # ASY602: fire-and-forget task — the handle is dropped.
        asyncio.create_task(self.refresh())

    async def guarded(self):
        with self._lock:
            # ASY603: threading lock held across the suspension point.
            await asyncio.sleep(0)

    async def stream(self):
        with self._lock:
            # ASY603 via the implicit awaits of `async with`.
            async with self._session():
                pass

    def _session(self):
        return None

    async def frames(self):
        # Async GENERATORS are loop code too: ASY601 applies inside.
        while True:
            time.sleep(0.01)
            yield "frame"


class Decorated:
    @traced
    async def slow(self):
        # ASY601: the decorator must not hide the async def.
        time.sleep(0.2)


class Scheduler:
    def __init__(self):
        self._loop = asyncio.new_event_loop()

    def kick(self):
        def wake():
            # ASY601: `wake` runs ON the loop (call_soon_threadsafe
            # dispatch), no matter that `kick` is a thread method.
            time.sleep(0.1)

        self._loop.call_soon_threadsafe(wake)


class Pool:
    def __init__(self):
        self._idle = []

    async def acquire(self):
        return self._idle.pop()

    def release(self, conn):
        # ASY604: the idle pool is loop-bound (acquire mutates it on
        # the loop) but this plain thread method mutates it directly.
        self._idle.append(conn)
