"""Clean twin of chain_bad.py: the same call chain, but the blocking
backoff runs after the lock is released — the lock guards only the
in-memory swap. The ``*_locked`` helper is called with the lock held,
as its name requires, and does no blocking work.
"""

import threading
import time


class Poller:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._state: dict = {}

    def tick(self) -> None:
        fresh = self._refresh()
        with self._lock:
            self._swap_locked(fresh)
        self._backoff()

    def _swap_locked(self, fresh: dict) -> None:
        self._state["latest"] = fresh

    def _refresh(self) -> dict:
        return self._fetch()

    def _fetch(self) -> dict:
        return {}

    def _backoff(self) -> None:
        time.sleep(0.05)
