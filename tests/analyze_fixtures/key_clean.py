"""Clean twin: keys come from a builder object; other-namespace keys
(slice topology) and a targeted noqa suppression stay silent."""

SLICE_LABEL = "acme.dev/slice-id"  # not an upgrade key: exempt


def annotate(node, keys):
    node.labels[keys.state_label] = "true"
    legacy = "acme.dev/widget-driver-upgrade-state"  # noqa: KEY301
    return node, legacy
