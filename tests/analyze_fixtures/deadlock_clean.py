"""Clean twin of deadlock_bad.py: the same two classes and the same
cross-class calls, but every path acquires the locks in one global order
(Cache before Queue) — the acquisition graph is a DAG.
"""

import threading


class Cache:
    def __init__(self, queue: "Queue") -> None:
        self._lock = threading.Lock()
        self.queue = queue

    def refresh(self) -> None:
        with self._lock:
            self.queue.requeue_all()

    def invalidate(self, key: str) -> None:
        with self._lock:
            del key

    def drop(self, key: str) -> None:
        # The inversion from the bad twin, restructured: take the cache
        # lock FIRST, then call down into the queue — same order as
        # refresh(), so no cycle.
        with self._lock:
            self.queue.requeue_all()


class Queue:
    def __init__(self, cache: Cache) -> None:
        self._lock = threading.Lock()
        self.cache = cache

    def requeue_all(self) -> None:
        with self._lock:
            pass
