"""Seeded state-machine violations: incomplete apply_state coverage."""


class WidgetMachine:
    def apply_state(self, state):
        # STM203: JAMMED / RETIRED / LOST / CHECKPOINTING have no
        # handler here (CHECKPOINTING is the deliberately-missing arc).
        self.process_idle_nodes(state)
        self.process_spinning_nodes(state)
        self.process_melted_nodes(state)  # STM204: maps to no state

    def process_idle_nodes(self, state):
        return state

    def process_spinning_nodes(self, state):
        return "widget-jammed"  # STM205: state value spelled inline

    def process_melted_nodes(self, state):
        return state
